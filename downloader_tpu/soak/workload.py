"""Soak profiles and the deterministic mixed-workload schedule.

The workload is the union of every traffic shape the repo has built a
subsystem for, interleaved so they contend the way production traffic
does: cache-hot fan-in (many jobs, one content key — the fleet lease
singleflight's regime), multi-origin racing (mirrors), segment-manifest
ingest (the streaming pipeline's live feed), multi-tenant BULK pressure
with deadlines (the overload layer's regime), and plain per-job HTTP
fetches.  The schedule is a pure function of the profile and the
injected origin endpoints — no randomness, so a failing soak replays
exactly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from ..platform.config import cfg_get
# ONE priority-class tuple repo-wide (the percentile discipline): the
# soak's p99 guards and the live SLO tracker must agree on which
# classes exist — re-exported here under the soak's historical name
from ..control.slo import PRIORITY_CLASSES

# job kinds (the ``kind`` of each JobSpec; job ids carry them too)
HOT = "hot"            # cache-hot fan-in: every hot job shares one URI
RACING = "racing"      # primary + mirror(s): the racing RangeScheduler
MANIFEST = "manifest"  # HLS-style segment playlist ingest
BULK = "bulk"          # BULK priority, deadline-carrying batch tenant
PLAIN = "plain"        # one ordinary HTTP fetch per job
#: the post-workload attribution probe: fresh-content single-stream
#: jobs run SEQUENTIALLY on a quiescent fleet, where stage wall is
#: attributable — the set the hop-ledger reconciliation guard judges
#: (the mixed phase's wall is contention-dominated by design: dozens
#: of concurrent jobs inflate each other's wall clock, which no
#: per-job ledger can or should account for)
PROBE = "probe"

__all__ = ["PRIORITY_CLASSES", "SoakProfile", "WorkloadOrigin",
           "SoakEndpoints", "JobSpec", "SoakWorkload", "download_msg",
           "HOT", "RACING", "MANIFEST", "BULK", "PLAIN", "PROBE"]


@dataclass(frozen=True)
class SoakProfile:
    """One soak run's shape: scale, chaos cadence, and SLO bounds.

    ``smoke`` must stay tier-1 safe (≤ ~60 s wall, single host); the
    ``full`` profile is the slow-marked capacity run.  ``from_config``
    lets operators resize either via the ``soak.*`` knobs without
    editing code (docs/OPERATIONS.md "Capacity & SLOs").
    """

    jobs: int = 60
    workers: int = 2
    #: seconds between SIGKILLs of a round-robin worker (0 = no chaos)
    kill_interval: float = 2.5
    #: SIGKILLs to deliver over the run
    kills: int = 1
    #: SIGSTOP/SIGCONT stall chaos (the degraded profile's regime): a
    #: worker frozen past the lease TTL is a *stalled* leader — alive,
    #: lease expired, resumed mid-takeover — the split-brain shape the
    #: fencing layer exists for.  0 stalls = off.
    stalls: int = 0
    stall_interval: float = 3.0
    stall_duration: float = 0.0
    #: fleet content-lease TTL written into worker configs (the
    #: degraded profile shrinks it so a stall overruns it quickly)
    lease_ttl: float = 8.0
    #: extra ``breakers`` config section for the workers (the degraded
    #: profile arms the store slow-call policy here)
    breakers: Dict[str, dict] = field(default_factory=dict)
    #: extra ``slo`` config section for the workers (tests tighten
    #: objectives so a browned-out worker's burn rate visibly rises)
    slo: Dict[str, dict] = field(default_factory=dict)
    #: ``retry`` config overrides merged over the rig defaults (the
    #: disk profile paces redelivery so a full-disk window can't burn
    #: a job's poison budget before the window closes)
    retry: Dict[str, dict] = field(default_factory=dict)
    #: extra ``scrub`` config section for the workers (the disk
    #: profile shrinks the pass interval so repairs land in-run)
    scrub: Dict[str, object] = field(default_factory=dict)
    #: shared `.fleet-cache/` entry max age written into worker
    #: configs (the disk profile stretches it so the scrubber's
    #: repair source outlives the bit-rot phase)
    shared_max_age: float = 30.0
    #: cache-entry files to bit-rot AFTER the workload drains (the
    #: scrubber must repair every one from the shared tier) — 0 = off
    corrupt_files: int = 0
    #: max seconds to wait for the scrubber to account for the seeds
    scrub_wall: float = 25.0
    #: wall-clock offset (seconds after worker 0 installs its fault
    #: plan) at which the profile's brownout window opens — kept in
    #: sync with ``fault_plan`` so the rig can anchor the
    #: ``brownout_shed_ms`` measurement
    brownout_start_s: float = 0.0
    #: sampler cadence
    sample_interval: float = 0.5
    #: hard wall for the workload phase (publish -> all jobs resolved)
    max_wall: float = 150.0
    #: per-worker concurrency / prefetch shape
    max_concurrent_jobs: int = 3
    scheduler_backlog: int = 6
    #: journal compaction bound the growth guard is armed against
    journal_max_bytes: int = 256 << 10
    #: fleet GC cadence + telemetry digest TTL (seconds)
    gc_interval: float = 1.25
    telemetry_ttl: float = 3.0
    #: shared `.fleet-cache/` eviction budget (bytes)
    shared_max_bytes: int = 8 << 20
    #: BULK deadline (seconds from receipt; generous — the smoke guards
    #: completion, the deadline machinery rides along armed)
    bulk_ttl: float = 120.0
    #: workload mix (fractions of ``jobs``; manifest is a fixed count —
    #: each manifest job is a multi-segment pipeline, not one fetch)
    hot_fraction: float = 0.25
    racing_fraction: float = 0.15
    bulk_fraction: float = 0.25
    manifest_jobs: int = 2
    #: sequential quiescent-fleet jobs for the hop reconciliation guard
    probe_jobs: int = 3
    #: open-loop arrival rate, jobs/s (0 = publish the whole schedule
    #: up front).  Long profiles MUST pace: with a burst publish, p99
    #: time-to-staged measures queue-drain time (jobs / throughput),
    #: not service under load — the guard would just re-derive the
    #: schedule length
    publish_rate: float = 0.0
    #: transient store faults injected on worker 0's first generation
    #: (exercises the retry/poison counter across the kill chaos)
    fault_plan: str = (
        '[{"seam": "store.put", "kind": "error", "count": 2,'
        ' "after": 4, "fault": "transient"}]'
    )
    # -- SLO bounds -----------------------------------------------------
    #: p99 time-to-staged ceiling per priority class, seconds — sized
    #: for the worst legitimate stall the chaos can cause (kill ->
    #: restart -> redelivery, or a dead lease-holder's takeover at
    #: lease_ttl * 1.25) plus CI-host margin
    p99_ceiling: Dict[str, float] = field(default_factory=lambda: {
        "HIGH": 25.0, "NORMAL": 35.0, "BULK": 60.0,
    })
    #: journal file peak across the run (compaction must hold the line)
    journal_peak_limit: int = 1 << 20
    #: RSS growth ceiling, MB per 1000 completed jobs (max over workers)
    rss_slope_limit_mb_per_kjob: float = 2000.0
    #: `.fleet-cache/` peak bytes (GC budget + one in-flight entry)
    shared_cache_limit: int = 12 << 20
    #: coordination docs at drain: telemetry left unswept (fraction of
    #: jobs) and worker-doc slack over the configured worker count
    telemetry_final_fraction: float = 0.5
    #: |1 - sum(hop seconds)/sum(stage seconds)| tolerance over the
    #: reconciliation set (DONE jobs that fetched their own bytes)
    hop_reconcile_tolerance: float = 0.10

    @classmethod
    def smoke(cls, **overrides) -> "SoakProfile":
        """The tier-1-safe profile (``make soak-smoke``)."""
        return cls(**overrides)

    @classmethod
    def full(cls, **overrides) -> "SoakProfile":
        """The slow-marked capacity profile (``make soak``)."""
        params = dict(
            jobs=300, workers=3, kill_interval=10.0, kills=3,
            max_wall=600.0, manifest_jobs=6, publish_rate=7.0,
            rss_slope_limit_mb_per_kjob=400.0,
        )
        params.update(overrides)
        return cls(**params)

    @classmethod
    def degraded(cls, **overrides) -> "SoakProfile":
        """The degraded-world profile (``make degraded`` / bench v19
        ``--degraded``): no SIGKILLs — instead a SIGSTOP/SIGCONT stall
        that overruns the (shortened) lease TTL, a store brownout
        window on worker 0, and the slow-call breaker policy armed.
        Guards the brownout sheds (breaker opens on ``slow``) and that
        split-brain staged no stale byte."""
        params = dict(
            jobs=18, workers=2, kill_interval=0.0, kills=0,
            stalls=1, stall_interval=2.0, stall_duration=4.0,
            lease_ttl=2.0,
            max_wall=110.0, publish_rate=2.5,
            # hot fan-in dominates so the stall lands on a lease
            # holder; racing/manifest lanes sit this profile out
            hot_fraction=0.5, racing_fraction=0.0, manifest_jobs=0,
            bulk_fraction=0.25,
            # the reconciliation probe measures a quiescent fleet —
            # out of scope for a deliberately-degraded one
            probe_jobs=0,
            # worker 0: latency-only store brownout (zero errors) —
            # the slow-call policy, not the failure counter, must trip.
            # The window opens almost immediately and spans the first
            # workload wave, so worker 0's store calls are reliably
            # inside it; it CLOSES so the post-window half-open probe
            # restores full-speed drain
            fault_plan=(
                '[{"seam": "store.*", "kind": "brownout",'
                ' "start_s": 1.0, "window_s": 6.0,'
                ' "latency_ms": 250, "jitter_ms": 100}]'
            ),
            brownout_start_s=1.0,
            breakers={"store": {"slow_threshold_ms": 120,
                                "slow_ratio": 0.5, "slow_window": 8,
                                "slow_min_calls": 4, "reset": 1.5}},
            # stall + brownout both inflate the tail legitimately
            p99_ceiling={"HIGH": 35.0, "NORMAL": 45.0, "BULK": 80.0},
            # breaker-shed jobs legitimately settle on BOTH workers
            # (park-then-nack on the browned-out one, completion on the
            # peer — digests key per worker+job), and the stall defers
            # the elected GC sweeper, so the final telemetry census
            # runs up to ~2x jobs before aging out; the bound still
            # caps growth, just sized for this profile's chaos
            telemetry_final_fraction=2.5,
        )
        params.update(overrides)
        return cls(**params)

    @classmethod
    def disk(cls, **overrides) -> "SoakProfile":
        """The storage-fault profile (``make bench-disk`` / bench v25
        ``--disk``): no kill/stall chaos — instead worker 0's landing
        writes hit a windowed ENOSPC (the disk is full for a few
        seconds, then an operator frees space), and AFTER the workload
        drains the rig flips one byte in several cache-entry files
        whose keys have healthy shared-tier replicas.  Guards: every
        job settles despite the full disk (zero FAILED/poisoned),
        every staged byte is exact (zero corrupt bytes served), and
        the scrubber's repair count equals the seeded corruption
        count — measured, not projected."""
        params = dict(
            jobs=18, workers=2, kill_interval=0.0, kills=0,
            max_wall=110.0, publish_rate=2.5,
            # hot fan-in dominates so cache entries AND their shared-
            # tier replicas exist for the bit-rot phase to corrupt and
            # the scrubber to repair from
            hot_fraction=0.5, racing_fraction=0.0, manifest_jobs=0,
            bulk_fraction=0.25, probe_jobs=0,
            # worker 0: the disk is full from t=1s for 6 s of landing
            # writes, then space "frees up" (transient classification:
            # redeliveries after the window land clean)
            fault_plan=(
                '[{"seam": "disk.write", "kind": "disk",'
                ' "disk_mode": "enospc", "fault": "transient",'
                ' "start_s": 1.0, "window_s": 6.0}]'
            ),
            brownout_start_s=1.0,
            # pace redelivery at operator timescales: a full disk does
            # not heal in 50 ms, and fast-looping redeliveries could
            # burn the 5-failure poison budget inside the window
            retry={"redelivery": {"base": 0.5, "cap": 2.5}},
            corrupt_files=3,
            scrub={"interval": 1.0, "rate_mb_s": 512},
            # the repair source must outlive the bit-rot phase
            shared_max_age=300.0,
            # the full-disk window inflates worker 0's tail
            # legitimately (paced redeliveries ride it out)
            p99_ceiling={"HIGH": 35.0, "NORMAL": 45.0, "BULK": 80.0},
            # breaker-shed jobs settle on both workers (see degraded)
            telemetry_final_fraction=2.5,
        )
        params.update(overrides)
        return cls(**params)

    @classmethod
    def from_config(cls, config, base: "Optional[SoakProfile]" = None,
                    **overrides) -> "SoakProfile":
        """Resize ``base`` (default: smoke) from the ``soak.*`` knobs."""
        base = base or cls()
        params = dict(
            jobs=int(cfg_get(config, "soak.jobs", base.jobs)),
            workers=int(cfg_get(config, "soak.workers", base.workers)),
            kill_interval=float(cfg_get(
                config, "soak.kill_interval", base.kill_interval)),
            stalls=int(cfg_get(config, "soak.stalls", base.stalls)),
            stall_interval=float(cfg_get(
                config, "soak.stall_interval", base.stall_interval)),
            stall_duration=float(cfg_get(
                config, "soak.stall_duration", base.stall_duration)),
        )
        params.update(overrides)
        from dataclasses import replace

        return replace(base, **params)


@dataclass(frozen=True)
class WorkloadOrigin:
    """One submittable origin: a URI plus the staged set it must yield.

    ``files`` is the expected staged artifact set ``(basename, bytes)``
    — the byte-identity oracle the rig verifies a sample of jobs
    against (every byte that reaches the staging store must match what
    the origin served, kills or not).
    """

    uri: str
    files: Tuple[Tuple[str, bytes], ...]
    mirrors: Tuple[str, ...] = ()
    source_kind: str = "AUTO"


@dataclass(frozen=True)
class SoakEndpoints:
    """The origin fleet the caller stood up, one pool per job kind."""

    hot: Tuple[WorkloadOrigin, ...]
    plain: Tuple[WorkloadOrigin, ...]
    racing: Tuple[WorkloadOrigin, ...] = ()
    manifest: Tuple[WorkloadOrigin, ...] = ()
    #: fresh-content, transfer-dominated origins (rate-limited so the
    #: splice dwarfs the coordination ceremony) — one per probe job
    probe: Tuple[WorkloadOrigin, ...] = ()


@dataclass(frozen=True)
class JobSpec:
    """One scheduled job: identity, class, and its origin contract."""

    job_id: str
    kind: str
    origin: WorkloadOrigin
    priority: str = "NORMAL"
    tenant: str = ""
    ttl_seconds: float = 0.0


def download_msg(spec: JobSpec) -> bytes:
    """Encode one spec as the wire ``Download`` message."""
    from .. import schemas

    msg = schemas.Download(media=schemas.Media(
        id=spec.job_id,
        creator_id=f"soak-{spec.kind}",
        name=f"soak {spec.kind} {spec.job_id}",
        type=schemas.MediaType.Value("MOVIE"),
        source=schemas.SourceType.Value("HTTP"),
        source_uri=spec.origin.uri,
    ))
    msg.priority = schemas.JobPriority.Value(spec.priority)
    if spec.tenant:
        msg.tenant = spec.tenant
    if spec.ttl_seconds:
        msg.ttl_seconds = spec.ttl_seconds
    if spec.origin.mirrors:
        msg.mirrors.extend(spec.origin.mirrors)
    if spec.origin.source_kind != "AUTO":
        msg.source_kind = schemas.SourceKind.Value(spec.origin.source_kind)
    return schemas.encode(msg)


class SoakWorkload:
    """The deterministic job schedule for one profile + endpoint set."""

    def __init__(self, profile: SoakProfile, endpoints: SoakEndpoints):
        self.profile = profile
        self.endpoints = endpoints
        self.specs: List[JobSpec] = self._build()
        # published one at a time AFTER the mixed phase drains (the
        # rig's attribution-probe step), not part of the mixed schedule
        self.probe_specs: List[JobSpec] = [
            JobSpec(f"soak-probe-{i:04d}", PROBE,
                    self.endpoints.probe[i % len(self.endpoints.probe)])
            for i in range(profile.probe_jobs
                           if self.endpoints.probe else 0)
        ]

    def _build(self) -> List[JobSpec]:
        profile = self.profile
        hot_n = max(int(profile.jobs * profile.hot_fraction), 0)
        racing_n = max(int(profile.jobs * profile.racing_fraction), 0)
        manifest_n = min(profile.manifest_jobs, profile.jobs)
        bulk_n = max(int(profile.jobs * profile.bulk_fraction), 0)
        if not self.endpoints.racing:
            racing_n = 0
        if not self.endpoints.manifest:
            manifest_n = 0
        plain_n = max(
            profile.jobs - hot_n - racing_n - manifest_n - bulk_n, 0)

        def pool(origins, index):
            return origins[index % len(origins)]

        lanes: List[List[JobSpec]] = []
        # hot fan-in: one shared content key, vip tenant, HIGH/NORMAL
        # alternating so the p99 guard sees fan-in in both classes
        lanes.append([
            JobSpec(f"soak-hot-{i:04d}", HOT,
                    pool(self.endpoints.hot, 0),
                    priority="HIGH" if i % 2 == 0 else "NORMAL",
                    tenant="vip" if i % 2 == 0 else "")
            for i in range(hot_n)
        ])
        lanes.append([
            JobSpec(f"soak-racing-{i:04d}", RACING,
                    pool(self.endpoints.racing, i))
            for i in range(racing_n)
        ])
        lanes.append([
            JobSpec(f"soak-manifest-{i:04d}", MANIFEST,
                    pool(self.endpoints.manifest, i))
            for i in range(manifest_n)
        ])
        lanes.append([
            JobSpec(f"soak-bulk-{i:04d}", BULK,
                    pool(self.endpoints.plain, i),
                    priority="BULK", tenant="batch",
                    ttl_seconds=profile.bulk_ttl)
            for i in range(bulk_n)
        ])
        lanes.append([
            JobSpec(f"soak-plain-{i:04d}", PLAIN,
                    pool(self.endpoints.plain, i + 3))
            for i in range(plain_n)
        ])
        # round-robin interleave: every kind is in flight from the
        # start, so the chaos window always lands on mixed traffic
        out: List[JobSpec] = []
        cursor = 0
        while any(lanes):
            lane = lanes[cursor % len(lanes)]
            if lane:
                out.append(lane.pop(0))
            lanes = [ln for ln in lanes if ln]
            cursor += 1
        return out

    def by_kind(self, kind: str) -> List[JobSpec]:
        return [spec for spec in self.specs if spec.kind == kind]

    def priority_class(self, spec: JobSpec) -> str:
        return spec.priority if spec.priority in PRIORITY_CLASSES \
            else "NORMAL"
