"""SLO guard math and the soak verdict report.

Everything here is pure: the rig hands over job outcomes, the growth
sampler's time series, and the end-of-run world census; this module
turns them into named guards with hard bounds.  A guard failing names
the guilty subsystem (journal compaction, fleet GC, lease plane,
scheduler fairness, hop ledger) — the soak's whole point is that a
capacity regression arrives with attribution, not as a vibe.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# THE percentile: one implementation shared with the in-process SLO
# plane (control/slo.py), so `make soak` and the production /readyz
# block report the same statistic by construction (re-exported here —
# the soak's public name since PR 13)
from ..control.slo import percentile
from .workload import PRIORITY_CLASSES


def fit_slope(xs: Sequence[float], ys: Sequence[float]) -> float:
    """Least-squares slope of ``ys`` over ``xs`` (0.0 when degenerate)."""
    n = len(xs)
    if n < 2 or n != len(ys):
        return 0.0
    mean_x = sum(xs) / n
    mean_y = sum(ys) / n
    var = sum((x - mean_x) ** 2 for x in xs)
    if var <= 0.0:
        return 0.0
    cov = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, ys))
    return cov / var


@dataclass
class Guard:
    """One SLO verdict: a measured value against a hard bound."""

    name: str
    value: float
    bound: float
    ok: bool
    #: which way the bound cuts ("<=" for ceilings, "==" for exacts)
    op: str = "<="
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "value": round(self.value, 4),
            "bound": self.bound,
            "op": self.op,
            "ok": self.ok,
            "detail": self.detail,
        }


@dataclass
class SoakReport:
    """Every guard plus the headline stats one soak run produced."""

    guards: List[Guard] = field(default_factory=list)
    stats: Dict[str, float] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return all(guard.ok for guard in self.guards)

    def failures(self) -> List[Guard]:
        return [guard for guard in self.guards if not guard.ok]

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "guards": [guard.to_dict() for guard in self.guards],
            "stats": self.stats,
        }

    def summary(self) -> str:
        # the mixed-phase attribution ratio rides the narrative even
        # though it stays unguarded by design (contention-dominated
        # wall): drift in WHERE the time went should be read in every
        # report, not discovered after a quarter of silent rot.  The
        # same number is live on the fleet overview
        # (totals.hopReconcileRatioMixed).
        mixed = self.stats.get("hop_reconcile_ratio_mixed")
        tail = (f" [hop_reconcile_ratio_mixed={mixed:.3f}, unguarded]"
                if mixed else "")
        failed = self.failures()
        if not failed:
            return f"soak OK: {len(self.guards)} guards green{tail}"
        names = ", ".join(
            f"{g.name}={g.value:.3f}!{g.op}{g.bound}" for g in failed)
        return (f"soak FAILED {len(failed)}/{len(self.guards)}: "
                f"{names}{tail}")


def _ceiling(name: str, value: float, bound: float,
             detail: str = "") -> Guard:
    return Guard(name, float(value), float(bound),
                 float(value) <= float(bound), "<=", detail)


def _exact_zero(name: str, value: float, detail: str = "") -> Guard:
    return Guard(name, float(value), 0.0, float(value) == 0.0, "==",
                 detail)


def evaluate(profile, outcomes, samples, world) -> SoakReport:
    """Build the report for one finished run.

    ``outcomes``: the rig's per-job results (``JobOutcome``); every
    published job must appear.  ``samples``: the
    :class:`~.sampler.GrowthSampler` series.  ``world``: the rig's
    end-of-run census (:class:`~.rig.SoakWorld`).
    """
    report = SoakReport()
    guards = report.guards
    stats = report.stats

    # -- completion & outcome hygiene ----------------------------------
    unresolved = [o for o in outcomes if o.resolved_mono is None]
    guards.append(_exact_zero(
        "unresolved_jobs", len(unresolved),
        ", ".join(o.spec.job_id for o in unresolved[:8])))
    bad = [o for o in outcomes
           if o.terminal_state in ("FAILED", "DROPPED_POISON")]
    # zero FAILED / DROPPED_POISON despite injected transient faults
    # and SIGKILLs == the poison budget stayed monotone and never
    # crossed its threshold from counting the same failure twice
    guards.append(_exact_zero(
        "failed_or_poisoned_jobs", len(bad),
        ", ".join(f"{o.spec.job_id}={o.terminal_state}"
                  for o in bad[:8])))
    expired = [o for o in outcomes if o.terminal_state == "EXPIRED"]
    non_bulk_expired = [o for o in expired if o.spec.priority != "BULK"]
    guards.append(_exact_zero(
        "non_bulk_expired_jobs", len(non_bulk_expired),
        "only deadline-carrying BULK work may expire"))
    stats["jobs"] = float(len(outcomes))
    stats["expired_bulk"] = float(len(expired) - len(non_bulk_expired))

    # -- p99 time-to-staged per priority class -------------------------
    by_class: Dict[str, List[float]] = {}
    for outcome in outcomes:
        if outcome.staged_mono is None:
            continue
        cls = outcome.spec.priority if outcome.spec.priority \
            in PRIORITY_CLASSES else "NORMAL"
        by_class.setdefault(cls, []).append(
            outcome.staged_mono - outcome.published_mono)
    for cls in PRIORITY_CLASSES:
        walls = by_class.get(cls, [])
        if not walls:
            continue
        p99 = percentile(walls, 99.0)
        stats[f"p99_{cls.lower()}_s"] = round(p99, 3)
        stats[f"p50_{cls.lower()}_s"] = round(
            percentile(walls, 50.0), 3)
        guards.append(_ceiling(
            f"p99_time_to_staged_{cls.lower()}", p99,
            profile.p99_ceiling.get(cls, 60.0),
            f"{len(walls)} jobs"))

    # -- bounded growth: journal ---------------------------------------
    journal_peak = 0
    for sample in samples:
        for size in sample.journal_bytes.values():
            journal_peak = max(journal_peak, size)
    for size in world.journal_final_bytes.values():
        journal_peak = max(journal_peak, size)
    stats["journal_peak_bytes"] = float(journal_peak)
    guards.append(_ceiling(
        "journal_peak_bytes", journal_peak, profile.journal_peak_limit,
        f"journal.max_bytes={profile.journal_max_bytes}"))

    # -- bounded growth: coordination store ----------------------------
    # finals judge LIVE docs (tombstones resolved away — a tombstone
    # already reads as absent and is compacted by the slower tombstone
    # sweep); the per-sample peaks track raw objects, disk reality
    telemetry_peak = max(
        (s.coord_docs.get("telemetry", 0) for s in samples), default=0)
    stats["coord_telemetry_peak_raw"] = float(telemetry_peak)
    telemetry_final = world.coord_live.get("telemetry", 0)
    stats["coord_telemetry_final"] = float(telemetry_final)
    guards.append(_ceiling(
        "coord_telemetry_docs_final", telemetry_final,
        max(profile.telemetry_final_fraction * len(outcomes), 4.0),
        f"raw peak {telemetry_peak}; fleet GC must age digests out"))
    guards.append(_ceiling(
        "coord_worker_docs_final", world.coord_live.get("workers", 0),
        profile.workers + 2,
        "dead generations must age out of the registry"))
    guards.append(_exact_zero(
        "leaked_leases_at_drain", len(world.leaked_leases),
        ", ".join(world.leaked_leases[:4])))

    # -- bounded growth: shared cache tier -----------------------------
    shared_peak = max((s.shared_cache_bytes for s in samples), default=0)
    stats["shared_cache_peak_bytes"] = float(shared_peak)
    guards.append(_ceiling(
        "shared_cache_peak_bytes", shared_peak,
        profile.shared_cache_limit,
        f"fleet.shared_max_bytes={profile.shared_max_bytes}"))

    # -- bounded growth: worker RSS ------------------------------------
    slope = rss_slope_mb_per_kjob(samples)
    stats["rss_slope_mb_per_kjob"] = round(slope, 3)
    guards.append(_ceiling(
        "rss_slope_mb_per_kjob", slope,
        profile.rss_slope_limit_mb_per_kjob,
        "max over worker generations"))

    # -- drain hygiene -------------------------------------------------
    orphans = [f"w{idx}:{name}"
               for idx, names in world.orphan_workdirs.items()
               for name in names]
    guards.append(_exact_zero(
        "orphan_workdirs_at_drain", len(orphans),
        ", ".join(orphans[:6])))
    guards.append(_exact_zero(
        "staged_byte_mismatches", len(world.byte_mismatches),
        ", ".join(world.byte_mismatches[:6])))
    guards.append(_exact_zero(
        "unsettled_journal_jobs_at_drain",
        len(world.unsettled_journal_jobs),
        ", ".join(world.unsettled_journal_jobs[:6])))
    # a kill OR a stall can each strand at most one in-flight scrape
    # (the sampler skips not-ready workers, but a freeze can land mid-
    # request) — both count toward the allowance
    chaos_events = (world.kills_delivered
                    + getattr(world, "stalls_delivered", 0))
    guards.append(_exact_zero(
        "sampler_scrape_failures_beyond_kills",
        max(world.scrape_failures - chaos_events, 0),
        f"{world.scrape_failures} failures, "
        f"{world.kills_delivered} kills, "
        f"{getattr(world, 'stalls_delivered', 0)} stalls"))
    stats["kills_delivered"] = float(world.kills_delivered)
    stats["stalls_delivered"] = float(
        getattr(world, "stalls_delivered", 0))

    # -- hop-ledger vs wall-clock reconciliation -----------------------
    # judged over the QUIESCENT attribution-probe jobs: sequential,
    # fresh-content, single-stream, transfer-dominated — the regime
    # where stage wall is attributable to I/O at all.  The mixed
    # phase's wall is contention (dozens of concurrent jobs inflate
    # each other's clocks) and racing/manifest jobs bill concurrent
    # origin connections > wall by design; both stay visible as the
    # ``hop_reconcile_ratio_mixed`` stat, unguarded.
    probe_ids = {o.spec.job_id for o in outcomes
                 if o.spec.kind == "probe"}
    ratio, eligible = hop_reconciliation(world.records, probe_ids)
    stats["hop_reconcile_ratio"] = round(ratio, 4)
    stats["hop_reconcile_jobs"] = float(eligible)
    mixed_ids = {o.spec.job_id for o in outcomes
                 if o.spec.kind in ("plain", "hot", "bulk")}
    mixed_ratio, mixed_n = hop_reconciliation(world.records, mixed_ids)
    stats["hop_reconcile_ratio_mixed"] = round(mixed_ratio, 4)
    stats["hop_reconcile_jobs_mixed"] = float(mixed_n)
    if not probe_ids:
        # no probe was scheduled (probe_jobs=0 / no probe endpoints):
        # the guard is out of scope, not vacuously green or red
        return report
    if eligible >= len(probe_ids):
        guards.append(_ceiling(
            "hop_reconcile_error", abs(1.0 - ratio),
            profile.hop_reconcile_tolerance,
            f"{eligible} probe jobs, sum(hop)/sum(stage)={ratio:.3f}"))
    else:
        guards.append(Guard(
            "hop_reconcile_error", 1.0, profile.hop_reconcile_tolerance,
            False, "<=",
            f"only {eligible}/{len(probe_ids)} probe jobs reconcilable "
            "— ledger coverage collapsed (vacuous pass refused)"))
    return report


def rss_slope_mb_per_kjob(samples) -> float:
    """Max RSS growth slope across worker generations.

    x = completed jobs (thousands) at sample time, y = that
    generation's RSS in MB.  The first quarter of each generation's
    series is dropped — a freshly-started interpreter ramps from ~20
    to ~45 MB while it warms caches and arenas, and fitting that ramp
    reads as a catastrophic "leak" (the soak's first full run measured
    1.1 GB/kjob of pure warmup).  A generation votes only with ≥ 8
    post-warmup samples spanning ≥ 20 jobs of progress.
    """
    series: Dict[tuple, List[tuple]] = {}
    for sample in samples:
        for (idx, generation), rss in sample.rss_bytes.items():
            if rss <= 0:
                continue
            series.setdefault((idx, generation), []).append(
                (sample.done_jobs / 1000.0, rss / 1e6))
    worst = 0.0
    for points in series.values():
        points = points[len(points) // 4:]
        if len(points) < 8:
            continue
        xs = [p[0] for p in points]
        ys = [p[1] for p in points]
        if (max(xs) - min(xs)) * 1000.0 < 20.0:
            continue
        worst = max(worst, fit_slope(xs, ys))
    return worst


def brownout_shed_seconds(samples, start_mono: float,
                          dependency: str = "store"
                          ) -> Optional[float]:
    """Seconds from the brownout window opening to the FIRST sample
    showing ``dependency``'s breaker away from closed on any worker —
    the shed latency the degraded profile guards (``brownout_shed_ms``).
    None when no sample ever saw the breaker leave closed."""
    selector = f'dependency="{dependency}"'
    for sample in samples:
        if sample.t_mono < start_mono:
            continue
        for index in sample.scraped:
            value = sample.metric(index, "breaker_state", selector)
            if value is not None and value >= 1.0:
                return sample.t_mono - start_mono
    return None


def slow_opens_total(samples, dependency: str = "store") -> float:
    """Total ``breaker_opened_total{reason="slow"}`` opens for
    ``dependency`` across workers, from each worker's LAST scrape —
    proves the brownout tripped the slow-call policy, not the failure
    counter."""
    latest: Dict[int, float] = {}
    selector = (f'dependency="{dependency}"', 'reason="slow"')
    for sample in samples:
        for index, scraped in sample.scraped.items():
            for name, value in scraped.items():
                family = name.split("{", 1)[0]
                if not family.endswith("breaker_opened_total"):
                    continue
                if all(part in name for part in selector):
                    latest[index] = value
    return sum(latest.values())


def fenced_writes_total(samples) -> float:
    """Total ``fleet_fenced_writes_total`` across workers and ops, from
    each worker's last scrape — the split-brain writes the fence
    rejected over the run."""
    latest: Dict[tuple, float] = {}
    for sample in samples:
        for index, scraped in sample.scraped.items():
            for name, value in scraped.items():
                family = name.split("{", 1)[0]
                if family.endswith("fleet_fenced_writes_total"):
                    latest[(index, name)] = value
    return sum(latest.values())


def hop_reconciliation(records: List[dict],
                       eligible_ids: Optional[set] = None
                       ) -> "tuple[float, int]":
    """``(sum(hop seconds)/sum(stage seconds), eligible jobs)`` over
    DONE records that fetched their own bytes (``bytes.downloaded`` >
    0) and carry a hop ledger — the set whose RUNNING wall is transfer
    work, so the ledger must account for it.  Coalesced waiters and
    cache hits idle inside their stage by design and are excluded;
    ``eligible_ids`` further restricts to single-stream jobs (parallel
    range fetchers bill concurrent hop seconds > wall by design).
    """
    hop_total = 0.0
    stage_total = 0.0
    eligible = 0
    for record in records:
        if record.get("state") != "DONE":
            continue
        if (eligible_ids is not None
                and record.get("id") not in eligible_ids):
            continue
        if not (record.get("bytes") or {}).get("downloaded"):
            continue
        ledger = record.get("hopLedger") or {}
        stage_seconds = record.get("stageSeconds") or {}
        if not ledger or not stage_seconds:
            continue
        hops = sum(float(entry.get("seconds", 0.0))
                   for entry in ledger.values())
        wall = sum(float(s) for s in stage_seconds.values())
        if wall <= 0.0:
            continue
        eligible += 1
        hop_total += hops
        stage_total += wall
    if stage_total <= 0.0:
        return 0.0, eligible
    return hop_total / stage_total, eligible
