"""The soak rig: a real multi-worker fleet under sustained mixed load.

Generalizes the crash harness's one-worker pattern (tests/test_crash.py
``CrashRig``) to N ``python -m downloader_tpu`` subprocess workers that
share one real-wire broker and one staging store, then holds them under
the full workload mix while SIGKILLing and restarting workers on a
cadence.  Per-job time-to-staged is measured from the *durable world*
— the staging store's done markers — so a worker dying mid-run can
never lose the measurement, only slow the job.

The rig owns no backends: the broker URL, object store, and origin
endpoints are injected (tests stand up MiniAmqp/MiniS3; a production
soak could point at real RabbitMQ/MinIO the same way).
"""

from __future__ import annotations

import asyncio
import os
import signal
import socket
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import aiohttp
import yaml

from .. import schemas
from ..control.journal import JOURNAL_DIRNAME, JOURNAL_FILENAME, replay
from ..fleet.coord import BucketCoordStore
from ..incident.replay import collect_incidents
from ..mq.amqp import AmqpQueue
from ..stages.upload import (STAGING_BUCKET, done_marker_name,
                             object_name)
from ..store.base import ObjectNotFound
from .sampler import GrowthSampler
from .slo import SoakReport, evaluate
from .workload import JobSpec, SoakProfile, SoakWorkload, download_msg

#: terminal states the admin-API fallback accepts as "resolved without
#: a done marker" (EXPIRED is legitimate for deadline-carrying BULK;
#: the others are guard violations the SLO layer flags)
_TERMINAL_NO_MARKER = ("EXPIRED", "FAILED", "DROPPED_POISON",
                       "CANCELLED")


def _flip_byte(path: str) -> None:
    """Deterministic single-byte bit-rot at the file's midpoint."""
    size = os.path.getsize(path)
    if size <= 0:
        raise OSError(0, "empty file")
    offset = size // 2
    with open(path, "r+b") as fh:
        fh.seek(offset)
        byte = fh.read(1)
        fh.seek(offset)
        fh.write(bytes([byte[0] ^ 0xFF]))


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _open_log(path: str):
    return open(path, "ab")


@dataclass
class WorkerSlot:
    """One worker identity: stable across kill/restart generations."""

    index: int
    worker_id: str
    downloads: str
    cache_dir: str
    config_dir: str
    log_dir: str
    health_port: int
    proc: Optional[object] = None
    generation: int = 0
    #: set once /readyz answered for the CURRENT generation — the
    #: sampler must not scrape (and tally failures against) a process
    #: still booting after a chaos respawn (cleared again while the
    #: stall chaos holds the process under SIGSTOP)
    ready: bool = False
    #: monotonic time the current generation's /readyz first answered —
    #: the anchor the degraded profile's brownout-window measurements
    #: (``brownout_shed_ms``) are taken from
    ready_mono: float = 0.0

    @property
    def pid(self) -> int:
        return self.proc.pid if self.proc is not None else 0

    @property
    def alive(self) -> bool:
        return (self.ready and self.proc is not None
                and self.proc.returncode is None)

    @property
    def journal_path(self) -> str:
        return os.path.join(self.downloads, JOURNAL_DIRNAME,
                            JOURNAL_FILENAME)


@dataclass
class JobOutcome:
    """One published job's fate, as observed from the durable world."""

    spec: JobSpec
    published_mono: float
    staged_mono: Optional[float] = None
    terminal_state: Optional[str] = None
    resolved_mono: Optional[float] = None


@dataclass
class SoakWorld:
    """The end-of-run census the SLO guards judge drain hygiene on."""

    leaked_leases: List[str] = field(default_factory=list)
    orphan_workdirs: Dict[int, List[str]] = field(default_factory=dict)
    records: List[dict] = field(default_factory=list)
    #: LIVE coordination docs per prefix at drain (tombstones resolved
    #: away — the per-sample census counts raw objects instead, which
    #: include tombstones until the fleet GC's sweep compacts them)
    coord_live: Dict[str, int] = field(default_factory=dict)
    journal_final_bytes: Dict[int, int] = field(default_factory=dict)
    unsettled_journal_jobs: List[str] = field(default_factory=list)
    byte_mismatches: List[str] = field(default_factory=list)
    scrape_failures: int = 0
    kills_delivered: int = 0
    #: SIGSTOP/SIGCONT stalls delivered (degraded profile) — like a
    #: kill, a stall can fail at most one in-flight scrape
    stalls_delivered: int = 0


class SoakRig:
    """Drive one profile's workload through a real worker fleet."""

    def __init__(self, profile: SoakProfile, *, amqp_url: str, store,
                 s3_endpoint: str, access_key: str = "AKIA",
                 secret_key: str = "SECRET", root: str,
                 bucket: str = STAGING_BUCKET, logger=None):
        self.profile = profile
        self.amqp_url = amqp_url
        self.store = store
        self.s3_endpoint = s3_endpoint
        self.access_key = access_key
        self.secret_key = secret_key
        self.root = root
        self.bucket = bucket
        self.logger = logger
        self.outcomes: Dict[str, JobOutcome] = {}
        self.kills_delivered = 0
        self.stalls_delivered = 0
        self.world: Optional[SoakWorld] = None
        #: the growth sampler's series, kept after run() for callers
        #: that inspect the raw timelines (tests, the bench)
        self.samples: List = []
        #: the fleet's auto-exported incident bundles (ISSUE 18),
        #: pulled from every live worker's /v1/incidents just before
        #: drain — the replay diff's raw material
        self.incidents: List[dict] = []
        #: the bit-rot phase's record (disk profile): seeded corrupt
        #: paths, scrub totals before seeding, and the final totals —
        #: the bench's ``scrub_repaired == seeded`` guard reads these
        self.seeded_corruptions: List[str] = []
        self.scrub_base: Dict[str, int] = {}
        self.scrub_final: Dict[str, int] = {}
        self.slots = [self._make_slot(i) for i in range(profile.workers)]
        self._session: Optional[aiohttp.ClientSession] = None

    def _make_slot(self, index: int) -> WorkerSlot:
        base = os.path.join(self.root, f"w{index}")
        return WorkerSlot(
            index=index,
            worker_id=f"soak-w{index}",
            downloads=os.path.join(base, "downloads"),
            cache_dir=os.path.join(base, "cache"),
            config_dir=os.path.join(base, "config"),
            log_dir=base,
            health_port=_free_port(),
        )

    # -- sampler duck-type ---------------------------------------------
    def live_workers(self) -> List[WorkerSlot]:
        return [slot for slot in self.slots if slot.alive]

    def resolved_jobs(self) -> int:
        return sum(1 for o in self.outcomes.values()
                   if o.resolved_mono is not None)

    async def store_census(self) -> "tuple[Dict[str, int], int]":
        """(coordination docs by prefix, `.fleet-cache/` bytes) counted
        from the durable store — tombstones included: disk reality."""
        docs = {"workers": 0, "leases": 0, "telemetry": 0}
        async for info in self.store.list_objects(self.bucket, ".fleet/"):
            rest = info.name[len(".fleet/"):]
            prefix = rest.split("/", 1)[0]
            if prefix in docs:
                docs[prefix] += 1
        shared = 0
        async for info in self.store.list_objects(self.bucket,
                                                  ".fleet-cache/"):
            shared += info.size
        return docs, shared

    # -- worker lifecycle ----------------------------------------------
    def write_config(self, slot: WorkerSlot) -> None:
        profile = self.profile
        cfg = {
            "instance": {
                "download_path": slot.downloads,
                "max_concurrent_jobs": profile.max_concurrent_jobs,
                "scheduler_backlog": profile.scheduler_backlog,
                "cache": {
                    "enabled": True,
                    "path": slot.cache_dir,
                    "max_bytes": 256 << 20,
                    "min_free_bytes": 1 << 20,
                },
            },
            "rabbitmq": {"backend": "amqp"},
            "minio": {"backend": "s3", "endpoint": self.s3_endpoint,
                      "access_key": self.access_key,
                      "secret_key": self.secret_key},
            "services": {"rabbitmq": self.amqp_url},
            "journal": {
                "max_bytes": profile.journal_max_bytes,
                # retire peer-settled placeholders fast: the kill chaos
                # hands redeliveries to surviving workers on purpose
                "staged_probe_interval": 1.5,
            },
            "retry": {
                "default": {"attempts": 2, "base": 0.05, "cap": 0.25},
                "redelivery": {"base": 0.05, "cap": 0.5},
            },
            "fleet": {
                "enabled": True, "backend": "bucket",
                # short lease TTL: a killed lease-holder must not park
                # fan-in waiters for tens of seconds — takeover at
                # ttl*1.25 bounds the worst hot-key stall the p99
                # guards can see (the degraded profile shrinks it so a
                # SIGSTOP stall reliably overruns it)
                "lease_ttl": profile.lease_ttl,
                "heartbeat_interval": 1.0,
                "liveness_ttl": 4.0, "poll_interval": 0.2,
                "max_wait": 30.0,
                "gc_interval": profile.gc_interval,
                "telemetry_ttl": profile.telemetry_ttl,
                "shared_max_age": profile.shared_max_age,
                "shared_max_bytes": profile.shared_max_bytes,
            },
            "tenants": {
                "vip": {"weight": 4},
                "batch": {"weight": 1,
                          "max_concurrent": max(
                              profile.max_concurrent_jobs - 1, 1)},
            },
            "origins": {"manifest": {"min_poll": 0.1, "max_poll": 0.5,
                                     "stall_timeout": 15.0}},
        }
        if profile.retry:
            # the disk profile paces redelivery at disk-heal timescales
            for section, knobs in profile.retry.items():
                cfg["retry"].setdefault(section, {}).update(knobs)
        if profile.scrub:
            # the disk profile shrinks the scrub interval so repairs
            # land inside the run's bit-rot phase
            cfg["scrub"] = dict(profile.scrub)
        if profile.breakers:
            # the degraded profile arms the slow-call policy here
            cfg["breakers"] = dict(profile.breakers)
        if profile.slo:
            # fleet-overview tests tighten the objectives so brownout
            # latency visibly burns budget inside a short run
            cfg["slo"] = dict(profile.slo)
        os.makedirs(slot.config_dir, exist_ok=True)
        with open(os.path.join(slot.config_dir, "converter.yaml"), "w",
                  encoding="utf-8") as fh:
            yaml.safe_dump(cfg, fh)

    async def spawn(self, slot: WorkerSlot, fault_plan: str = "") -> None:
        slot.generation += 1
        slot.ready = False
        env = {key: value for key, value in os.environ.items()
               if key not in ("FAULT_PLAN", "PIPELINE_MODE", "CACHE_DIR",
                              "CACHE_ENABLED", "UPLOAD_CONCURRENCY",
                              "CONFIG_PATH", "PORT", "WORKER_ID")}
        env["CONFIG_PATH"] = slot.config_dir
        env["PORT"] = str(slot.health_port)
        env["WORKER_ID"] = slot.worker_id  # stable across generations
        if fault_plan:
            env["FAULT_PLAN"] = fault_plan
        log_path = os.path.join(
            slot.log_dir, f"worker-gen{slot.generation}.log")
        log = await asyncio.to_thread(_open_log, log_path)
        try:
            slot.proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "downloader_tpu",
                env=env, stdout=log, stderr=log, cwd=_repo_root(),
            )
        finally:
            log.close()
        await self._wait_ready(slot)

    async def _wait_ready(self, slot: WorkerSlot,
                          timeout: float = 30.0) -> None:
        async with asyncio.timeout(timeout):
            while True:
                if slot.proc.returncode is not None:
                    raise AssertionError(
                        f"worker {slot.worker_id} gen{slot.generation} "
                        f"exited {slot.proc.returncode} before ready "
                        f"(see {slot.log_dir})"
                    )
                try:
                    async with self._session.get(
                            self._url(slot, "/readyz")) as resp:
                        if resp.status == 200:
                            slot.ready = True
                            slot.ready_mono = time.monotonic()
                            return
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.1)

    def _url(self, slot: WorkerSlot, path: str) -> str:
        return f"http://127.0.0.1:{slot.health_port}{path}"

    async def kill_worker(self, slot: WorkerSlot) -> None:
        """True SIGKILL — no shutdown hooks, no journal flush."""
        slot.ready = False
        slot.proc.send_signal(signal.SIGKILL)
        await slot.proc.wait()
        self.kills_delivered += 1

    async def stall_worker(self, slot: WorkerSlot,
                           duration: float) -> None:
        """SIGSTOP the worker for ``duration`` seconds, then SIGCONT.

        A stalled worker is NOT a killed worker: its leases expire and
        peers take over (fence + 1) while its process state — in-flight
        transfers, held "leases", unacked deliveries — survives intact
        and resumes mid-takeover.  Exactly the GC-pause split-brain the
        fencing enforcement exists for.  ``ready`` is cleared for the
        stall window so the sampler doesn't tally the frozen process's
        unanswered scrapes as failures."""
        slot.ready = False
        slot.proc.send_signal(signal.SIGSTOP)
        self.stalls_delivered += 1
        try:
            await asyncio.sleep(duration)
        finally:
            slot.proc.send_signal(signal.SIGCONT)
            if slot.proc.returncode is None:
                slot.ready = True

    async def _stall_loop(self) -> None:
        profile = self.profile
        if profile.stalls <= 0 or profile.stall_duration <= 0:
            return
        stalls = 0
        while stalls < profile.stalls:
            await asyncio.sleep(profile.stall_interval)
            # stall workers from the TOP index down, away from worker 0
            # (the fault-plan host): the brownout and the stall must
            # degrade different workers or the scenario collapses into
            # one sick process
            slot = self.slots[len(self.slots) - 1
                              - (stalls % len(self.slots))]
            if not slot.alive:
                continue
            await self.stall_worker(slot, profile.stall_duration)
            stalls += 1

    async def stop_workers(self) -> None:
        """Clean TERM (deregister + journal close); KILL stragglers."""
        for slot in self.slots:
            # raw process check, not `alive`: a still-BOOTING worker
            # (ready not yet set) must be terminated too
            if slot.proc is not None and slot.proc.returncode is None:
                slot.proc.send_signal(signal.SIGTERM)
        for slot in self.slots:
            if slot.proc is None:
                continue
            try:
                async with asyncio.timeout(12):
                    await slot.proc.wait()
            except TimeoutError:
                slot.proc.send_signal(signal.SIGKILL)
                await slot.proc.wait()

    # -- workload -------------------------------------------------------
    async def publish_all(self, specs: List[JobSpec],
                          rate: float = 0.0) -> None:
        """Publish the schedule; ``rate`` > 0 paces arrivals open-loop
        (jobs/s) so long profiles measure service under load, not the
        drain time of one giant burst."""
        queue = AmqpQueue(self.amqp_url, heartbeat=10)
        await queue.connect()
        try:
            for index, spec in enumerate(specs):
                await queue.publish(schemas.DOWNLOAD_QUEUE,
                                    download_msg(spec))
                self.outcomes[spec.job_id] = JobOutcome(
                    spec, time.monotonic())
                if rate > 0 and index + 1 < len(specs):
                    await asyncio.sleep(1.0 / rate)
        finally:
            await queue.close()

    async def _check_marker(self, outcome: JobOutcome) -> None:
        try:
            await self.store.stat_object(
                self.bucket, done_marker_name(outcome.spec.job_id))
        except ObjectNotFound:
            return
        except Exception:
            return  # store blip: next pass decides
        now = time.monotonic()
        outcome.staged_mono = now
        outcome.resolved_mono = now
        outcome.terminal_state = "DONE"

    async def _poll_admin_terminal(self, outcome: JobOutcome) -> None:
        for slot in self.live_workers():
            try:
                async with self._session.get(self._url(
                        slot, f"/v1/jobs/{outcome.spec.job_id}")) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except (aiohttp.ClientError, OSError):
                continue
            if body.get("state") in _TERMINAL_NO_MARKER:
                outcome.terminal_state = body["state"]
                outcome.resolved_mono = time.monotonic()
                return

    async def _completion_loop(self, deadline_mono: float,
                               expected: int) -> bool:
        """Poll until every one of ``expected`` jobs resolves (paced
        publishing means outcomes appear over time — an empty pending
        set only counts once the whole schedule has been published)."""
        tick = 0
        while time.monotonic() < deadline_mono:
            pending = [o for o in self.outcomes.values()
                       if o.resolved_mono is None]
            if not pending and len(self.outcomes) >= expected:
                return True
            for start in range(0, len(pending), 16):
                await asyncio.gather(*(
                    self._check_marker(o)
                    for o in pending[start:start + 16]))
            tick += 1
            if tick % 5 == 0:
                now = time.monotonic()
                for outcome in pending:
                    if (outcome.resolved_mono is None
                            and now - outcome.published_mono > 8.0):
                        await self._poll_admin_terminal(outcome)
            await asyncio.sleep(0.2)
        return (len(self.outcomes) >= expected
                and all(o.resolved_mono is not None
                        for o in self.outcomes.values()))

    async def _attribution_probe(self, specs: List[JobSpec]) -> None:
        """Run the probe jobs one at a time on the now-quiescent fleet.

        Sequential + fresh content + rate-limited origins = a stage
        wall that is genuinely attributable, the regime the hop-ledger
        reconciliation guard (≤ 10%) is defined over.  The mixed phase
        deliberately runs dozens of concurrent jobs whose wall clock is
        contention — reconciling THAT against per-job ledgers would
        punish the load the soak exists to create.
        """
        if not specs:
            return
        queue = AmqpQueue(self.amqp_url, heartbeat=10)
        await queue.connect()
        try:
            for spec in specs:
                await queue.publish(schemas.DOWNLOAD_QUEUE,
                                    download_msg(spec))
                outcome = JobOutcome(spec, time.monotonic())
                self.outcomes[spec.job_id] = outcome
                try:
                    async with asyncio.timeout(30):
                        while outcome.resolved_mono is None:
                            await self._check_marker(outcome)
                            if outcome.resolved_mono is None:
                                await asyncio.sleep(0.1)
                except TimeoutError:
                    # a hung probe must not abort the run with a bare
                    # traceback: the job stays unresolved and the
                    # unresolved_jobs guard fails WITH the rest of the
                    # report's attribution intact
                    continue
        finally:
            await queue.close()

    async def _chaos_loop(self, expected: int) -> None:
        profile = self.profile
        if profile.kill_interval <= 0 or profile.kills <= 0:
            return
        kills = 0
        while kills < profile.kills:
            await asyncio.sleep(profile.kill_interval)
            if self.resolved_jobs() >= expected:
                return  # workload already drained: chaos window over
            slot = self.slots[kills % len(self.slots)]
            if not slot.alive:
                continue
            await self.kill_worker(slot)
            kills += 1
            await asyncio.sleep(0.25)
            # same worker id: boot-time lease reclaim + journal replay
            await self.spawn(slot)

    # -- drain + census -------------------------------------------------
    async def drain_workers(self, grace: float = 10.0) -> None:
        for slot in self.live_workers():
            try:
                async with self._session.post(self._url(
                        slot, f"/v1/drain?grace={grace}")) as resp:
                    await resp.read()
            except (aiohttp.ClientError, OSError):
                continue

    async def live_leases(self) -> List[str]:
        """Lease keys whose coordination doc is LIVE (tombstoned and
        expired docs resolve to None, like real readers see them)."""
        coord = BucketCoordStore(self.store, self.bucket)
        out = []
        async for info in self.store.list_objects(self.bucket,
                                                  ".fleet/leases/"):
            key = info.name[len(".fleet/"):]
            if await coord.get(key) is not None:
                out.append(info.name)
        return out

    async def live_coord_census(self) -> Dict[str, int]:
        """LIVE docs per prefix (tombstones resolved away) — the drain
        census: what the fleet GC is accountable for leaving behind."""
        coord = BucketCoordStore(self.store, self.bucket)
        out = {"workers": 0, "leases": 0, "telemetry": 0}
        for prefix in out:
            for key in await coord.list_keys(prefix + "/"):
                try:
                    if await coord.get(key) is not None:
                        out[prefix] += 1
                except Exception:
                    continue
        return out

    async def collect_records(self) -> List[dict]:
        """Merged ``GET /v1/jobs`` across live workers: per job, prefer
        the DONE record (the settle that counts), else the latest."""
        merged: Dict[str, dict] = {}
        for slot in self.live_workers():
            try:
                async with self._session.get(
                        self._url(slot, "/v1/jobs")) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except (aiohttp.ClientError, OSError):
                continue
            for record in body.get("jobs", []):
                job_id = record.get("id")
                if not job_id:
                    continue
                prior = merged.get(job_id)
                if prior is None or (record.get("state") == "DONE"
                                     and prior.get("state") != "DONE"):
                    merged[job_id] = record
        return list(merged.values())

    def _orphan_workdirs(self, slot: WorkerSlot) -> List[str]:
        try:
            entries = os.listdir(slot.downloads)
        except OSError:
            return []
        return sorted(
            entry for entry in entries
            if not entry.startswith(".")
            and os.path.isdir(os.path.join(slot.downloads, entry)))

    async def verify_staged_bytes(self) -> List[str]:
        """Byte-identity of every DONE job's staged set against what
        its origin served — kills or not, a staged byte is exact."""
        mismatches: List[str] = []
        for outcome in self.outcomes.values():
            if outcome.terminal_state != "DONE":
                continue
            for basename, payload in outcome.spec.origin.files:
                name = object_name(outcome.spec.job_id, basename)
                try:
                    staged = await self.store.get_object(
                        self.bucket, name)
                except Exception:
                    mismatches.append(
                        f"{outcome.spec.job_id}:{basename}:missing")
                    continue
                if staged != payload:
                    mismatches.append(
                        f"{outcome.spec.job_id}:{basename}:diverged")
        return mismatches

    # -- the bit-rot phase (disk profile) -------------------------------
    async def scrub_totals(self) -> Dict[str, int]:
        """Fleet-summed scrubber verdict counters, read from each live
        worker's own SLO digest (``local.digest.scrub`` on the fleet
        overview endpoint — no aggregation TTL in the way)."""
        totals = {"passes": 0, "clean": 0, "repaired": 0,
                  "quarantined": 0}
        for slot in self.live_workers():
            try:
                async with self._session.get(self._url(
                        slot, "/v1/fleet/overview")) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except (aiohttp.ClientError, OSError):
                continue
            snap = (((body.get("local") or {}).get("digest") or {})
                    .get("scrub") or {})
            for key in totals:
                totals[key] += int(snap.get(key) or 0)
        return totals

    def _cache_entry_files(self, slot: WorkerSlot) -> List[tuple]:
        """(key, path) for every payload file in this worker's cache."""
        entries_dir = os.path.join(slot.cache_dir, "entries")
        out: List[tuple] = []
        try:
            keys = sorted(os.listdir(entries_dir))
        except OSError:
            return out
        for key in keys:
            key_dir = os.path.join(entries_dir, key)
            for dirpath, _dirnames, filenames in os.walk(key_dir):
                for name in sorted(filenames):
                    if name.startswith("."):
                        continue  # .meta.json / transient temps
                    out.append((key, os.path.join(dirpath, name)))
        return out

    async def _repairable_keys(self) -> set:
        """Cache keys whose shared-tier manifest is live — the set the
        scrubber can repair (not just quarantine)."""
        keys = set()
        async for info in self.store.list_objects(self.bucket,
                                                  ".fleet-cache/"):
            rest = info.name[len(".fleet-cache/"):]
            if rest.endswith("/manifest.json"):
                keys.add(rest[: -len("/manifest.json")])
        return keys

    async def seed_bitrot(self, count: int) -> List[str]:
        """Flip one byte in up to ``count`` cache-entry files whose key
        has a live shared-tier replica.  Returns the corrupted paths —
        the oracle the ``scrub_repaired == seeded`` guard compares
        against."""
        repairable = await self._repairable_keys()
        seeded: List[str] = []
        for slot in self.slots:
            for key, path in await asyncio.to_thread(
                    self._cache_entry_files, slot):
                if len(seeded) >= count:
                    return seeded
                if key not in repairable:
                    continue
                try:
                    await asyncio.to_thread(_flip_byte, path)
                except OSError:
                    continue
                seeded.append(path)
        return seeded

    async def _bitrot_phase(self) -> None:
        """Seed bit-rot on the drained fleet, then hold it up until the
        scrubber has accounted for every seed (repair or quarantine —
        the guard that they were all *repairs* is the bench's)."""
        profile = self.profile
        if profile.corrupt_files <= 0:
            return
        self.scrub_base = await self.scrub_totals()
        self.seeded_corruptions = await self.seed_bitrot(
            profile.corrupt_files)
        deadline = time.monotonic() + profile.scrub_wall
        while True:
            self.scrub_final = await self.scrub_totals()
            found = ((self.scrub_final["repaired"]
                      - self.scrub_base["repaired"])
                     + (self.scrub_final["quarantined"]
                        - self.scrub_base["quarantined"]))
            if found >= len(self.seeded_corruptions):
                return
            if time.monotonic() >= deadline:
                return  # the bench guard reports the shortfall
            await asyncio.sleep(0.3)

    async def collect_world(self, scrape_failures: int) -> SoakWorld:
        world = SoakWorld(scrape_failures=scrape_failures,
                          kills_delivered=self.kills_delivered,
                          stalls_delivered=self.stalls_delivered)
        world.leaked_leases = await self.live_leases()
        world.coord_live = await self.live_coord_census()
        world.records = await self.collect_records()
        await self.stop_workers()
        settled: set = set()
        live: set = set()
        for slot in self.slots:
            state = await asyncio.to_thread(replay, slot.journal_path)
            try:
                world.journal_final_bytes[slot.index] = os.path.getsize(
                    slot.journal_path)
            except OSError:
                world.journal_final_bytes[slot.index] = 0
            for job_id, job in state.jobs.items():
                if job.settle == "ack":
                    settled.add(job_id)
                elif job.redelivery_expected:
                    live.add(job_id)
            world.orphan_workdirs[slot.index] = await asyncio.to_thread(
                self._orphan_workdirs, slot)
        world.unsettled_journal_jobs = sorted(live - settled)
        world.byte_mismatches = await self.verify_staged_bytes()
        return world

    # -- the run --------------------------------------------------------
    async def run(self, workload: SoakWorkload) -> SoakReport:
        profile = self.profile
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=5.0))
        sampler = GrowthSampler(self, interval=profile.sample_interval)
        stop_sampling = asyncio.Event()
        chaos_task = None
        sampler_task = None
        try:
            for slot in self.slots:
                await asyncio.to_thread(self.write_config, slot)
                await self.spawn(
                    slot,
                    fault_plan=(profile.fault_plan
                                if slot.index == 0 else ""))
            async with sampler:
                sampler_task = asyncio.get_running_loop().create_task(
                    sampler.run(stop_sampling))
                expected = len(workload.specs)
                publisher = asyncio.get_running_loop().create_task(
                    self.publish_all(workload.specs,
                                     rate=profile.publish_rate))
                chaos_task = asyncio.get_running_loop().create_task(
                    self._chaos_loop(expected))
                stall_task = asyncio.get_running_loop().create_task(
                    self._stall_loop())
                deadline = time.monotonic() + profile.max_wall
                try:
                    await self._completion_loop(deadline, expected)
                finally:
                    for task in (chaos_task, stall_task, publisher):
                        task.cancel()
                        try:
                            await task
                        except asyncio.CancelledError:
                            pass
                    # a stall window interrupted mid-cancel must not
                    # leave a worker frozen into the census
                    if profile.stalls > 0:
                        for slot in self.slots:
                            if (slot.proc is not None
                                    and slot.proc.returncode is None
                                    and not slot.ready):
                                slot.proc.send_signal(signal.SIGCONT)
                                slot.ready = True
                # quiescent-fleet attribution probe (the hop-ledger
                # reconciliation guard's measurement set)
                await self._attribution_probe(workload.probe_specs)
                # disk profile: seed bit-rot between phases and hold
                # the fleet up until the scrubber accounts for it
                await self._bitrot_phase()
                # let the elected sweeper age out telemetry digests and
                # shared-tier entries before the final census
                await asyncio.sleep(
                    max(profile.telemetry_ttl,
                        2 * profile.gc_interval) + 0.5)
                # incident bundles (ISSUE 18): pull every worker's
                # auto-exported ring while the admin APIs still answer
                self.incidents = await collect_incidents(
                    [self._url(slot, "") for slot in self.live_workers()])
                await self.drain_workers()
                await sampler.sample_once()
                world = await self.collect_world(sampler.scrape_failures)
                self.world = world
                stop_sampling.set()
                await sampler_task
                sampler_task = None
            self.samples = sampler.samples
            report = evaluate(profile, list(self.outcomes.values()),
                              sampler.samples, world)
            report.stats["wall_s"] = round(
                sampler.samples[-1].t_mono - sampler.samples[0].t_mono,
                3) if sampler.samples else 0.0
            return report
        finally:
            if chaos_task is not None and not chaos_task.done():
                chaos_task.cancel()
            if sampler_task is not None and not sampler_task.done():
                stop_sampling.set()
                try:
                    await sampler_task
                except Exception:
                    # unwind path: the sampler's closed-session noise
                    # must never mask the exception that got us here
                    pass
            await self.stop_workers()
            if self._session is not None:
                await self._session.close()
                self._session = None
