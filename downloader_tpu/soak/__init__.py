"""Sustained-load soak harness (ROADMAP item 5; ISSUE 13 tentpole).

Every subsystem built since the fault-tolerance layer has its own
targeted chaos/bench rig — fault plans (``make chaos``), the SIGKILL
crash harness (``make crash``), racing chaos, the fairness bench — but
none of them exercise the subsystems *together* under sustained load,
which is exactly the regime a production fleet lives in.  This package
is that missing rig:

- :class:`~.workload.SoakWorkload` builds a deterministic mixed job
  schedule — cache-hot fan-in, multi-origin racing, segment-manifest
  ingest, multi-tenant BULK pressure with deadlines — against origin
  endpoints the caller provides;
- :class:`~.rig.SoakRig` drives that schedule through a REAL
  multi-worker fleet (``python -m downloader_tpu`` subprocesses over a
  real-wire broker + object store), SIGKILLs and restarts workers on a
  cadence, and tracks per-job time-to-staged from the durable world
  (done markers), not from any worker's memory;
- :class:`~.sampler.GrowthSampler` scrapes ``/metrics`` + ``/readyz``,
  worker RSS, journal size, coordination-store document counts, and
  shared-cache bytes throughout the run;
- :mod:`~.slo` turns the run into hard SLO verdicts: p99
  time-to-staged per priority class, bounded RSS slope, bounded
  journal/coord-store/shared-cache growth (compaction and GC must hold
  the line under duress, not merely exist), zero leaked leases or
  orphan workdirs at drain, zero poison-budget burn, and hop-ledger
  totals that reconcile with stage wall clock.

Profiles: :meth:`SoakProfile.smoke` is the tier-1-safe ≤60 s run
(``make soak-smoke``); :meth:`SoakProfile.full` is the slow-marked
capacity run (``make soak``); :meth:`SoakProfile.degraded` swaps the
SIGKILL chaos for *degraded-world* chaos — a SIGSTOP/SIGCONT worker
stall that overruns the lease TTL (split-brain rehearsal for the
fencing layer) plus a windowed store brownout that must open the
breaker via the slow-call policy (``bench.py --degraded`` emits
``brownout_shed_ms`` / ``split_brain_stale_writes``).  ``bench.py
--soak`` emits ``soak_p99_ms`` / ``soak_rss_slope_mb_per_kjob`` /
``soak_journal_peak_bytes`` from the same rig.  Knobs ``soak.jobs`` /
``soak.workers`` / ``soak.kill_interval`` / ``soak.stalls`` /
``soak.stall_interval`` / ``soak.stall_duration`` override any profile
(see docs/OPERATIONS.md "Capacity & SLOs").

The backends (broker, store, origins) are injected: tests and the
bench own the MiniAmqp/MiniS3/origin servers, the package owns the
workload, the chaos, the sampling, and the verdicts.
"""

from .rig import SoakRig, SoakWorld
from .sampler import GrowthSampler, Sample, parse_prometheus
from .slo import (Guard, SoakReport, brownout_shed_seconds, evaluate,
                  fenced_writes_total, fit_slope, percentile,
                  slow_opens_total)
from .workload import (JobSpec, SoakEndpoints, SoakProfile, SoakWorkload,
                       WorkloadOrigin, download_msg)

__all__ = [
    "SoakRig",
    "SoakWorld",
    "GrowthSampler",
    "Sample",
    "parse_prometheus",
    "Guard",
    "SoakReport",
    "evaluate",
    "fit_slope",
    "percentile",
    "brownout_shed_seconds",
    "slow_opens_total",
    "fenced_writes_total",
    "JobSpec",
    "SoakEndpoints",
    "SoakProfile",
    "SoakWorkload",
    "WorkloadOrigin",
    "download_msg",
]
