"""Growth sampling for soak runs: /metrics, /readyz, RSS, journal,
coordination store, shared cache tier.

One :class:`GrowthSampler` task scrapes every live worker each
``profile.sample_interval`` and appends one :class:`Sample` to its
series.  The series is the input to the bounded-growth SLO guards
(:mod:`~.slo`): journal bytes over time, coordination-document census,
shared-tier footprint, and per-generation RSS — sampled from the
*outside* (the /proc filesystem and the durable store), so a worker
dying mid-run costs a gap in its series, never the series itself.
"""

from __future__ import annotations

import os
import resource
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import aiohttp

# /metrics families the sampler keeps (matched by suffix: the service
# namespace prefix varies with the configured service name)
SCRAPE_SUFFIXES = (
    "journal_bytes",
    "journal_lines",
    "fleet_coord_docs_total",
    "recorder_ring_evictions_total",
    "jobs_shed_total",
    "overload_saturated",
    # degraded-profile observables: when did the slow-call policy open
    # the breaker (brownout_shed_ms), with what attribution, and how
    # many stale cross-worker writes did the fence reject
    "breaker_state",
    "breaker_opened_total",
    "dependency_slow_total",
    "fleet_fenced_writes_total",
    "jobs_parked_total",
)

_PAGE_SIZE = resource.getpagesize()


def parse_prometheus(text: str, suffixes=SCRAPE_SUFFIXES) -> Dict[str, float]:
    """Exposition-format lines -> ``{family{labels}: value}`` for the
    families whose (namespace-stripped) name ends with a suffix."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        family = name_part.split("{", 1)[0]
        if not any(family.endswith(suffix) for suffix in suffixes):
            continue
        try:
            out[name_part] = float(value)
        except ValueError:
            continue
    return out


def rss_bytes(pid: int) -> int:
    """Resident set size of ``pid`` via /proc (0 when unreadable —
    non-Linux hosts or a pid that died between listing and reading)."""
    try:
        with open(f"/proc/{pid}/statm", "r", encoding="ascii") as fh:
            fields = fh.read().split()
        return int(fields[1]) * _PAGE_SIZE
    except (OSError, IndexError, ValueError):
        return 0


def journal_size(path: str) -> int:
    try:
        return os.path.getsize(path)
    except OSError:
        return 0


@dataclass
class Sample:
    """One sampling pass across the whole rig."""

    t_mono: float
    #: jobs resolved (staged or terminal) when the sample was taken —
    #: the x-axis of the RSS-slope fit
    done_jobs: int = 0
    #: worker index -> journal file bytes (direct stat of the file the
    #: scraped ``journal_bytes`` gauge also reads)
    journal_bytes: Dict[int, int] = field(default_factory=dict)
    #: (worker index, generation) -> RSS bytes
    rss_bytes: Dict[tuple, int] = field(default_factory=dict)
    #: coordination-store census by prefix (workers/leases/telemetry),
    #: counted from the durable store (tombstones included: disk
    #: reality, not liveness)
    coord_docs: Dict[str, int] = field(default_factory=dict)
    #: `.fleet-cache/` shared-tier footprint
    shared_cache_bytes: int = 0
    #: worker index -> scraped metric subset (empty when the scrape
    #: failed; failures are tallied on the sampler)
    scraped: Dict[int, Dict[str, float]] = field(default_factory=dict)
    #: worker index -> /readyz HTTP status (0 = unreachable)
    ready_status: Dict[int, int] = field(default_factory=dict)

    def metric(self, index: int, suffix: str,
               labels: str = "") -> Optional[float]:
        """The scraped value whose name ends with ``suffix`` (plus a
        label-selector substring when given)."""
        for name, value in (self.scraped.get(index) or {}).items():
            family = name.split("{", 1)[0]
            if not family.endswith(suffix):
                continue
            if labels and labels not in name:
                continue
            return value
        return None


class GrowthSampler:
    """Periodic sampler over a :class:`~.rig.SoakRig`.

    The rig is duck-typed: it exposes ``live_workers()`` (index,
    generation, pid, health port, journal path), ``resolved_jobs()``,
    and ``store_census()`` (coord docs by prefix + shared-tier bytes).
    """

    def __init__(self, rig, interval: float = 0.5):
        self.rig = rig
        self.interval = max(float(interval), 0.05)
        self.samples: List[Sample] = []
        self.scrape_failures = 0
        self._session: Optional[aiohttp.ClientSession] = None

    async def __aenter__(self) -> "GrowthSampler":
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=3.0))
        return self

    async def __aexit__(self, *_exc) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    async def run(self, stop_event) -> None:
        """Sample until ``stop_event`` is set (one final pass after)."""
        import asyncio

        while not stop_event.is_set():
            await self.sample_once()
            try:
                await asyncio.wait_for(stop_event.wait(), self.interval)
            except asyncio.TimeoutError:
                continue
        await self.sample_once()

    async def sample_once(self) -> Sample:
        sample = Sample(t_mono=time.monotonic(),
                        done_jobs=self.rig.resolved_jobs())
        for worker in self.rig.live_workers():
            sample.journal_bytes[worker.index] = journal_size(
                worker.journal_path)
            rss = rss_bytes(worker.pid)
            if rss:
                sample.rss_bytes[(worker.index, worker.generation)] = rss
            await self._scrape(worker, sample)
        try:
            docs, shared = await self.rig.store_census()
            sample.coord_docs = docs
            sample.shared_cache_bytes = shared
        except Exception:
            # the store census shares the staging store with the
            # workload: a transient listing failure is a gap, not a
            # soak failure (the guards read peaks over many samples)
            if self.samples:
                sample.coord_docs = dict(self.samples[-1].coord_docs)
                sample.shared_cache_bytes = \
                    self.samples[-1].shared_cache_bytes
        self.samples.append(sample)
        return sample

    async def _scrape(self, worker, sample: Sample) -> None:
        base = f"http://127.0.0.1:{worker.health_port}"
        try:
            async with self._session.get(base + "/metrics") as resp:
                text = await resp.text()
            sample.scraped[worker.index] = parse_prometheus(text)
            async with self._session.get(base + "/readyz") as resp:
                await resp.read()
                sample.ready_status[worker.index] = resp.status
        except (aiohttp.ClientError, OSError, RuntimeError):
            # a worker killed between listing and scraping (tallied and
            # judged against the kill count by the SLO layer) — or the
            # session already closed during an exception unwind, which
            # must not mask the original error
            sample.ready_status[worker.index] = 0
            self.scrape_failures += 1
