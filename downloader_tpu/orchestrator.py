"""Job orchestrator: consume download jobs, run the stage pipeline, publish
convert jobs.

Capability-equivalent to /root/reference/lib/main.js:40-205:

- consumes ``v1.download`` (lib/main.js:172), decodes protobuf ``Download``
  (lib/main.js:63)
- emits status ``DOWNLOADING`` (=2) on receipt (lib/main.js:68)
- tracks active jobs for the health endpoint (lib/main.js:70-73) — with the
  reference's ``activeJobs.slice`` no-op bug fixed (lib/main.js:169; see
  SURVEY.md §7 step 6): completed jobs are actually removed here
- per-job EventEmitter registered in an emitter table (lib/main.js:26,81)
- loads the stage plugins dynamically by name and validates the contract
  (lib/main.js:99-115)
- idempotency probe against ``triton-staging/<jobId>/original/done``
  (lib/main.js:119-124): if present, skip the stages but still publish the
  convert message (lib/main.js:153-167)
- sequential stage loop threading ``last_stage`` (lib/main.js:126-140)
- error policy: ``ERRDLSTALL`` -> ack (drop job) (lib/main.js:144-146);
  any other stage error -> status ``ERRORED`` (=6) + nack for redelivery
  (lib/main.js:148-150)
- publishes protobuf ``Convert`` to ``v1.convert`` then acks
  (lib/main.js:157-168)
"""

from __future__ import annotations

import asyncio
import os
import shutil
import time
import uuid
from datetime import datetime, timezone
from typing import Dict, List, Optional

from . import control, schemas
from .control.cancel import CancelToken, JobCancelled
from .control.journal import JobJournal, recovery_counters
from .control.registry import JobRecord, JobRegistry
from .control.overload import OverloadController
from .control.scheduler import (PriorityScheduler, RunSlot,
                                aging_from_config, backlog_from_config,
                                priority_name, priority_rank)
from .control.slo import SloTracker
from .control.tenancy import TenantTable
from .fleet.controller import PlacementController
from .fleet.plane import FleetPlane, resolve_worker_id
from .fleet.router import ContentRouter, route_key_for
from .incident.bundle import TRIGGER_BREACH, IncidentStore, build_bundle
from .mq.base import Delivery, MessageQueue
from .platform import faults
from .platform.config import cfg_get
from .platform.errors import (OPEN_DISK, PERMANENT, POISON, BreakerBoard,
                              Retrier, classify)
from .platform.faults import FaultInjector
from .platform.logging import Logger, get_logger
from .platform.metrics import Metrics
from .platform.obs import (DEFAULT_EVENT_LIMIT, DEFAULT_LAG_INTERVAL,
                           DEFAULT_PROFILE_INTERVAL, LoopLagMonitor,
                           TransferProfiler)
from .platform.telemetry import NullTelemetry, Telemetry
from .platform.tracing import (NullTracer, Tracer, format_traceparent,
                               parse_traceparent)
from .stages.base import STAGES, Job, StageContext, load_stages
from .stages.download import job_download_dir
from .stages.streaming import (PIPELINE_STAGE, pipeline_mode,
                               run_streaming_job)
from .stages.upload import STAGING_BUCKET, done_marker_name
from .store.base import ObjectNotFound, ObjectStore
from .store.cache import ContentCache
from .store.scrub import Scrubber, verify_landed
from .utils import EventEmitter, utcnow_iso as _utcnow_iso


def _submission_age_seconds(created_at: str) -> float:
    """Seconds since the submitter stamped ``Download.created_at``.

    Anchors ``ttl_seconds`` to the SUBMISSION, not this delivery's
    receipt: a shed/parked/nacked BULK job keeps the same created_at on
    every redelivery, so its deadline genuinely elapses instead of
    resetting each cycle.  Absent/unparseable stamps (and clock skew
    that would make the age negative) anchor at receipt — the
    conservative pre-anchoring behavior.
    """
    if not created_at:
        return 0.0
    try:
        stamp = datetime.fromisoformat(created_at.replace("Z", "+00:00"))
        if stamp.tzinfo is None:
            stamp = stamp.replace(tzinfo=timezone.utc)
        return max(
            (datetime.now(timezone.utc) - stamp).total_seconds(), 0.0
        )
    except ValueError:
        return 0.0


class _RecordingTelemetry:
    """Per-job telemetry facade: forwards to the real client while
    sampling progress percent into the job's registry record, so
    ``GET /v1/jobs/{id}`` shows live progress without a new event path."""

    def __init__(self, inner: Telemetry, record: JobRecord):
        self._inner = inner
        self._record = record

    async def emit_status(self, media_id: str, status: int) -> None:
        await self._inner.emit_status(media_id, status)

    async def emit_progress(self, media_id: str, status: int,
                            percent: int) -> None:
        if media_id == self._record.job_id:
            self._record.note_progress(percent)
        await self._inner.emit_progress(media_id, status, percent)


class Orchestrator:
    def __init__(
        self,
        config,
        mq: MessageQueue,
        store: ObjectStore,
        telemetry: Optional[Telemetry] = None,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        logger: Optional[Logger] = None,
        stages: Optional[List[str]] = None,
        prefetch: Optional[int] = None,
        poison_threshold: int = 5,
        cache: Optional[ContentCache] = None,
        admission_timeout: float = 30.0,
        fleet: Optional[FleetPlane] = None,
        worker_id: Optional[str] = None,
    ):
        self.config = config
        self.mq = mq
        self.store = store
        self.telemetry = telemetry or NullTelemetry()
        self.metrics = metrics
        self.tracer = tracer or NullTracer()
        # worker identity (fleet/plane.py): bound into the ROOT logger
        # context — every log line this orchestrator (and its per-job
        # child loggers) emits carries workerId, so a fleet's merged
        # log stream joins on (traceId, workerId)
        self.worker_id = worker_id or resolve_worker_id(config)
        self.logger = (logger or get_logger("orchestrator")).child(
            workerId=self.worker_id
        )
        self.stage_names = stages or list(STAGES)
        # Consumer prefetch = max concurrently-processed jobs, now
        # configurable (MAX_CONCURRENT_JOBS / instance.max_concurrent_jobs)
        # instead of hardcoded.  The default of 2 resolves BASELINE.md's
        # ``new AMQP(addr, 1, 2, prom)`` question (lib/main.js:46):
        # triton-core's AMQP signature is (host, connections, prefetch,
        # prom) — one connection (we likewise hold one job connection;
        # telemetry rides its own, app.py), and a consumer prefetch of 2:
        # up to two deliveries in flight, processed CONCURRENTLY (both
        # backends dispatch one handler task per delivery), matching the
        # reference's async consumer behavior under the same qos.  See
        # PARITY.md "AMQP constructor constants".  Fan-in deployments
        # raise it: with the content cache, same-content jobs coalesce
        # onto one fetch, so extra in-flight jobs are nearly free.
        if prefetch is None:
            raw = os.environ.get("MAX_CONCURRENT_JOBS") or cfg_get(
                config, "instance.max_concurrent_jobs", 2
            )
            try:
                prefetch = int(raw)
            except (TypeError, ValueError):
                raise ValueError(
                    f"max_concurrent_jobs must be an integer, got {raw!r}"
                ) from None
        if prefetch < 1:
            raise ValueError(f"max_concurrent_jobs must be >= 1, got {prefetch}")
        self.prefetch = prefetch

        # stage dispatch mode (stages/streaming.py): "streaming" (the
        # default) overlaps download/filter/upload per file for the
        # standard three-stage chain; "barrier" (instance.pipeline /
        # PIPELINE_MODE) keeps the exact sequential stage loop.  Custom
        # stage chains (e.g. the config-gated upscale stage) always run
        # the barrier loop — the streaming runner models only the
        # default download -> process -> upload topology.
        self.pipeline_mode = pipeline_mode(config)
        self.streaming_enabled = (
            self.pipeline_mode == "streaming"
            and self.stage_names == list(STAGES)
        )

        # control plane (control/): every delivery is registered at
        # receipt and steered through the lifecycle state machine;
        # admitted jobs take a run slot from the priority scheduler.
        # scheduler_backlog > 0 widens the consumer prefetch past the run
        # slots so the scheduler has deliveries to reorder (default 0 =
        # exact pre-control-plane behavior).
        # crash-safe durability (control/journal.py): an append-only
        # journal under the work dir records lifecycle transitions,
        # settle modes, and retry counters, and start() replays it —
        # so a SIGKILL costs at most one in-flight attempt's incremental
        # work, never the retry schedule and never disk.
        self._download_root = os.path.dirname(
            job_download_dir(config, "_probe")
        )
        self.journal = JobJournal.from_config(
            config, self._download_root, logger=self.logger
        )
        # populated by start()'s reconciliation: the /readyz "recovery"
        # block + the jobs_recovered_total attribution
        self.recovery: Optional[dict] = None
        # job_id -> {"cancelled": bool, "reason": str} for recovered jobs
        # whose redelivery has not arrived yet (the replay window)
        self._recovered: Dict[str, dict] = {}
        self._recovery_watchers: List[asyncio.Task] = []
        # fleet-settled placeholder reconciliation (the soak harness
        # flushed this out): in a multi-worker fleet, a killed worker's
        # unacked delivery is redelivered to a PEER — the restarted
        # worker's recovery placeholder then waits for a redelivery
        # that will NEVER arrive (the peer acked it), parking a ghost
        # record and keeping its "resumable" workdir until
        # journal.tombstone_ttl (a day).  This loop probes the durable
        # done marker for waiting placeholders every
        # ``journal.staged_probe_interval`` seconds (0 = off) and
        # retires the already-staged ones DONE, sweeping their workdirs.
        self._staged_probe_interval = float(cfg_get(
            config, "journal.staged_probe_interval", 30.0))
        self._staged_probe_task: Optional[asyncio.Task] = None
        # detached per-job trace-digest publishes (fleet/plane.py
        # publish_telemetry): fired after settle so a coordination-store
        # round trip never delays an ack; drained at shutdown
        self._telemetry_tasks: "set[asyncio.Task]" = set()
        self.registry = JobRegistry(
            metrics=metrics, logger=self.logger,
            recorder_events=int(cfg_get(
                config, "obs.recorder_events", DEFAULT_EVENT_LIMIT
            )),
            worker_id=self.worker_id,
            journal=self.journal,
            # per-hop transfer attribution (platform/obs.py HopLedger):
            # on by default, `obs.hop_ledger: false` is the bench A-B leg
            hop_ledger=bool(cfg_get(config, "obs.hop_ledger", True)),
        )
        # runtime introspection (platform/obs.py): loop-lag sampling
        # into /metrics, and the transfer profiler feeding throughput /
        # stall_suspect events into each RUNNING job's flight recorder
        self.loop_monitor = LoopLagMonitor(
            metrics=metrics, logger=self.logger,
            interval=float(cfg_get(
                config, "obs.loop_lag_interval", DEFAULT_LAG_INTERVAL
            )),
        )
        self.profiler = TransferProfiler(
            self.registry, logger=self.logger,
            interval=float(cfg_get(
                config, "obs.profile_interval", DEFAULT_PROFILE_INTERVAL
            )),
        )
        # multi-tenant overload control (control/tenancy.py +
        # control/overload.py): the tenant table resolves
        # ``Download.tenant`` and holds per-tenant weights / concurrency
        # caps / byte quotas; the scheduler apportions run slots across
        # tenants by weighted-fair stride within each priority class.
        # With no ``tenants`` config every delivery is the "default"
        # tenant and the scheduler behaves exactly as before.
        self.tenants = TenantTable(config, logger=self.logger)
        # in-process SLO accounting (control/slo.py, ``slo.*``): every
        # settled delivery classified against its priority class's (and
        # optionally its tenant's) time-to-staged objective; burn rates
        # and error-budget remaining ride /metrics, /readyz, and the
        # fleet heartbeat digest.  None = ``slo.enabled: false``.
        self.slo = SloTracker.from_config(
            config, tenant_names=self.tenants.names())
        self.scheduler = PriorityScheduler(
            prefetch, aging_seconds=aging_from_config(config),
            tenants=self.tenants,
        )
        self.consumer_prefetch = prefetch + backlog_from_config(config)
        # intake pause (POST /v1/intake/pause | /v1/drain): stop pulling
        # deliveries without dropping in-flight work; /readyz -> 503
        self.intake_paused = False
        # telemetry status emitted for a cancelled job: CANCELLED (=7) by
        # default; config `control.errored_on_cancel: true` keeps legacy
        # consumers that only know the reference's enum range on ERRORED
        self._cancel_status = schemas.TelemetryStatus.Value(
            "ERRORED"
            if cfg_get(config, "control.errored_on_cancel", False)
            else "CANCELLED"
        )

        # content-addressed staging cache (store/cache.py): shared with
        # the download stage via stage_resources, consulted by the
        # admission gate below.  None = disabled (the config default).
        self.cache = cache if cache is not None else ContentCache.from_config(
            config, logger=self.logger
        )
        if self.cache is not None and metrics is not None:
            self.cache.metrics = metrics
        # how long admission may hold a job waiting for cache-volume disk
        # headroom before letting it proceed (the download stage's own
        # ensure_disk_space preflight still fails loudly if truly full)
        self.admission_timeout = admission_timeout
        # disk-full graceful degradation (ISSUE 20): the cache's
        # min_free_bytes discipline extended to the WORKDIR volume —
        # ``download.min_free_bytes`` is the free-space floor admission
        # holds for, ``download.reserve_bytes`` a per-job preflight
        # reservation on top of it.  Both default 0 = off (exactly the
        # prior behavior).  A deadline-forced admission that still
        # fails the workdir floor force-opens the store breaker with
        # the ``disk`` reason (surfaced on /readyz + the fleet
        # overview), because eviction cannot reclaim workdir space.
        self.workdir_min_free = int(cfg_get(
            config, "download.min_free_bytes", 0))
        self.workdir_reserve = int(cfg_get(
            config, "download.reserve_bytes", 0))

        # (reference EmitterTable / activeJobs, lib/main.js:26,34)
        self.emitter_table: Dict[str, EventEmitter] = {}
        self.active_jobs: List[dict] = []

        # shared across every job's StageContext: stage-memoized resources
        # (e.g. the download stage's long-lived DHT node) and their
        # teardown callables, run once at shutdown
        self.stage_resources: dict = {}
        self.stage_cleanups: list = []
        # the download stage probes/fills the same cache instance the
        # admission gate watches (None = disabled; the stage respects it)
        self.stage_resources["content_cache"] = self.cache

        # poison-job guard: the reference nacks failed jobs forever
        # (lib/main.js:148-150), which on RabbitMQ without a dead-letter
        # policy hot-loops a deterministically-failing job at the head of
        # the queue.  After this many failures of one job in this process,
        # drop it (ack + ERRORED) instead of redelivering.  0 disables.
        self.poison_threshold = poison_threshold
        self._failure_counts: Dict[str, int] = {}

        # dependency fault tolerance (platform/errors.py): per-dependency
        # circuit breakers consulted at admission (an open staging-store
        # or convert-publish breaker parks intake instead of burning the
        # poison budget) and a retry executor shared with the stages via
        # stage_resources, so every seam — store puts, the idempotency
        # probe, convert publish, HTTP fetch — rides the same
        # config-driven policies (``retry.<dependency>`` /
        # ``breakers.<dependency>``).
        self.breakers = BreakerBoard(config, metrics=metrics,
                                     logger=self.logger)
        self.retrier = Retrier(config, breakers=self.breakers,
                               metrics=metrics, logger=self.logger)
        self.stage_resources["retrier"] = self.retrier

        # fleet coordination plane (fleet/): worker registry heartbeats,
        # lease-based cross-worker singleflight, and the shared cache
        # tier.  None (the default) = single-worker posture, zero cost.
        # The download stage consults the plane through stage_resources
        # before any origin fetch; the registry handle lets it park a
        # lease-waiting job in the control plane's PARKED state.
        # the shared per-origin throughput table, created eagerly so the
        # fleet plane can share it and boot seeding has a target before
        # the first download stage runs (origins/plan.py lazily shares
        # the same instance through stage_resources)
        from .origins.plan import OriginHealth
        self.origin_health = OriginHealth.shared(self.stage_resources,
                                                 config)
        self.fleet = fleet if fleet is not None else FleetPlane.from_config(
            config, worker_id=self.worker_id, store=store,
            metrics=metrics, logger=self.logger, retrier=self.retrier,
            payload_fn=self.autoscale_signals,
            digest_fn=self.slo_digest,
            origin_fn=self.origin_health.snapshot,
        )
        if self.fleet is not None and self.fleet.payload_fn is None:
            # a plane built by hand (tests/bench) still heartbeats the
            # autoscale trio once an orchestrator adopts it
            self.fleet.payload_fn = self.autoscale_signals
        if self.fleet is not None and self.fleet.digest_fn is None:
            # same adoption for the SLO/health digest the fleet
            # overview aggregates
            self.fleet.digest_fn = self.slo_digest
        if self.fleet is not None and self.fleet.origin_fn is None:
            # and for the fleet-shared origin-health table
            self.fleet.origin_fn = self.origin_health.snapshot
        self.stage_resources["fleet_plane"] = self.fleet
        # fleet data plane v2 (ISSUE 17): the content router steers
        # same-content deliveries to the current lease holder at
        # admission, and the elected placement controller closes the
        # overview->plan loop.  Both are None without a fleet — the
        # lone-worker admission path is untouched.
        self.router = ContentRouter.from_config(
            config, self.fleet, self.tenants,
            metrics=metrics, logger=self.logger,
        )
        self.controller = PlacementController.from_config(
            config, self.fleet, metrics=metrics, logger=self.logger,
        )
        # incident plane (ISSUE 18): bounded ring of exported forensic
        # bundles, fed by auto-export when a settle burns error budget
        # and by the admin API/CLI on demand.  None (``incident.enabled:
        # false``) keeps the settle path exactly as before.
        self.incidents = IncidentStore.from_config(
            config, metrics=metrics, logger=self.logger,
        )
        self.stage_resources["job_registry"] = self.registry
        # the stages stack each job's per-tenant byte quota under the
        # service-wide rate limiter through this shared table
        self.stage_resources["tenant_table"] = self.tenants
        # saturation-aware shedding (control/overload.py): samples the
        # autoscale trio + event-loop lag; while saturated, BULK
        # deliveries are parked+nacked at admission (never FAILED, never
        # charged poison) so HIGH/NORMAL time-to-staged survives the
        # worker's own overload.  ``overload.enabled: false`` removes it.
        self.overload = OverloadController.from_config(
            config, self.autoscale_signals,
            lambda: getattr(self.loop_monitor, "last_lag", None),
            metrics=metrics, logger=self.logger,
        )
        # integrity scrubber (store/scrub.py): rate-limited background
        # re-hash of cache entries, co-located shared-tier objects, and
        # staged workdir outputs against their landing digests —
        # repairing from healthy replicas (always into a fresh inode)
        # and quarantining the rest.  ``scrub.enabled: false`` removes
        # it; its cumulative verdicts ride the SLO digest onto the
        # fleet overview.
        self.scrubber = Scrubber.from_config(
            config, cache=self.cache, fleet=self.fleet,
            workdir_root=self._download_root,
            metrics=metrics, logger=self.logger,
        )
        # autoscale signal trio on /metrics: the same snapshot the fleet
        # heartbeat carries (ROADMAP item 5's fleet-facing contract)
        if metrics is not None:
            metrics.bind_autoscale(self.autoscale_signals)
            metrics.bind_tenants(self.tenants.names(),
                                 self.registry.tenant_queue_depths)
            if self.slo is not None:
                # slo_burn_rate{class,window} + slo_error_budget_
                # remaining{class}: the live SLO plane on /metrics
                metrics.bind_slo(self.slo)
            if self.fleet is not None:
                # overview staleness: steady state must sit under 2x
                # the heartbeat interval (bench v20 guards it)
                metrics.bind_overview_age(self.fleet.overview_age)
            # per-tenant staging *footprint* (ROADMAP item 5 remaining
            # depth): live workdir bytes per tenant — quotas today cover
            # transfer rate; this gauge is the disk-accounting half
            # (observability only, no enforcement yet)
            metrics.bind_tenant_staging(self.tenants.names(),
                                        self.tenant_staging_bytes)
            if self.journal is not None:
                # journal growth gauges (journal_bytes/journal_lines):
                # the bounded-growth signal the soak harness guards —
                # compaction must hold the file O(live jobs)
                metrics.bind_journal(self.journal)
        self._staging_memo = {"at": 0.0, "snap": None, "busy": False}
        # the dependencies whose open breaker pauses intake: everything a
        # job needs to SETTLE (staging writes + convert publish) — origin
        # fetch trouble stays per-job (a broken origin is one job's
        # problem, not the fleet's)
        self.admission_dependencies = ("store", "publish")
        # delayed redelivery (park-then-nack): a transiently-failed
        # delivery holds its unsettled slot for an exponentially-growing
        # pause before the nack, replacing the reference's instant-nack
        # hot loop.  ``retry.redelivery.base: 0`` restores instant nacks.
        self._redeliver_base = float(
            cfg_get(config, "retry.redelivery.base", 0.25)
        )
        self._redeliver_cap = float(
            cfg_get(config, "retry.redelivery.cap", 15.0)
        )

        # deterministic fault injection (platform/faults.py): installed
        # from ``faults.plan`` / env FAULT_PLAN for chaos drills; None —
        # the production default — keeps every seam's hook a no-op.
        self._fault_injector = FaultInjector.from_config(
            config, logger=self.logger
        )
        if self._fault_injector is not None:
            faults.install(self._fault_injector)

        # readiness: True between a successful start() and shutdown()
        # (surfaced by /readyz, health.py)
        self.consuming = False

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Connect and begin consuming (reference lib/main.js:47,172)."""
        # reconcile BEFORE the first delivery can arrive: redeliveries
        # must find their restored retry counters and their placeholder
        # records already in place
        await self._recover()
        await self.mq.connect()
        await self.telemetry.connect()
        # route Convert through a fanout exchange bound to the canonical
        # queue where the backend supports it: the downstream converter
        # consumes the same queue as before, and observers (submit --wait)
        # can tap completion events without stealing deliveries
        try:
            await self.mq.bind_queue(
                schemas.CONVERT_QUEUE, schemas.CONVERT_EXCHANGE
            )
            self._convert_fanout = True
        except NotImplementedError:
            self._convert_fanout = False
        await self.mq.listen(
            schemas.DOWNLOAD_QUEUE, self.processor,
            prefetch=self.consumer_prefetch,
        )
        self.consuming = True
        self.loop_monitor.start()
        self.profiler.start()
        if (self.journal is not None
                and self._staged_probe_interval > 0):
            self._staged_probe_task = asyncio.get_running_loop() \
                .create_task(self._staged_probe_loop())
        if self.overload is not None:
            self.overload.start()
        if self.scrubber is not None:
            self.scrubber.start()
        if self.fleet is not None:
            # join the fleet LAST: by the time peers can route around or
            # toward this worker, it is actually consuming
            await self.fleet.start()
            self.logger.info("joined fleet", workerId=self.worker_id)
            # cold-start head start (ISSUE 17): seed the local origin
            # throughput table from the fleet-shared rows, so the first
            # racing fetch starts from the fleet's observed rates
            # instead of zero.  Best-effort: one bounded read, and any
            # trouble boots cold exactly as before.
            try:
                rows = await self.fleet.fetch_origin_health()
                if rows:
                    seeded = self.origin_health.seed(rows)
                    if seeded:
                        self.logger.info(
                            "seeded origin health from fleet",
                            labels=seeded)
            except asyncio.CancelledError:
                raise
            except Exception as err:
                self.logger.warn("origin-health boot seed failed",
                                 error=str(err)[:200])
            if self.controller is not None:
                # the placement controller only ever acts when this
                # worker wins the election, so every worker starts it
                self.controller.start()
        self.logger.info("successfully connected to queue")

    def _workdir_free_bytes(self) -> Optional[int]:
        """Free bytes on the workdir (download-root) volume, probed at
        the deepest existing ancestor; None when unprobeable — the
        disk gates then stand down rather than block on a blind
        spot."""
        from .utils.disk import free_bytes

        path = self._download_root
        while path and not os.path.isdir(path):
            parent = os.path.dirname(path)
            if parent == path:
                break
            path = parent
        try:
            return free_bytes(path or os.sep)
        except OSError:
            return None

    # -- autoscale signals ----------------------------------------------
    def autoscale_signals(self) -> dict:
        """The scale-out/scale-down trio, one snapshot for BOTH surfaces
        (/metrics gauges and the fleet heartbeat payload): queue depth,
        oldest-queued-job age, and disk headroom on the volumes jobs
        land on (the TIGHTER of cache and workdir volumes when
        caching, the download volume otherwise).
        """
        depth, oldest = self.registry.queued_snapshot()
        workdir_free = self._workdir_free_bytes()
        if self.cache is not None:
            # tightest volume wins: the cache may live on a different
            # volume than the workdirs, and a full WORKDIR volume kills
            # jobs just as surely (the overload controller's
            # disk_headroom shed watches exactly this signal)
            headroom = self.cache.free_disk_bytes()
            if workdir_free is not None:
                headroom = min(headroom, workdir_free)
        else:
            headroom = workdir_free if workdir_free is not None else 0
        return {
            "queue_depth": depth,
            "oldest_queued_seconds": round(oldest, 3),
            "cache_headroom_bytes": headroom,
            "active_jobs": len(self.active_jobs),
        }

    def slo_digest(self) -> dict:
        """The compact SLO/health digest the fleet heartbeat carries
        (fleet/plane.py ``digest_fn``): burn rates + budgets per
        objective, open breakers with reasons, per-hop totals (the
        overview's top-hops + mixed-phase reconcile ratio), and this
        worker's per-tenant queue depths — the fleet-wide tenant
        fairness view is aggregated from exactly these
        (``build_overview``).  Sync and cheap: the SLO snapshot is
        memoized, the rest are dict reads."""
        digest = self.slo.digest() if self.slo is not None else {}
        breakers = getattr(self, "breakers", None)
        if breakers is not None:
            states = breakers.states()
            reasons = breakers.open_reasons()
            open_breakers = {
                dependency: {"state": state,
                             "reason": reasons.get(dependency)}
                for dependency, state in states.items()
                if state != "closed"
            }
            if open_breakers:
                digest["openBreakers"] = open_breakers
        queued = self.registry.tenant_queue_depths()
        if queued:
            digest["tenantQueued"] = queued
        router = getattr(self, "router", None)
        if router is not None and router.last is not None:
            # this worker's last routing action (defer/shed/fairness):
            # the DECISION column on the overview doc / `fleet top`
            digest["lastDecision"] = dict(router.last)
        scrubber = getattr(self, "scrubber", None)
        if scrubber is not None:
            # cumulative scrub verdicts: build_overview sums these
            # fleet-wide (repaired/quarantined climbing = a disk going
            # bad somewhere in the fleet)
            digest["scrub"] = scrubber.snapshot()
        return digest

    async def assemble_trace(self, trace_id: str,
                             remote: bool = True) -> dict:
        """The cross-worker timeline for one trace id (``GET
        /v1/trace/{id}``): local registry segments + tracer spans,
        joined with peer workers' coordination-store digests and live
        admin APIs (control/trace.py).  Coordination trouble degrades
        to the local-only view — never an error."""
        from .control.trace import assemble

        return await assemble(self, trace_id, remote=remote)

    def tenant_staging_bytes(self) -> Dict[str, int]:
        """Live per-tenant staging footprint: bytes on disk under each
        non-terminal job's workdir, attributed to the job's tenant.

        Fed to the ``tenant_staging_bytes`` gauges and ``GET
        /v1/tenants`` — the disk half of per-tenant accounting (quotas
        cover transfer rate only; this is observability, not
        enforcement).  Stale-while-revalidate: callers are sync gauge
        callbacks on the event loop, and the walk stats real workdirs
        (a large torrent is tens of thousands of files — inline it
        would be exactly the loop stall the OverloadController sheds
        on), so a stale snapshot answers immediately and the re-walk
        runs on the executor.  The first call returns ``{}``.
        """
        now = time.monotonic()
        memo = self._staging_memo
        stale = memo["snap"] is None or now - memo["at"] >= 5.0
        if stale and not memo["busy"]:
            memo["busy"] = True
            # capture (job_id, tenant) on the loop side: the walk thread
            # must not touch live registry records
            jobs = []
            seen: set = set()
            for record in self.registry.jobs():
                if record.terminal or record.job_id in seen:
                    continue
                seen.add(record.job_id)
                jobs.append((record.job_id, record.tenant))

            from .utils.disk import dir_bytes

            def _walk() -> None:
                out: Dict[str, int] = {}
                try:
                    for job_id, tenant in jobs:
                        size = dir_bytes(
                            job_download_dir(self.config, job_id))
                        if size:
                            out[tenant] = out.get(tenant, 0) + size
                    memo["snap"] = out
                    memo["at"] = time.monotonic()
                finally:
                    memo["busy"] = False

            try:
                asyncio.get_running_loop().run_in_executor(None, _walk)
            except RuntimeError:
                # no loop (direct sync use in tests): walk inline
                _walk()
        return memo["snap"] or {}

    # -- crash recovery (control/journal.py) ----------------------------
    async def _recover(self) -> None:
        """Startup reconciliation: replay the journal, restore retry
        schedules, open PARKED placeholders for jobs whose redelivery is
        still coming, sweep orphan workdirs, and release any content
        leases a previous incarnation of this worker died holding.

        The outcome is surfaced three ways: the ``recovery`` block on
        ``/readyz``, ``jobs_recovered_total{outcome}``, and a
        ``recovered`` event + flag on each placeholder record.
        """
        if self.journal is None:
            return
        state = await asyncio.to_thread(self.journal.replay)
        live = state.live()
        counters = recovery_counters(state)
        restored = 0
        for job_id, failures in counters.items():
            self._failure_counts[job_id] = failures
            restored += 1
        tombstone_ttl = float(cfg_get(
            self.config, "journal.tombstone_ttl", 86400.0))
        expired: set = set()

        def _retire(job_id: str, why: str) -> None:
            # the redelivery never came (dead-lettered, message TTL,
            # queue purge, completed by a fleet peer): settle-ack the
            # journal so the job stops replaying — and re-counting —
            # on every boot forever
            self.journal.append("settle", job_id, mode="ack", why=why)
            self._failure_counts.pop(job_id, None)
            expired.add(job_id)
            if self.metrics is not None:
                self.metrics.jobs_recovered.labels(
                    outcome="expired").inc()

        for job_id, job in live.items():
            if job.state == control.CANCELLED:
                # an operator-cancelled placeholder from a PREVIOUS
                # recovery window (the CANCELLED transition is journaled,
                # the delivery never settled): the decision is final
                # across any number of restarts — no run placeholder,
                # just the tombstone that settles the eventual
                # redelivery as cancelled the moment it arrives
                if (tombstone_ttl > 0
                        and _submission_age_seconds(job.updated_at)
                        > tombstone_ttl):
                    _retire(job_id, "tombstone_expired")
                    continue
                # no metrics inc here: outcome="cancelled" counted once,
                # when the cancel first settled the placeholder — a
                # crash-looping worker must not re-count the same
                # tombstone every boot
                self._recovered[job_id] = {
                    "cancelled": True,
                    "reason": job.reason or "cancelled",
                    "watcher": None,
                }
                continue
            # the placeholder-retirement clock: recovered_at survives
            # re-registration across boots (clears on adoption/progress),
            # so a placeholder the broker has owed a redelivery for a
            # full TTL is a ghost — retire it instead of parking it,
            # keeping its workdir, and re-counting it at every boot
            if (tombstone_ttl > 0 and job.recovered_at
                    and _submission_age_seconds(job.recovered_at)
                    > tombstone_ttl):
                _retire(job_id, "recovery_expired")
                continue
            record = self.registry.register(
                job_id, job.file_id, priority=job.priority,
                tenant=self.tenants.resolve(job.tenant),
                ttl_seconds=job.ttl_seconds,
                recovered_at=(job.recovered_at or job.updated_at
                              or _utcnow_iso()),
            )
            record.recovered = True
            record.event("recovered", prior_state=job.state,
                         prior_stage=job.stage, failures=job.failures,
                         settle=job.settle)
            if job.failures > 0:
                # GET /v1/jobs answers "how burned is this job's poison
                # budget" before the redelivery even lands
                record.retry = {"why": "recovered",
                                "failures": job.failures}
            self.registry.transition(
                record, control.PARKED,
                reason="recovered: awaiting redelivery",
            )
            watcher = asyncio.create_task(self._watch_recovered(record))
            self._recovered[job_id] = {"cancelled": False, "reason": "",
                                       "watcher": watcher}
            self._recovery_watchers.append(watcher)
            if self.metrics is not None:
                self.metrics.jobs_recovered.labels(outcome="replayed").inc()
        swept, resumed, demoted = await asyncio.to_thread(
            self._sweep_workdirs,
            # cancelled tombstones are never resumable (their workdir,
            # if the kill beat the cancel's own rmtree, is an orphan),
            # and neither are jobs just retired past tombstone_ttl —
            # keeping a retired ghost's workdir would leak it for the
            # whole process lifetime
            {job_id for job_id, job in live.items()
             if job.state != control.CANCELLED and job_id not in expired},
        )
        if self.metrics is not None:
            if swept:
                self.metrics.jobs_recovered.labels(
                    outcome="swept").inc(swept)
            if resumed:
                self.metrics.jobs_recovered.labels(
                    outcome="resumable").inc(resumed)
            if demoted:
                self.metrics.jobs_recovered.labels(
                    outcome="demoted").inc(demoted)
        # compact now that the history is replayed: the journal restarts
        # as one snapshot line of the still-live jobs (self-replaying,
        # so the placeholder lines just appended are part of the basis)
        await asyncio.to_thread(self.journal.compact)
        leases_reclaimed = 0
        if self.fleet is not None:
            try:
                leases_reclaimed = await self.fleet.reclaim_own_leases()
            except Exception as err:
                # coordination trouble degrades, never blocks a boot —
                # the acquire-time own-orphan reclaim still applies
                self.logger.warn("recovery lease reclaim failed",
                                 error=str(err))
        self.recovery = {
            "recoveredJobs": len(live),
            "restoredRetryCounters": restored,
            "sweptWorkdirs": swept,
            "resumableWorkdirs": resumed,
            "demotedOutputs": demoted,
            "tornJournalLines": state.torn_lines,
            "reclaimedLeases": leases_reclaimed,
            "at": _utcnow_iso(),
        }
        if live or swept or state.torn_lines:
            self.logger.info("crash recovery complete", **self.recovery)

    def _sweep_workdirs(self, live_ids: set) -> "tuple[int, int, int]":
        """Reconcile the download root against the journal (thread-side).

        A workdir whose job still expects a redelivery is KEPT — its
        ``.partial``/piece state is content-keyed (validators in
        ``.partial.meta``, SHA-1 piece hashes) so the resumed attempt
        pays only the missing bytes.  Its PROMOTED outputs, though,
        are re-verified against the landing recovery sidecar
        (store/scrub.py): a digest mismatch is the torn-tail crash —
        the rename outlived the data pages — and the output is
        DEMOTED (deleted) so the redelivered job re-fetches instead of
        serving the hole.  Everything else — ack-settled terminal
        jobs, dirs the journal has never heard of — is an orphan and
        is deleted: the journal is authoritative for this root
        (dot-dirs, including the journal's own, are skipped).  Returns
        ``(swept, resumed, demoted)`` counts.
        """
        swept = resumed = demoted = 0
        # service dirs that legitimately live under the download root but
        # are NOT job workdirs: the journal's own dir and a configured
        # content cache (CACHE_DIR/instance.cache.path may point a
        # non-dot-prefixed dir here — sweeping it would silently discard
        # the whole LRU cache at every boot)
        protected = set()
        if self.journal is not None:
            protected.add(os.path.realpath(os.path.dirname(
                self.journal.path)))
        from .store.cache import resolve_cache_path

        # the ONE resolver the cache itself uses — a diverging copy here
        # would eventually sweep the LRU cache as an "orphan"
        protected.add(os.path.realpath(resolve_cache_path(self.config)))
        try:
            entries = os.scandir(self._download_root)
        except OSError:
            return swept, resumed
        with entries:
            for entry in entries:
                if not entry.is_dir(follow_symlinks=False):
                    continue
                if entry.name.startswith("."):
                    continue  # .journal, .cache-style service dirs
                if os.path.realpath(entry.path) in protected:
                    continue
                if entry.name in live_ids:
                    verified, torn = verify_landed(entry.path)
                    if torn:
                        demoted += torn
                        self.logger.warn(
                            "boot recovery: demoted torn outputs for "
                            "re-fetch", workdir=entry.path,
                            demoted=torn, verified=verified)
                    resumed += 1
                    continue
                try:
                    shutil.rmtree(entry.path)
                    swept += 1
                except OSError as err:
                    self.logger.warn("orphan workdir sweep failed",
                                     path=entry.path, error=str(err))
        return swept, resumed, demoted

    async def _watch_recovered(self, record: JobRecord) -> None:
        """Settle a recovered placeholder that is cancelled before its
        redelivery arrives (the cancel-during-reconciliation window).

        The placeholder holds no run slot and no delivery, so nothing
        else will ever settle it: this watcher transitions it to
        CANCELLED, removes the workdir, and leaves a tombstone in
        ``_recovered`` so the eventual redelivery is acked as cancelled
        instead of silently re-running an operator-cancelled job.
        """
        await record.cancel.wait()
        if record.terminal or not (record.state == control.PARKED
                                   and record.recovered):
            return  # adopted by a redelivery first: the normal path owns it
        reason = record.cancel.reason or "cancelled"
        entry = self._recovered.get(record.job_id)
        if entry is not None:
            entry["cancelled"] = True
            entry["reason"] = reason
        self._clear_failures(record.job_id)
        await self._remove_workdir(record.job_id, self.logger)
        record.event("settle", mode="none", why="cancelled_during_recovery",
                     reason=reason)
        self.registry.transition(record, control.CANCELLED, reason=reason)
        if self.metrics is not None:
            self.metrics.jobs_cancelled.inc()
            self.metrics.jobs_recovered.labels(outcome="cancelled").inc()

    async def _staged_probe_loop(self) -> None:
        # a peer SETTLING a job publishes its telemetry digest — the
        # exact moment a done marker may have appeared — so the probe
        # rides the fleet's telemetry watch and wakes on peer activity;
        # the configured interval survives as the bounded long-poll cap
        # and as the whole cadence on the degraded path (no fleet,
        # watch refused, coord brownout): the PR 9 contract.
        watch = None
        try:
            while True:
                if (watch is None and self.fleet is not None
                        and self.fleet.watch_enabled):
                    watch = self.fleet.telemetry_watch()
                if watch is None:
                    if self.fleet is not None:
                        self.fleet._note_watch_wakeup("poll")
                    await asyncio.sleep(self._staged_probe_interval)
                else:
                    try:
                        events = await watch.next(
                            self._staged_probe_interval)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        watch.close()
                        watch = None
                        self.fleet._note_watch_wakeup("poll")
                        await asyncio.sleep(self._staged_probe_interval)
                        events = []
                    else:
                        self.fleet._note_watch_wakeup(
                            "event" if events else "timeout")
                try:
                    if self._recovered:
                        await self._probe_recovered_staged()
                    await self._sweep_peer_staged_workdirs()
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    # store trouble: the placeholders keep waiting, the
                    # next pass probes again — degradation, never a
                    # crash
                    self.logger.warn(
                        "recovered-placeholder probe failed",
                        error=str(err))
        finally:
            if watch is not None:
                watch.close()

    async def _probe_recovered_staged(self) -> int:
        """Retire PARKED recovery placeholders whose content the fleet
        already staged (done marker present).

        The placeholder's redelivery went to a peer worker (our unacked
        delivery requeued when the previous incarnation died, and
        another consumer won it) — the peer ran the job and acked it,
        so no redelivery is owed to US.  Without this probe the
        placeholder parks until ``journal.tombstone_ttl`` and its
        workdir leaks for just as long.  If a redelivery *does* still
        arrive after retirement (a second requeue), the normal intake
        path's idempotency probe acks it as already staged — retiring
        here is safe either way.
        """
        retired = 0
        for record in self.registry.jobs(control.PARKED):
            if not (record.recovered
                    and (record.reason or "").startswith("recovered")):
                continue
            try:
                await self.store.get_object(
                    STAGING_BUCKET, done_marker_name(record.job_id))
            except ObjectNotFound:
                continue
            except Exception:
                continue  # store trouble: decide nothing this pass
            if not (record.state == control.PARKED and record.recovered
                    and (record.reason or "").startswith("recovered")):
                # the probe's await yielded the loop: a redelivery
                # adopted the placeholder (or a cancel settled it)
                # while we were reading the marker — the normal intake
                # path owns the record now, and its idempotency probe
                # will make the same already-staged call
                continue
            entry = self._recovered.pop(record.job_id, None)
            if entry is not None and entry.get("watcher") is not None:
                entry["watcher"].cancel()
            self._clear_failures(record.job_id)
            record.event("settle", mode="none", why="staged_elsewhere")
            self._journal_settle(record, "ack", "staged_elsewhere")
            self.registry.transition(
                record, control.DONE,
                reason="recovered: staged by a fleet peer")
            await self._remove_workdir(record.job_id, self.logger)
            if self.metrics is not None:
                self.metrics.jobs_recovered.labels(
                    outcome="staged_elsewhere").inc()
            self.logger.info(
                "recovered placeholder already staged by a peer",
                jobId=record.job_id)
            retired += 1
        return retired

    async def _sweep_peer_staged_workdirs(self) -> int:
        """Remove resumable workdirs nobody is coming back for: the
        job's delivery was park-then-NACKED away (transient failure,
        open breaker, overload shed) and a PEER worker completed it.

        A nacked job's workdir is deliberately kept so a redelivery to
        US can resume its ``.partial``/piece state — but when the
        redelivery lands on a peer (the broker owes it to *a* consumer,
        not to this one) and that peer seals the done marker, no
        resume is ever owed here: any late redelivery acks at the
        idempotency probe without touching these bytes.  Flushed out
        by the degraded soak: a breaker-shed job migrating to the
        healthy worker left its partial workdir behind forever.
        """
        swept = 0
        for record in self.registry.jobs(control.FAILED):
            latest = self.registry.get(record.job_id)
            if latest is not record or not latest.terminal:
                continue  # a live redelivery owns this job id right now
            workdir = job_download_dir(self.config, record.job_id)
            if not os.path.isdir(workdir):
                continue
            try:
                await self.store.get_object(
                    STAGING_BUCKET, done_marker_name(record.job_id))
            except ObjectNotFound:
                continue  # not staged anywhere yet: keep the resume state
            except Exception:
                continue  # store trouble: decide nothing this pass
            # re-check after the await: a redelivery may have arrived
            # and re-registered the job while the marker read yielded
            if self.registry.get(record.job_id) is not record:
                continue
            await self._remove_workdir(record.job_id, self.logger)
            record.event("workdir_swept", why="staged_elsewhere")
            if self.metrics is not None:
                self.metrics.jobs_recovered.labels(
                    outcome="staged_elsewhere").inc()
            self.logger.info(
                "swept workdir of a job a peer already staged",
                jobId=record.job_id)
            swept += 1
        return swept

    # -- control plane: intake steering --------------------------------
    async def pause_intake(self) -> None:
        """Stop pulling deliveries; in-flight jobs keep running.

        The prefetch window's unsettled deliveries stay assigned to this
        worker (they are already in ``processor``); nothing new arrives
        until :meth:`resume_intake`.  ``/readyz`` answers 503 while
        paused so load-aware orchestrators stop routing to the replica.
        """
        if self.intake_paused:
            return
        # consumers first, flag after: if the broker-side cancel fails
        # (AMQP stop_consuming propagates protocol errors on a healthy
        # connection), the pause must FAIL — reporting "paused" while
        # deliveries still flow would make /v1/drain lie to operators
        await self.mq.stop_consuming()
        self.intake_paused = True
        self.logger.info("intake paused")

    async def resume_intake(self) -> None:
        if not self.intake_paused:
            return
        await self.mq.resume_consuming()
        self.intake_paused = False
        self.logger.info("intake resumed")

    async def drain(self, grace_seconds: float = 30.0) -> bool:
        """Pause intake and wait (bounded) for in-flight jobs to settle.

        The programmatic form of :meth:`shutdown`'s grace loop, minus the
        teardown: the service stays up (resumable) after a drain.
        Returns True when everything settled within the grace period.
        """
        await self.pause_intake()
        try:
            async with asyncio.timeout(grace_seconds):
                while self.active_jobs:
                    await asyncio.sleep(0.05)
        except TimeoutError:
            self.logger.warn("drain grace period expired with active jobs",
                             active=len(self.active_jobs))
            return False
        return True

    async def shutdown(self, grace_seconds: float = 30.0) -> None:
        """Stop consuming; wait for in-flight jobs to settle.

        The reference's termination closure refuses a clean exit while jobs
        are active (lib/main.js:197-204); here we stop pulling new work
        first, then actually drain the in-flight jobs.
        """
        self.consuming = False
        try:
            await self.mq.stop_consuming()
        except Exception as err:
            # shutdown is best-effort here: close() below tears down the
            # connection (and any consumer with it) regardless
            self.logger.warn("stop_consuming failed during shutdown",
                             error=str(err))
        try:
            async with asyncio.timeout(grace_seconds):
                while self.active_jobs:
                    await asyncio.sleep(0.05)
        except TimeoutError:
            self.logger.warn(
                "shutdown grace period expired with active jobs",
                active=len(self.active_jobs),
            )
        await self.profiler.stop()
        await self.loop_monitor.stop()
        if self.overload is not None:
            await self.overload.stop()
        if self.scrubber is not None:
            await self.scrubber.stop()
        if self.controller is not None:
            # stop planning before leaving the fleet: a departing
            # worker must not publish a plan mid-deregistration
            await self.controller.stop()
        if self.fleet is not None:
            # leave the fleet before the backends close: deregistration
            # and lease release still have a live store to write to
            await self.fleet.stop()
        if self._staged_probe_task is not None:
            self._staged_probe_task.cancel()
            try:
                await self._staged_probe_task
            except asyncio.CancelledError:
                pass
            self._staged_probe_task = None
        for watcher in self._recovery_watchers:
            watcher.cancel()
        if self._recovery_watchers:
            await asyncio.gather(*self._recovery_watchers,
                                 return_exceptions=True)
            self._recovery_watchers.clear()
        if self._telemetry_tasks:
            # give in-flight digest publishes a moment (they are one
            # coordination put), then cut them — digests are best-effort
            pending = list(self._telemetry_tasks)
            try:
                async with asyncio.timeout(2):
                    await asyncio.gather(*pending, return_exceptions=True)
            except TimeoutError:
                for task in pending:
                    task.cancel()
                await asyncio.gather(*pending, return_exceptions=True)
            self._telemetry_tasks.clear()
        await self.mq.close()
        await self.telemetry.close()
        if self.journal is not None:
            # synchronous flush: a clean shutdown's journal is exact
            await asyncio.to_thread(self.journal.close)
        for cleanup in self.stage_cleanups:
            try:
                await cleanup()
            except Exception as err:
                self.logger.warn("stage cleanup failed", error=str(err))
        self.stage_cleanups.clear()
        self.stage_resources.clear()
        # remove only OUR injector: a test that installed its own plan
        # around this orchestrator keeps it
        if self._fault_injector is not None:
            faults.uninstall(self._fault_injector)

    # ------------------------------------------------------------------
    async def processor(self, delivery: Delivery) -> None:
        """Handle one ``v1.download`` delivery (reference lib/main.js:62-170)."""
        try:
            msg = schemas.decode(schemas.Download, delivery.body)
        except Exception as err:
            # malformed delivery: ack + count instead of letting the
            # decode error escape the handler — both MQ backends would
            # nack-requeue it and hot-loop forever (the poison guard
            # needs a job id a body that can't decode can never provide)
            self.logger.error("dropping malformed delivery",
                              error=str(err), bytes=len(delivery.body))
            if self.metrics is not None:
                self.metrics.jobs_failed.labels(reason="malformed").inc()
            await delivery.ack()
            return
        file_id = msg.media.creator_id  # (reference lib/main.js:64)
        job_id = msg.media.id           # (reference lib/main.js:65)
        priority = priority_name(msg.priority)
        # tenant identity (control/tenancy.py): absent/empty and
        # unconfigured names both resolve to "default" (the
        # unknown-priority -> NORMAL posture), so old producers and
        # un-onboarded submitters get exactly the pre-tenancy behavior
        tenant = self.tenants.resolve(getattr(msg, "tenant", ""))
        ttl_seconds = float(getattr(msg, "ttl_seconds", 0.0) or 0.0)

        if self.metrics is not None:
            self.metrics.jobs_consumed.inc()

        job_entry = {"cardId": file_id, "jobId": job_id}

        # correlation ids, allocated at RECEIPT: the job span's W3C
        # trace/span id (inheriting the submitter's trace when the
        # delivery carries a traceparent header) goes into the child
        # logger's bindings, the registry record, and — below — the span
        # itself, so a log line, an OTLP span, and a flight-recorder
        # timeline entry for the same job are joinable on one id
        remote = parse_traceparent(delivery.headers.get("traceparent"))
        trace_id = remote.trace_id if remote is not None else uuid.uuid4().hex
        span_id = uuid.uuid4().hex[:16]
        child = self.logger.child(jobId=job_id, fileId=file_id,
                                  traceId=trace_id, spanId=span_id)
        if tenant != "default":
            # the tenant joins the job's log context only when one is
            # actually named — single-tenant log streams stay unchanged
            child = child.child(tenant=tenant)

        # registered + counted from RECEIPT: a job waiting in admission
        # or the priority queue is visible to /health, GET /v1/jobs,
        # drain, and shutdown (pre-control-plane blind spot).  All
        # bookkeeping after this point is undone in the finally, so a
        # failure anywhere can't leak the gauge or the active-jobs entry.
        # crash-recovery adoption (control/journal.py): a redelivery for
        # a job the startup replay knows about takes over its PARKED
        # placeholder — same record, same cancel token, restored retry
        # schedule — so the attempt resumes its history instead of
        # starting cold.  A placeholder cancelled during the replay
        # window leaves a tombstone: the redelivery is settled as
        # cancelled the moment it arrives (an operator decision is
        # final, PR 7's cancel-while-PARKED posture).
        recovered_entry = self._recovered.pop(job_id, None)
        record = None
        if recovered_entry is not None:
            watcher = recovered_entry.get("watcher")
            if watcher is not None and not watcher.done():
                watcher.cancel()
            record = self.registry.adopt_recovered(
                job_id, file_id, priority=priority, tenant=tenant,
                ttl_seconds=ttl_seconds,
            )
            if record is not None and self.metrics is not None:
                self.metrics.jobs_recovered.labels(outcome="adopted").inc()
        if record is None:
            record = self.registry.register(job_id, file_id,
                                            priority=priority,
                                            tenant=tenant,
                                            ttl_seconds=ttl_seconds)
            if recovered_entry is not None:
                # the placeholder is gone (cancelled during the replay
                # window settled it) but the delivery is still this
                # job's: mark provenance on the fresh record
                record.recovered = True
        if recovered_entry is not None and recovered_entry.get("cancelled"):
            record.cancel.cancel(recovered_entry.get("reason")
                                 or "cancelled")
        if record.deadline_mono is not None:
            # the TTL ran from SUBMISSION: shift the cutoff back by the
            # age the message already has, so redeliveries (which carry
            # the same created_at) cannot reset the clock
            record.deadline_mono -= _submission_age_seconds(
                getattr(msg, "created_at", "")
            )
        record.trace_id = trace_id
        record.span_id = span_id
        record.event("delivered", redelivered=delivery.redelivered)
        record.event("span", name="job", traceId=trace_id, spanId=span_id,
                     remoteParent=remote.span_id if remote else None)
        token = record.cancel
        self.active_jobs.append(job_entry)
        if self.metrics is not None:
            self.metrics.jobs_active.inc()
        # keyed by the unique job id — the reference keys its EmitterTable by
        # creator/file id (lib/main.js:81), which collides when two jobs from
        # the same creator run concurrently
        emitter = self.emitter_table[job_id] = EventEmitter()
        # idempotent release (RunSlot): the delayed-redelivery park
        # gives the run slot back BEFORE its backoff sleep (a healthy
        # queued job must not wait behind a parked one), the fleet
        # plane's lease waiters release-and-reacquire around their
        # park, and the finally below must not double-release
        slot = RunSlot(self.scheduler, priority_rank(priority),
                       tenant=tenant)
        release_slot = slot.release

        try:
            # saturation shedding (control/overload.py): while this
            # worker is saturated, BULK deliveries bounce at admission —
            # parked briefly then nacked (never FAILED permanently,
            # never charged poison), so the backlog waits out the
            # pressure or lands on a healthier fleet peer
            if self.overload is not None:
                shed_reason = self.overload.should_shed(priority)
                if shed_reason is not None:
                    await self._shed_delivery(delivery, child, record,
                                              token, shed_reason)
                    return
            # content-aware routing (fleet/router.py): when a live peer
            # already leads this content, or the placement controller's
            # plan sheds/defers this class, hand the delivery back to
            # the broker here — before admission, a run slot, or a
            # parked fleet wait are spent on it.  Pure cached-view
            # reads; "run" (the lone-worker default) costs nothing.
            if self.router is not None:
                source_uri = getattr(msg.media, "source_uri", "") or ""
                decision = self.router.decide(
                    source_uri, priority=priority, tenant=tenant,
                )
                if record is not None:
                    # placement context, stamped BEFORE the settles
                    # check so even a deferred/shed delivery's record
                    # (and any later slo_breach / incident bundle)
                    # carries where the router put it (ISSUE 18)
                    record.route_key = route_key_for(source_uri)
                    record.route_decision = decision.outcome
                    if self.fleet is not None:
                        record.plan_epoch = self.fleet.plan_epoch()
                if decision.settles:
                    await self._route_delivery(delivery, child, record,
                                               token, decision)
                    return
                if decision.outcome != "run" and record is not None:
                    # non-default decisions that still admit (own
                    # lease, router error) are timeline-worthy too
                    record.event("route", outcome=decision.outcome,
                                 reason=decision.reason)
            # submitter deadline (Download.ttl_seconds): a redelivered
            # BULK job that already outlived its TTL is dropped before
            # it consumes anything
            if await self._enforce_deadline(delivery, child, record,
                                            where="receipt"):
                return
            # dependency breakers gate intake BEFORE admission: when the
            # staging store or convert publish is hard-down (breaker
            # open), starting the job would only burn its poison budget
            # against a dependency that cannot answer — park it instead,
            # visibly (jobs_by_state{state="PARKED"}, /readyz 503), until
            # the breaker's half-open window opens
            blocked = self.breakers.blocking_dependencies(
                self.admission_dependencies
            )
            if blocked:
                child.warn("parking job: dependency breaker open",
                           dependencies=blocked)
                record.event("breaker_parked", dependencies=blocked)
                if self.metrics is not None:
                    self.metrics.jobs_parked.labels(reason="breaker").inc()
                self.registry.transition(
                    record, control.PARKED,
                    reason="breaker_open: " + ",".join(blocked),
                )
                await token.guard(
                    self.breakers.wait_ready(self.admission_dependencies)
                )
                record.event("breaker_cleared")
            # admission control: a new job only starts once the cache
            # volume has its configured disk headroom — LRU entries are
            # evicted to make room, and if nothing is evictable the job
            # waits (bounded) for in-flight work to free space.  The
            # delivery stays unsettled while we wait, so the broker's
            # prefetch window provides the backpressure.  The token
            # guard makes a parked job cancellable.
            await token.guard(self._admit_job(child, record))
            # queue wait (RECEIPT -> ADMITTED): PR 2 made it visible
            # per-job via the registry timestamps; the histogram finally
            # aggregates it
            queue_wait = time.monotonic() - record._created_mono
            self.registry.transition(record, control.ADMITTED)
            record.event("queue_wait", seconds=round(queue_wait, 6))
            if self.metrics is not None:
                self.metrics.queue_wait_seconds.observe(queue_wait)
            admitted_mono = time.monotonic()
            # priority scheduling: wait for one of the run slots, queued
            # by class (HIGH before NORMAL before BULK) with aging
            await token.guard(slot.acquire())
            sched_wait = time.monotonic() - admitted_mono
            record.event("sched_wait", seconds=round(sched_wait, 6))
            if self.metrics is not None:
                self.metrics.scheduler_wait_seconds.observe(sched_wait)
            # deadline re-check now that the full queue + scheduler wait
            # is known: expired BULK drops (EXPIRED), expired HIGH/NORMAL
            # is surfaced (event + warn log) but still runs
            if await self._enforce_deadline(delivery, child, record,
                                            where="slot_granted"):
                return
            # set DOWNLOADING status (reference lib/main.js:68) — only
            # once the job actually holds a run slot: a job parked in
            # admission or the priority queue must not tell telemetry
            # consumers it is transferring (its queued/admitted state is
            # visible via GET /v1/jobs instead)
            await self.telemetry.emit_status(
                job_id, schemas.TelemetryStatus.Value("DOWNLOADING")
            )
            # parent the job span to the submitter's span when the
            # message carries W3C trace context (triton's design intent,
            # /root/reference/lib/main.js:20 — unused there; live here),
            # under the ids pre-allocated at receipt so logger bindings
            # and recorder events already reference this exact span
            with self.tracer.span("job", remote_parent=remote,
                                  trace_id=trace_id, span_id=span_id,
                                  jobId=job_id, fileId=file_id):
                await self._run_job(msg, delivery, child, emitter,
                                    record, token, slot)
        except JobCancelled:
            await self._settle_cancelled(msg, delivery, child, record, token)
        finally:
            release_slot()
            # remove the finished job (fixes reference lib/main.js:169,
            # which called Array.slice — a no-op — so activeJobs only grew)
            try:
                self.active_jobs.remove(job_entry)
            except ValueError:
                pass
            self.emitter_table.pop(job_id, None)
            if self.metrics is not None:
                self.metrics.jobs_active.dec()
            if not record.terminal:
                # the handler unwound without settling the record (an
                # unexpected error, or task teardown at shutdown): the
                # MQ layer requeues the delivery; close this record
                self.registry.transition(record, control.FAILED,
                                         reason="handler_exit")
            if self.metrics is not None:
                # per-tenant outcome slice (label set bounded: resolved
                # tenants x lifecycle states)
                self.metrics.tenant_jobs.labels(
                    tenant=record.tenant, outcome=record.state
                ).inc()
            if (self.fleet is not None and record.terminal
                    and record.trace_id):
                # publish the job's trace digest to the coordination
                # store (fleet/plane.py) as a detached task: the settle
                # is already acked, and a store round trip must not
                # extend the handler.  Best-effort by contract.
                task = asyncio.create_task(
                    self.fleet.publish_telemetry(record),
                    name=f"telemetry-digest-{record.job_id[:12]}",
                )
                self._telemetry_tasks.add(task)
                task.add_done_callback(self._telemetry_tasks.discard)

    async def _settle_cancelled(self, msg: schemas.Download,
                                delivery: Delivery, logger: Logger,
                                record: JobRecord,
                                token: CancelToken) -> None:
        """Settle a cooperatively-cancelled job.

        ``ack`` (an operator decision is final — no requeue), telemetry
        CANCELLED (or ERRORED under ``control.errored_on_cancel``),
        partial staging files removed, registry record closed.  A
        cancelled singleflight leader already rejected its flight on the
        way here, so coalesced waiters have failed over.
        """
        job_id = msg.media.id
        logger.warn("job cancelled", reason=token.reason or "cancelled")
        # the job owns <download_path>/<id>: remove partial files BEFORE
        # settling, so "delivery settled" implies "disk reclaimed" (the
        # cancel-latency bench and any operator automation can treat the
        # ack as the single completion signal)
        await self._remove_workdir(job_id, logger)
        record.event("settle", mode="ack", why="cancelled",
                     reason=token.reason or "cancelled")
        self._journal_settle(record, "ack", "cancelled")
        await delivery.ack()
        # terminal state BEFORE the telemetry await: observers woken by
        # the ack (broker join, drain, /v1/jobs pollers) must already
        # see CANCELLED, not a settled-but-ADMITTED limbo — the same
        # PR 8 invariant the EXPIRED path honors (graftlint
        # ack-settle-atomicity)
        self.registry.transition(record, control.CANCELLED,
                                 reason=token.reason or "cancelled")
        self._clear_failures(job_id)
        if self.metrics is not None:
            self.metrics.jobs_cancelled.inc()
        try:
            await self.telemetry.emit_status(job_id, self._cancel_status)
        except Exception as err:
            logger.warn("cancel status emit failed", error=str(err))

    async def _admit_job(self, logger: Logger,
                         record: Optional[JobRecord] = None) -> None:
        """Gate job start on disk headroom.

        Two floors: the cache volume's ``min_free_bytes`` (when
        caching, as before) and the WORKDIR volume's
        ``download.min_free_bytes`` plus the per-job
        ``download.reserve_bytes`` space reservation (when configured
        — both default off).  The order is: evict LRU cache entries
        first (cached bytes are the one reclaimable resource), then
        wait for running jobs to release space, then — after
        ``admission_timeout`` — proceed anyway and let the download
        stage's preflight make the loud per-job call.  A forced
        admission that still fails the WORKDIR floor additionally
        force-opens the store breaker with the ``disk`` reason
        (eviction cannot reclaim workdir space, so this worker is
        degraded until the volume drains): /readyz and the fleet
        overview surface it, and follow-on deliveries park on the
        breaker instead of marching into ENOSPC.
        """
        workdir_need = self.workdir_min_free + self.workdir_reserve
        if self.cache is None and workdir_need <= 0:
            return

        def _floors() -> "tuple[bool, bool]":
            cache_ok = self.cache is None or self.cache.has_headroom()
            workdir_ok = True
            if workdir_need > 0:
                free = self._workdir_free_bytes()
                workdir_ok = free is None or free >= workdir_need
            return cache_ok, workdir_ok

        deadline = time.monotonic() + self.admission_timeout
        warned = False
        while True:
            cache_ok, workdir_ok = await asyncio.to_thread(_floors)
            if cache_ok and workdir_ok:
                return
            if self.cache is not None:
                evicted = await self.cache.evict_to_budget(
                    extra_needed=self.workdir_reserve
                    if not workdir_ok else 0)
                if evicted:
                    continue  # re-check the floors after the reclaim
            free_now = (self.cache.free_disk_bytes()
                        if self.cache is not None
                        else (self._workdir_free_bytes() or 0))
            if time.monotonic() >= deadline:
                logger.warn(
                    "admitting job without disk headroom",
                    free_bytes=free_now,
                    cache_ok=cache_ok, workdir_ok=workdir_ok,
                )
                if record is not None:
                    record.event("admission_forced",
                                 free_bytes=free_now)
                if not workdir_ok and self.breakers is not None:
                    breaker = self.breakers.get("store")
                    if breaker is not None:
                        breaker.force_open(OPEN_DISK)
                return
            if not warned:
                warned = True
                logger.warn(
                    "job admission waiting for disk headroom",
                    free_bytes=free_now,
                    cache_ok=cache_ok, workdir_ok=workdir_ok,
                )
                if record is not None:
                    record.event("admission_wait",
                                 free_bytes=free_now)
            await asyncio.sleep(0.25)

    # -- classified failure settlement ---------------------------------
    def _note_failure(self, job_id: str) -> int:
        """Advance the poison counter for one failed delivery attempt.

        Re-inserts at the back so the bound below evicts the LEAST-
        recently-failing job, never an actively hot one; the 10 000-entry
        cap stops jobs whose redeliveries land on other replicas (or get
        dead-lettered) from leaking one entry each for the process
        lifetime.
        """
        failures = self._failure_counts.pop(job_id, 0) + 1
        self._failure_counts[job_id] = failures
        if len(self._failure_counts) > 10_000:
            self._failure_counts.pop(next(iter(self._failure_counts)))
        if self.journal is not None:
            # the poison counter must survive a worker kill: a job that
            # failed twice before the crash is on its third strike after
            self.journal.append("retry", job_id, failures=failures)
        return failures

    def _clear_failures(self, job_id: str) -> None:
        """Drop the poison counter (and journal the drop, so a restart
        cannot resurrect a count the live process already cleared)."""
        if self._failure_counts.pop(job_id, None) is not None \
                and self.journal is not None:
            self.journal.append("retry_clear", job_id)

    def _journal_settle(self, record: JobRecord, mode: str,
                        why: str) -> None:
        """Record how the delivery settled — the bit recovery uses to
        decide whether a redelivery is still coming (nack) or the job's
        story is over and its workdir is an orphan (ack).

        Also the ONE seam every settle path funnels through, so the
        SLO tracker (control/slo.py) classifies each resolution here:
        acked done/staged inside its objective's latency target is
        good, acked failures and latency breaches burn error budget
        (and stamp an ``slo_breach`` event on the record before it
        retires), nacks and cancels are not resolutions at all.
        """
        if self.journal is not None:
            self.journal.append("settle", record.job_id, mode=mode,
                                why=why)
        breached = False
        if self.slo is not None:
            breached = bool(self.slo.note_settle(record, mode, why))
        if breached and self.incidents is not None \
                and self.incidents.auto_export:
            # auto-export (incident/bundle.py): the breach that was just
            # stamped becomes a forensic bundle in the bounded ring —
            # best-effort, because a full ring or a torn journal must
            # never fail the settle itself
            try:
                bundle = build_bundle(self, record, trigger=TRIGGER_BREACH)
                self.incidents.add(bundle, trigger=TRIGGER_BREACH)
                record.event("incident_export",
                             bundleId=bundle.get("bundleId"),
                             trigger=TRIGGER_BREACH)
            except Exception as err:
                self.logger.warn("incident auto-export failed",
                                 jobId=record.job_id, error=str(err))

    async def _remove_workdir(self, job_id: str, logger: Logger) -> None:
        """Best-effort workdir removal for settles after which no
        redelivery will ever come (ack + terminal): without this, a
        permanently-failed or expired job's partial downloads sat on
        disk until an operator noticed (the crash-recovery sweep now
        catches them at the NEXT boot; this catches them live)."""
        try:
            await asyncio.to_thread(
                shutil.rmtree, job_download_dir(self.config, job_id), True
            )
        except OSError as err:
            logger.warn("terminal workdir cleanup failed", error=str(err))

    def _redelivery_delay(self, failures: int) -> float:
        """Exponential park-then-nack pause for the Nth failure."""
        if self._redeliver_base <= 0:
            return 0.0
        return min(self._redeliver_cap,
                   self._redeliver_base * (2 ** (max(failures, 1) - 1)))

    async def _park(self, record: JobRecord, token: CancelToken,
                    delay: float, release_slot, reason: str,
                    failures: Optional[int] = None) -> None:
        """Hold the unsettled delivery for ``delay`` seconds before its
        nack — the broker's prefetch window is the park bench, so the
        redelivery arrives *after* the backoff instead of instantly.
        The run slot is released first and the wait is cancellable."""
        if delay <= 0:
            return
        if release_slot is not None:
            release_slot()
        retry_info = {"why": reason, "nackDelayS": round(delay, 3)}
        if failures is not None:
            retry_info["failures"] = failures
        record.retry = retry_info
        record.event("park", why=reason, delay_s=round(delay, 3))
        if self.metrics is not None:
            if reason.startswith("breaker"):
                label = "breaker"
            elif reason.startswith("overload"):
                label = "overload"
            else:
                label = "backoff"
            self.metrics.jobs_parked.labels(reason=label).inc()
        self.registry.transition(
            record, control.PARKED,
            reason=f"{reason}: redeliver in {delay:.2f}s",
        )
        await token.guard(asyncio.sleep(delay))

    async def _shed_delivery(self, delivery: Delivery, logger: Logger,
                             record: JobRecord, token: CancelToken,
                             reason: str) -> None:
        """Bounce one BULK delivery while the worker is saturated.

        PR 5's park-then-nack discipline, applied to OUR overload
        instead of a dependency's: the unsettled delivery parks for
        ``overload.shed_backoff`` (so the redelivery arrives after the
        pressure sample window, not into it), then nacks for
        redelivery.  The poison counter is NOT advanced — nothing about
        the job failed — and the record closes FAILED with an
        ``overload_shed`` reason, mirroring the breaker-open settle.
        """
        logger.warn("shedding BULK delivery: worker saturated",
                    reason=reason, tenant=record.tenant)
        record.event("shed", why="overload", reason=reason)
        if self.metrics is not None:
            self.metrics.jobs_shed.labels(
                reason=reason, tenant=record.tenant
            ).inc()
        await self._park(record, token, self.overload.shed_backoff, None,
                         reason=f"overload_shed:{reason}")
        record.retry = None
        record.event("settle", mode="nack", why="overload_shed",
                     reason=reason)
        self._journal_settle(record, "nack", "overload_shed")
        await delivery.nack()
        self.registry.transition(
            record, control.FAILED, reason=f"overload_shed: {reason}"
        )

    async def _route_delivery(self, delivery: Delivery, logger: Logger,
                              record: JobRecord, token: CancelToken,
                              decision) -> None:
        """Settle one delivery the content router steered off this
        worker (defer to the lease holder, fleet-fairness defer, or a
        plan-driven BULK shed).

        The PR 5 park-then-nack discipline: the unsettled delivery
        parks for the router's backoff (so the redelivery lands after
        the holder's publish / the next plan beat, not instantly), then
        nacks for redelivery elsewhere.  Poison is NOT charged —
        nothing about the job failed — and the record closes FAILED
        with a ``routed`` reason, mirroring the overload shed.
        """
        logger.info("routing delivery off this worker",
                    outcome=decision.outcome, reason=decision.reason,
                    holder=decision.holder)
        record.event("route", outcome=decision.outcome,
                     reason=decision.reason, holder=decision.holder)
        if decision.outcome == "shed" and self.metrics is not None:
            # the controller's admission shed is an SLO-protective
            # drop, accounted beside the overload layer's sheds
            self.metrics.jobs_shed.labels(
                reason="plan", tenant=record.tenant
            ).inc()
        await self._park(record, token, decision.backoff, None,
                         reason=f"route:{decision.outcome}")
        record.retry = None
        record.event("settle", mode="nack", why="routed",
                     outcome=decision.outcome)
        self._journal_settle(record, "nack", "routed")
        await delivery.nack()
        self.registry.transition(
            record, control.FAILED,
            reason=f"routed: {decision.outcome}"
        )

    async def _enforce_deadline(self, delivery: Delivery, logger: Logger,
                                record: JobRecord, where: str) -> bool:
        """Honor ``Download.ttl_seconds`` at an admission checkpoint.

        Returns True when the delivery was settled here (expired BULK:
        acked + EXPIRED — re-running queue-aged bulk work would burn the
        very capacity the TTL protects).  Expired HIGH/NORMAL work is
        *surfaced* — warn log + ``deadline_exceeded`` event at the
        ``slot_granted`` checkpoint, where the full queueing delay is
        known — but still runs: a user-facing job is never silently
        dropped.
        """
        if not record.deadline_expired():
            return False
        overdue = -(record.deadline_remaining() or 0.0)
        if record.priority != "BULK":
            if where == "slot_granted":
                logger.warn("job deadline exceeded; running anyway "
                            "(non-BULK work is never dropped)",
                            ttlSeconds=record.ttl_seconds,
                            overdueSeconds=round(overdue, 3))
                record.event("deadline_exceeded",
                             overdue_s=round(overdue, 3), where=where)
            return False
        logger.warn("dropping deadline-expired BULK job",
                    ttlSeconds=record.ttl_seconds,
                    overdueSeconds=round(overdue, 3), where=where)
        if self.metrics is not None:
            self.metrics.jobs_shed.labels(
                reason="deadline", tenant=record.tenant
            ).inc()
        # telemetry consumers learn the drop (ERRORED — the same
        # terminal signal the other deliberate drops emit; EXPIRED has
        # no wire enum and legacy consumers only know the reference's
        # range).  Best-effort: a telemetry blip must not block settling.
        try:
            await self.telemetry.emit_status(
                record.job_id, schemas.TelemetryStatus.Value("ERRORED")
            )
        except Exception as err:
            logger.warn("expired-job status emit failed", error=str(err))
        record.event("settle", mode="ack", why="deadline",
                     overdue_s=round(overdue, 3), where=where)
        self._journal_settle(record, "ack", "deadline")
        await delivery.ack()
        self._clear_failures(record.job_id)
        # terminal state BEFORE the workdir removal's await: anything
        # woken by the ack (broker join, drain, /v1/jobs pollers) must
        # already see EXPIRED, not a settled-but-ADMITTED limbo
        self.registry.transition(
            record, control.EXPIRED,
            reason=f"deadline: ttl {record.ttl_seconds:g}s exceeded",
        )
        await self._remove_workdir(record.job_id, logger)
        return True

    async def _settle_failed_attempt(
        self,
        job_id: str,
        delivery: Delivery,
        logger: Logger,
        record: JobRecord,
        token: CancelToken,
        err: Exception,
        release_slot,
        why: str,
        emit_errored: bool = True,
    ) -> None:
        """Settle one failed attempt under the error taxonomy
        (platform/errors.py):

        - breaker-open: park + nack WITHOUT advancing the poison counter
          (the job never reached the dependency)
        - PERMANENT: ack + FAILED immediately — retrying a 4xx/bad-config
          error re-runs the same deterministic outcome
        - POISON (bad content): ack + DROPPED_POISON immediately
        - TRANSIENT/unclassified: advance the poison counter (the seams'
          in-process retry budget is already spent), then park-then-nack
          with exponential backoff so the broker redelivers after the
          blip, not into it
        """
        fault = classify(err)
        seam = getattr(err, "fault_seam", None)
        if getattr(err, "counts_toward_poison", True) is False:
            # the job never got to fail the dependency (BreakerOpen is
            # the in-tree case): park + redeliver WITHOUT charging the
            # poison budget
            dependency = getattr(err, "dependency", None) or seam or "?"
            delay = max(getattr(err, "retry_after", 0.0),
                        self._redeliver_base)
            await self._park(record, token, delay, release_slot,
                             reason=f"breaker_open:{dependency}")
            record.retry = None
            record.event("settle", mode="nack", why="breaker_open",
                         dependency=dependency)
            self._journal_settle(record, "nack", "breaker_open")
            await delivery.nack()
            self.registry.transition(
                record, control.FAILED,
                reason=f"breaker_open: {dependency}",
            )
            return
        if emit_errored:
            await self.telemetry.emit_status(
                job_id, schemas.TelemetryStatus.Value("ERRORED")
            )
        if fault in (PERMANENT, POISON):
            logger.error("dropping job on non-retryable failure",
                         fault=fault, error=str(err)[:200])
            if self.metrics is not None:
                self.metrics.jobs_failed.labels(reason=fault).inc()
            self._clear_failures(job_id)
            # drop any between-attempts retry blob the Retrier left: a
            # terminal record must not read as "waiting for a retry"
            record.retry = None
            record.event("settle", mode="ack", why=fault,
                         type=type(err).__name__)
            self._journal_settle(record, "ack", fault)
            await delivery.ack()
            self.registry.transition(
                record,
                control.FAILED if fault == PERMANENT
                else control.DROPPED_POISON,
                reason=f"{fault}: {type(err).__name__}",
            )
            # no redelivery is coming: the workdir would otherwise leak
            # until the next boot's orphan sweep
            await self._remove_workdir(job_id, logger)
            return
        failures = self._note_failure(job_id)
        record.event("retry", failures=failures,
                     threshold=self.poison_threshold, fault=fault,
                     seam=seam)
        if self.poison_threshold and failures >= self.poison_threshold:
            logger.error(
                "dropping poison job after repeated failures",
                failures=failures,
            )
            # one failure, one count: this attempt is recorded as the
            # drop, not double-counted as a stage_error too
            if self.metrics is not None:
                self.metrics.jobs_failed.labels(reason="poison").inc()
            self._clear_failures(job_id)
            record.retry = None
            record.event("settle", mode="ack", why="poison",
                         failures=failures)
            self._journal_settle(record, "ack", "poison")
            await delivery.ack()
            self.registry.transition(record, control.DROPPED_POISON,
                                     reason=f"{failures} failures")
            await self._remove_workdir(job_id, logger)
            return
        if self.metrics is not None:
            self.metrics.jobs_failed.labels(reason=why).inc()
        delay = self._redelivery_delay(failures)
        await self._park(record, token, delay, release_slot,
                         reason=why, failures=failures)
        record.retry = None
        record.event("settle", mode="nack", why=why,
                     delay_s=round(delay, 3))
        self._journal_settle(record, "nack", why)
        await delivery.nack()
        self.registry.transition(record, control.FAILED, reason=why)

    async def _run_job(
        self,
        msg: schemas.Download,
        delivery: Delivery,
        logger: Logger,
        emitter: EventEmitter,
        record: JobRecord,
        token: CancelToken,
        slot: Optional[RunSlot] = None,
    ) -> None:
        job_id = msg.media.id
        release_slot = slot.release if slot is not None else None

        # build the stage table for this job (reference lib/main.js:99-115)
        ctx = StageContext(
            config=self.config,
            emitter=emitter,
            logger=logger,
            telemetry=_RecordingTelemetry(self.telemetry, record),
            metrics=self.metrics,
            store=self.store,
            tracer=self.tracer,
            resources=self.stage_resources,
            cleanups=self.stage_cleanups,
            cancel=token,
            record=record,
            slot=slot,
        )
        # the streaming dispatch builds what it needs itself (the download
        # stage against a merged-progress facade, the per-file Uploader);
        # only the barrier loop wants the full stage table
        stage_table = (None if self.streaming_enabled
                       else await load_stages(ctx, self.stage_names))

        # idempotency probe (reference lib/main.js:119-124) — a transient
        # store blip here must not decide "not staged" (re-running the
        # stages is merely wasteful) nor escape as a handler crash
        # (instant requeue): it rides the store retry policy, and an
        # exhausted budget settles through the classified path below
        already_staged = True
        try:
            logger.info("checking staging bucket for existing files", jobId=job_id)

            async def _probe():
                if faults.enabled():
                    await faults.fire("store.get", key=job_id)
                return await self.store.get_object(
                    STAGING_BUCKET, done_marker_name(job_id)
                )

            await self.retrier.run("store.get", _probe, cancel=token,
                                   record=record, logger=logger)
        except ObjectNotFound:
            already_staged = False
        except JobCancelled:
            raise
        except Exception as err:
            logger.error("staging probe failed", error=str(err))
            record.event("error", type=type(err).__name__,
                         error=str(err)[:300])
            await self._settle_failed_attempt(
                job_id, delivery, logger, record, token, err,
                release_slot, why="stage_error")
            return

        if not already_staged:
            logger.info("starting main processor after successful stage init")
            last_stage_data: object = {}
            try:
                if self.streaming_enabled:
                    # pipelined dispatch (stages/streaming.py): one
                    # combined RUNNING("pipeline") attribution — the
                    # three logical stages run overlapped, and the
                    # per-file detail rides the flight recorder's
                    # file_complete/upload_start/upload_done events
                    self.registry.transition(record, control.RUNNING,
                                             stage=PIPELINE_STAGE)
                    token.raise_if_cancelled()
                    logger.info("invoking streaming pipeline")
                    started = time.monotonic()
                    try:
                        await token.guard(run_streaming_job(
                            ctx, msg.media,
                            mirrors=tuple(msg.mirrors),
                            source_kind=schemas.enum_to_string(
                                schemas.SourceKind, msg.source_kind
                            ),
                        ))
                    finally:
                        if self.metrics is not None:
                            self.metrics.stage_seconds.labels(
                                stage=PIPELINE_STAGE
                            ).observe(time.monotonic() - started)
                else:
                    for name in self.stage_names:
                        self.registry.transition(record, control.RUNNING,
                                                 stage=name)
                        token.raise_if_cancelled()
                        job = Job(media=msg.media,
                                  last_stage=last_stage_data,
                                  mirrors=tuple(msg.mirrors),
                                  source_kind=schemas.enum_to_string(
                                      schemas.SourceKind,
                                      msg.source_kind,
                                  ))
                        logger.info("invoking stage", stage=name)
                        started = time.monotonic()
                        try:
                            # the guard bounds the whole stage dispatch
                            # by the cancel token: even a stage blocked
                            # somewhere without a cooperative check (DNS,
                            # TLS handshake, a wedged origin) unwinds
                            # promptly
                            last_stage_data = await token.guard(
                                stage_table[name](job)
                            )
                        finally:
                            if self.metrics is not None:
                                self.metrics.stage_seconds.labels(
                                    stage=name
                                ).observe(time.monotonic() - started)
                        # NOTE: the reference emits
                        # ``emitter.emit('progress', 0)`` here
                        # (lib/main.js:139) but no listener exists in
                        # either codebase, and forwarding a hardcoded 0
                        # to telemetry would reset real stage progress —
                        # deliberately dropped (PARITY.md "Reference
                        # bugs fixed").
            except JobCancelled:
                raise  # settled by the processor (ack, cleanup, CANCELLED)
            except Exception as err:
                logger.error("failed to invoke stage", error=str(err))
                record.event("error", stage=record.stage,
                             type=type(err).__name__, error=str(err)[:300])

                # permanent stall -> drop the job (reference lib/main.js:144-146)
                if getattr(err, "code", None) == "ERRDLSTALL":
                    if self.metrics is not None:
                        self.metrics.jobs_failed.labels(reason="stalled").inc()
                    self._clear_failures(job_id)  # job is settled
                    record.event("settle", mode="ack", why="stalled")
                    self._journal_settle(record, "ack", "stalled")
                    await delivery.ack()
                    self.registry.transition(record, control.FAILED,
                                             reason="stalled")
                    await self._remove_workdir(job_id, logger)
                    return

                # anything else settles under the error taxonomy:
                # permanent/poison drop immediately, transients advance
                # the poison counter and park before their nack
                # (replacing the reference's instant ERRORED + redelivery
                # hot loop, lib/main.js:148-150)
                await self._settle_failed_attempt(
                    job_id, delivery, logger, record, token, err,
                    release_slot, why="stage_error")
                return
            logger.info("creating convert job")
        else:
            logger.warn("skipping download due to files existing in triton-staging")
            record.event("idempotent_skip")
            if self.metrics is not None:
                self.metrics.jobs_skipped.inc()

        # publish the convert message even when staging was skipped
        # (reference lib/main.js:153-167).  Cancellation past this point
        # is a no-op by design: the bytes are staged and the cheapest
        # path for everyone is finishing the publish.
        self.registry.transition(record, control.PUBLISHING)
        payload = schemas.Convert(created_at=_utcnow_iso(), media=msg.media)
        # deadline propagation (ROADMAP item 5 remaining depth): the
        # SURVIVING ttl budget rides into the convert pipeline — the
        # downstream converter can apply the same expired-BULK shedding
        # instead of transcoding work nobody is waiting for.  Floor at
        # 1 ms, never 0: proto3 drops a 0.0 from the wire, and the
        # field's contract reads absent/0 as "no deadline" — exactly
        # the overdue jobs (negative remaining) must NOT decode as
        # deadline-free.  Jobs without a TTL leave the field unset, so
        # old consumers decode identically.
        remaining = record.deadline_remaining()
        if remaining is not None:
            payload.deadline_seconds = max(round(remaining, 3), 0.001)
        try:
            # carry the job span's context to the downstream converter so
            # its spans join this trace (submit -> job -> convert); a
            # NullTracer records nothing, so propagating its span ids
            # would hand the converter parents that exist nowhere
            tp = (None if isinstance(self.tracer, NullTracer)
                  else format_traceparent())
            headers = {"traceparent": tp} if tp else None

            async def _publish():
                if faults.enabled():
                    await faults.fire("publish", key=job_id)
                if getattr(self, "_convert_fanout", False):
                    await self.mq.publish_exchange(
                        schemas.CONVERT_EXCHANGE, schemas.encode(payload),
                        headers=headers,
                    )
                else:
                    await self.mq.publish(
                        schemas.CONVERT_QUEUE, schemas.encode(payload),
                        headers=headers,
                    )

            # broker blips ride the publish retry policy in-process; an
            # exhausted budget falls through to the classified settle
            await self.retrier.run("publish", _publish, cancel=token,
                                   record=record, logger=logger)
            record.event("publish", queue=schemas.CONVERT_QUEUE,
                         fanout=bool(getattr(self, "_convert_fanout", False)))
            if self.metrics is not None:
                self.metrics.messages_published.labels(
                    queue=schemas.CONVERT_QUEUE
                ).inc()
        except JobCancelled:
            raise  # cancel fired during a publish retry backoff
        except Exception as err:
            # the reference logs and returns without settling
            # (lib/main.js:161-166), which leaks the delivery.  Settle
            # through the classified path instead — crucially, publish
            # failures now COUNT toward the poison threshold (they
            # previously bypassed it, so a perpetually failing convert
            # publish redelivered forever): the idempotency marker makes
            # each redelivery skip straight to re-publishing, and a
            # hard-down broker trips the publish breaker + parks intake.
            # No ERRORED telemetry here: the media is fully staged, and
            # the reference never emitted one for publish trouble either.
            logger.error("failed to create job", error=str(err))
            record.event("error", type=type(err).__name__,
                         error=str(err)[:300])
            await self._settle_failed_attempt(
                job_id, delivery, logger, record, token, err,
                release_slot, why="publish_error", emit_errored=False)
            return

        # crash point "settle.ack" (platform/faults.py kind: crash): the
        # pre-ack seam — everything staged and published, the delivery
        # not yet settled.  A kill here is the redelivery-of-a-finished-
        # job case the idempotency probe + journal must absorb.
        if faults.enabled():
            await faults.fire("settle.ack", key=job_id)
        record.event("settle", mode="ack", why="done")
        self._journal_settle(record, "ack", "done")
        await delivery.ack()
        # success clears the poison counter: transient-failure retries that
        # eventually succeed must not count against a later redelivery
        self._clear_failures(job_id)
        if self.metrics is not None:
            self.metrics.jobs_completed.inc()
        self.registry.transition(record, control.DONE)
