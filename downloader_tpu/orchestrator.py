"""Job orchestrator: consume download jobs, run the stage pipeline, publish
convert jobs.

Capability-equivalent to /root/reference/lib/main.js:40-205:

- consumes ``v1.download`` (lib/main.js:172), decodes protobuf ``Download``
  (lib/main.js:63)
- emits status ``DOWNLOADING`` (=2) on receipt (lib/main.js:68)
- tracks active jobs for the health endpoint (lib/main.js:70-73) — with the
  reference's ``activeJobs.slice`` no-op bug fixed (lib/main.js:169; see
  SURVEY.md §7 step 6): completed jobs are actually removed here
- per-job EventEmitter registered in an emitter table (lib/main.js:26,81)
- loads the stage plugins dynamically by name and validates the contract
  (lib/main.js:99-115)
- idempotency probe against ``triton-staging/<jobId>/original/done``
  (lib/main.js:119-124): if present, skip the stages but still publish the
  convert message (lib/main.js:153-167)
- sequential stage loop threading ``last_stage`` (lib/main.js:126-140)
- error policy: ``ERRDLSTALL`` -> ack (drop job) (lib/main.js:144-146);
  any other stage error -> status ``ERRORED`` (=6) + nack for redelivery
  (lib/main.js:148-150)
- publishes protobuf ``Convert`` to ``v1.convert`` then acks
  (lib/main.js:157-168)
"""

from __future__ import annotations

import asyncio
import datetime
import os
import time
from typing import Dict, List, Optional

from . import schemas
from .mq.base import Delivery, MessageQueue
from .platform.config import cfg_get
from .platform.logging import Logger, get_logger
from .platform.metrics import Metrics
from .platform.telemetry import NullTelemetry, Telemetry
from .platform.tracing import (NullTracer, Tracer, format_traceparent,
                               parse_traceparent)
from .stages.base import STAGES, Job, StageContext, load_stages
from .stages.upload import STAGING_BUCKET, done_marker_name
from .store.base import ObjectNotFound, ObjectStore
from .store.cache import ContentCache
from .utils import EventEmitter


def _utcnow_iso() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="milliseconds")
        .replace("+00:00", "Z")
    )


class Orchestrator:
    def __init__(
        self,
        config,
        mq: MessageQueue,
        store: ObjectStore,
        telemetry: Optional[Telemetry] = None,
        metrics: Optional[Metrics] = None,
        tracer: Optional[Tracer] = None,
        logger: Optional[Logger] = None,
        stages: Optional[List[str]] = None,
        prefetch: Optional[int] = None,
        poison_threshold: int = 5,
        cache: Optional[ContentCache] = None,
        admission_timeout: float = 30.0,
    ):
        self.config = config
        self.mq = mq
        self.store = store
        self.telemetry = telemetry or NullTelemetry()
        self.metrics = metrics
        self.tracer = tracer or NullTracer()
        self.logger = logger or get_logger("orchestrator")
        self.stage_names = stages or list(STAGES)
        # Consumer prefetch = max concurrently-processed jobs, now
        # configurable (MAX_CONCURRENT_JOBS / instance.max_concurrent_jobs)
        # instead of hardcoded.  The default of 2 resolves BASELINE.md's
        # ``new AMQP(addr, 1, 2, prom)`` question (lib/main.js:46):
        # triton-core's AMQP signature is (host, connections, prefetch,
        # prom) — one connection (we likewise hold one job connection;
        # telemetry rides its own, app.py), and a consumer prefetch of 2:
        # up to two deliveries in flight, processed CONCURRENTLY (both
        # backends dispatch one handler task per delivery), matching the
        # reference's async consumer behavior under the same qos.  See
        # PARITY.md "AMQP constructor constants".  Fan-in deployments
        # raise it: with the content cache, same-content jobs coalesce
        # onto one fetch, so extra in-flight jobs are nearly free.
        if prefetch is None:
            raw = os.environ.get("MAX_CONCURRENT_JOBS") or cfg_get(
                config, "instance.max_concurrent_jobs", 2
            )
            try:
                prefetch = int(raw)
            except (TypeError, ValueError):
                raise ValueError(
                    f"max_concurrent_jobs must be an integer, got {raw!r}"
                ) from None
        if prefetch < 1:
            raise ValueError(f"max_concurrent_jobs must be >= 1, got {prefetch}")
        self.prefetch = prefetch

        # content-addressed staging cache (store/cache.py): shared with
        # the download stage via stage_resources, consulted by the
        # admission gate below.  None = disabled (the config default).
        self.cache = cache if cache is not None else ContentCache.from_config(
            config, logger=self.logger
        )
        if self.cache is not None and metrics is not None:
            self.cache.metrics = metrics
        # how long admission may hold a job waiting for cache-volume disk
        # headroom before letting it proceed (the download stage's own
        # ensure_disk_space preflight still fails loudly if truly full)
        self.admission_timeout = admission_timeout

        # (reference EmitterTable / activeJobs, lib/main.js:26,34)
        self.emitter_table: Dict[str, EventEmitter] = {}
        self.active_jobs: List[dict] = []

        # shared across every job's StageContext: stage-memoized resources
        # (e.g. the download stage's long-lived DHT node) and their
        # teardown callables, run once at shutdown
        self.stage_resources: dict = {}
        self.stage_cleanups: list = []
        # the download stage probes/fills the same cache instance the
        # admission gate watches (None = disabled; the stage respects it)
        self.stage_resources["content_cache"] = self.cache

        # poison-job guard: the reference nacks failed jobs forever
        # (lib/main.js:148-150), which on RabbitMQ without a dead-letter
        # policy hot-loops a deterministically-failing job at the head of
        # the queue.  After this many failures of one job in this process,
        # drop it (ack + ERRORED) instead of redelivering.  0 disables.
        self.poison_threshold = poison_threshold
        self._failure_counts: Dict[str, int] = {}

        # readiness: True between a successful start() and shutdown()
        # (surfaced by /readyz, health.py)
        self.consuming = False

    # ------------------------------------------------------------------
    async def start(self) -> None:
        """Connect and begin consuming (reference lib/main.js:47,172)."""
        await self.mq.connect()
        await self.telemetry.connect()
        # route Convert through a fanout exchange bound to the canonical
        # queue where the backend supports it: the downstream converter
        # consumes the same queue as before, and observers (submit --wait)
        # can tap completion events without stealing deliveries
        try:
            await self.mq.bind_queue(
                schemas.CONVERT_QUEUE, schemas.CONVERT_EXCHANGE
            )
            self._convert_fanout = True
        except NotImplementedError:
            self._convert_fanout = False
        await self.mq.listen(
            schemas.DOWNLOAD_QUEUE, self.processor, prefetch=self.prefetch
        )
        self.consuming = True
        self.logger.info("successfully connected to queue")

    async def shutdown(self, grace_seconds: float = 30.0) -> None:
        """Stop consuming; wait for in-flight jobs to settle.

        The reference's termination closure refuses a clean exit while jobs
        are active (lib/main.js:197-204); here we stop pulling new work
        first, then actually drain the in-flight jobs.
        """
        self.consuming = False
        await self.mq.stop_consuming()
        try:
            async with asyncio.timeout(grace_seconds):
                while self.active_jobs:
                    await asyncio.sleep(0.05)
        except TimeoutError:
            self.logger.warn(
                "shutdown grace period expired with active jobs",
                active=len(self.active_jobs),
            )
        await self.mq.close()
        await self.telemetry.close()
        for cleanup in self.stage_cleanups:
            try:
                await cleanup()
            except Exception as err:
                self.logger.warn("stage cleanup failed", error=str(err))
        self.stage_cleanups.clear()
        self.stage_resources.clear()

    # ------------------------------------------------------------------
    async def processor(self, delivery: Delivery) -> None:
        """Handle one ``v1.download`` delivery (reference lib/main.js:62-170)."""
        msg = schemas.decode(schemas.Download, delivery.body)
        file_id = msg.media.creator_id  # (reference lib/main.js:64)
        job_id = msg.media.id           # (reference lib/main.js:65)

        if self.metrics is not None:
            self.metrics.jobs_consumed.inc()

        job_entry = {"cardId": file_id, "jobId": job_id}
        child = self.logger.child(jobId=job_id, fileId=file_id)

        # admission control: a new job only starts once the cache volume
        # has its configured disk headroom — LRU entries are evicted to
        # make room, and if nothing is evictable the job waits (bounded)
        # for in-flight work to free space.  The delivery stays unsettled
        # while we wait, so the broker's prefetch window provides the
        # backpressure.
        await self._admit_job(child)

        # all bookkeeping after this point is undone in the finally, so a
        # failure anywhere (even in the status emit) can't leak the gauge or
        # the active-jobs entry
        self.active_jobs.append(job_entry)
        if self.metrics is not None:
            self.metrics.jobs_active.inc()
        # keyed by the unique job id — the reference keys its EmitterTable by
        # creator/file id (lib/main.js:81), which collides when two jobs from
        # the same creator run concurrently
        emitter = self.emitter_table[job_id] = EventEmitter()

        try:
            # set DOWNLOADING status (reference lib/main.js:68)
            await self.telemetry.emit_status(
                job_id, schemas.TelemetryStatus.Value("DOWNLOADING")
            )
            # parent the job span to the submitter's span when the
            # message carries W3C trace context (triton's design intent,
            # /root/reference/lib/main.js:20 — unused there; live here)
            remote = parse_traceparent(delivery.headers.get("traceparent"))
            with self.tracer.span("job", remote_parent=remote,
                                  jobId=job_id, fileId=file_id):
                await self._run_job(msg, delivery, child, emitter)
        finally:
            # remove the finished job (fixes reference lib/main.js:169,
            # which called Array.slice — a no-op — so activeJobs only grew)
            try:
                self.active_jobs.remove(job_entry)
            except ValueError:
                pass
            self.emitter_table.pop(job_id, None)
            if self.metrics is not None:
                self.metrics.jobs_active.dec()

    async def _admit_job(self, logger: Logger) -> None:
        """Gate job start on cache-volume disk headroom.

        No cache -> no gate (the download stage's ensure_disk_space
        preflight is then the only guard, as before).  With a cache, the
        order is: evict LRU entries first (cached bytes are the one
        reclaimable resource), then wait for running jobs to release
        space, then — after ``admission_timeout`` — proceed anyway and
        let the preflight make the loud per-job call.
        """
        if self.cache is None:
            return
        deadline = time.monotonic() + self.admission_timeout
        warned = False
        while not await asyncio.to_thread(self.cache.has_headroom):
            evicted = await self.cache.evict_to_budget()
            if evicted:
                continue  # re-check headroom after the reclaim
            if time.monotonic() >= deadline:
                logger.warn(
                    "admitting job without cache disk headroom",
                    free_bytes=self.cache.free_disk_bytes(),
                    min_free_bytes=self.cache.min_free_bytes,
                )
                return
            if not warned:
                warned = True
                logger.warn(
                    "job admission waiting for cache disk headroom",
                    free_bytes=self.cache.free_disk_bytes(),
                    min_free_bytes=self.cache.min_free_bytes,
                )
            await asyncio.sleep(0.25)

    async def _run_job(
        self,
        msg: schemas.Download,
        delivery: Delivery,
        logger: Logger,
        emitter: EventEmitter,
    ) -> None:
        job_id = msg.media.id

        # build the stage table for this job (reference lib/main.js:99-115)
        ctx = StageContext(
            config=self.config,
            emitter=emitter,
            logger=logger,
            telemetry=self.telemetry,
            metrics=self.metrics,
            store=self.store,
            tracer=self.tracer,
            resources=self.stage_resources,
            cleanups=self.stage_cleanups,
        )
        stage_table = await load_stages(ctx, self.stage_names)

        # idempotency probe (reference lib/main.js:119-124)
        already_staged = True
        try:
            logger.info("checking staging bucket for existing files", jobId=job_id)
            await self.store.get_object(STAGING_BUCKET, done_marker_name(job_id))
        except ObjectNotFound:
            already_staged = False

        if not already_staged:
            logger.info("starting main processor after successful stage init")
            last_stage_data: object = {}
            try:
                for name in self.stage_names:
                    job = Job(media=msg.media, last_stage=last_stage_data)
                    logger.info("invoking stage", stage=name)
                    started = time.monotonic()
                    try:
                        last_stage_data = await stage_table[name](job)
                    finally:
                        if self.metrics is not None:
                            self.metrics.stage_seconds.labels(stage=name).observe(
                                time.monotonic() - started
                            )
                    # NOTE: the reference emits ``emitter.emit('progress', 0)``
                    # here (lib/main.js:139) but no listener exists in either
                    # codebase, and forwarding a hardcoded 0 to telemetry
                    # would reset real stage progress — deliberately dropped
                    # (PARITY.md "Reference bugs fixed").
            except Exception as err:
                logger.error("failed to invoke stage", error=str(err))

                # permanent stall -> drop the job (reference lib/main.js:144-146)
                if getattr(err, "code", None) == "ERRDLSTALL":
                    if self.metrics is not None:
                        self.metrics.jobs_failed.labels(reason="stalled").inc()
                    self._failure_counts.pop(job_id, None)  # job is settled
                    await delivery.ack()
                    return

                # anything else -> ERRORED + redelivery
                # (reference lib/main.js:148-150)
                await self.telemetry.emit_status(
                    job_id, schemas.TelemetryStatus.Value("ERRORED")
                )
                failures = self._failure_counts.pop(job_id, 0) + 1
                # re-insert at the back: dict eviction below then drops the
                # LEAST-recently-failing job, never an actively hot one
                self._failure_counts[job_id] = failures
                # bound the counter dict: jobs whose redeliveries land on
                # other replicas (or get dead-lettered) would otherwise
                # leak one entry each for the process lifetime
                if len(self._failure_counts) > 10_000:
                    self._failure_counts.pop(
                        next(iter(self._failure_counts))
                    )
                if self.poison_threshold and failures >= self.poison_threshold:
                    logger.error(
                        "dropping poison job after repeated failures",
                        failures=failures,
                    )
                    # one failure, one count: this attempt is recorded as
                    # the drop, not double-counted as a stage_error too
                    if self.metrics is not None:
                        self.metrics.jobs_failed.labels(reason="poison").inc()
                    self._failure_counts.pop(job_id, None)
                    await delivery.ack()
                    return
                if self.metrics is not None:
                    self.metrics.jobs_failed.labels(reason="stage_error").inc()
                await delivery.nack()
                return
            logger.info("creating convert job")
        else:
            logger.warn("skipping download due to files existing in triton-staging")
            if self.metrics is not None:
                self.metrics.jobs_skipped.inc()

        # publish the convert message even when staging was skipped
        # (reference lib/main.js:153-167)
        payload = schemas.Convert(created_at=_utcnow_iso(), media=msg.media)
        try:
            # carry the job span's context to the downstream converter so
            # its spans join this trace (submit -> job -> convert); a
            # NullTracer records nothing, so propagating its span ids
            # would hand the converter parents that exist nowhere
            tp = (None if isinstance(self.tracer, NullTracer)
                  else format_traceparent())
            headers = {"traceparent": tp} if tp else None
            if getattr(self, "_convert_fanout", False):
                await self.mq.publish_exchange(
                    schemas.CONVERT_EXCHANGE, schemas.encode(payload),
                    headers=headers,
                )
            else:
                await self.mq.publish(
                    schemas.CONVERT_QUEUE, schemas.encode(payload),
                    headers=headers,
                )
            if self.metrics is not None:
                self.metrics.messages_published.labels(
                    queue=schemas.CONVERT_QUEUE
                ).inc()
        except Exception as err:
            # the reference logs and returns without settling
            # (lib/main.js:161-166), which leaks the delivery; nack instead so
            # the message is redelivered — the idempotency marker makes the
            # retry skip straight to re-publishing the convert message
            logger.error("failed to create job", error=str(err))
            await delivery.nack()
            return

        await delivery.ack()
        # success clears the poison counter: transient-failure retries that
        # eventually succeed must not count against a later redelivery
        self._failure_counts.pop(job_id, None)
        if self.metrics is not None:
            self.metrics.jobs_completed.inc()
