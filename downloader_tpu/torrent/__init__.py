"""Pure-asyncio BitTorrent client.

Capability-equivalent to the reference's use of webtorrent
(/root/reference/lib/download.js:9,19,43-123): download a torrent given a
magnet link, a ``.torrent`` URL, or a local ``.torrent`` file, into a target
directory, with progress reporting and the 240 s metadata/stall watchdog
semantics the reference builds around it.

Scope: the BitTorrent peer wire protocol with the extension protocol
(BEP 3/10), fast extension (BEP 6), metadata exchange (BEP 9), compact
peers v4/v6 (BEP 23/7), peer exchange (BEP 11), webseeds (BEP 19),
HTTP(S) and UDP trackers with scrape (BEP 15/48), mainline DHT peer
discovery (BEP 5), ``x.pe`` direct peers, MSE/PE stream encryption, a
uTP datagram transport (BEP 29, ``utp.py``) with TCP fallback policy,
and fast-resume sidecars (``resume.py``) — so magnet links resolve
through trackers, the DHT, or explicit peers, matching and exceeding
webtorrent's discovery/transport surface.  The package also includes a
:class:`Seeder` (webtorrent seeds as well as leeches) serving both
transports, which doubles as the hermetic swarm for tests.
"""

from .bencode import bdecode, bencode
from .client import TorrentClient, TorrentError
from .dht import DHTNode
from .magnet import MagnetLink, parse_magnet
from .metainfo import Metainfo, make_metainfo
from .seeder import Seeder

__all__ = [
    "bdecode",
    "bencode",
    "TorrentClient",
    "TorrentError",
    "DHTNode",
    "MagnetLink",
    "parse_magnet",
    "Metainfo",
    "make_metainfo",
    "Seeder",
]
