"""Pure-asyncio BitTorrent client.

Capability-equivalent to the reference's use of webtorrent
(/root/reference/lib/download.js:9,19,43-123): download a torrent given a
magnet link, a ``.torrent`` URL, or a local ``.torrent`` file, into a target
directory, with progress reporting and the 240 s metadata/stall watchdog
semantics the reference builds around it.

Scope: the BitTorrent peer wire protocol with the ut_metadata extension
(BEP 3/9/10, compact peers BEP 23), HTTP(S) and UDP trackers (BEP 15),
mainline DHT peer discovery (BEP 5), and ``x.pe`` direct peers — so magnet
links resolve through trackers, the DHT, or explicit peers, matching
webtorrent's discovery surface.  The package also includes a
:class:`Seeder` (webtorrent seeds as well as leeches), which doubles as the
hermetic swarm for tests.
"""

from .bencode import bdecode, bencode
from .client import TorrentClient
from .dht import DHTNode
from .magnet import MagnetLink, parse_magnet
from .metainfo import Metainfo, make_metainfo
from .seeder import Seeder

__all__ = [
    "bdecode",
    "bencode",
    "TorrentClient",
    "DHTNode",
    "MagnetLink",
    "parse_magnet",
    "Metainfo",
    "make_metainfo",
    "Seeder",
]
