"""Bencoding (BEP 3): the wire/metadata format of BitTorrent.

Canonical rules: integers ``i<n>e``, byte strings ``<len>:<bytes>``, lists
``l...e``, dicts ``d...e`` with byte-string keys sorted lexicographically.
Round-trip stability matters because infohashes are SHA-1 of the re-encoded
``info`` dict.
"""

from __future__ import annotations

from typing import Any, Tuple


def bencode(value: Any) -> bytes:
    """Encode ints, bytes, str (utf-8), lists, and dicts."""
    if isinstance(value, bool):
        raise TypeError("bool is not bencodable")
    if isinstance(value, int):
        return b"i%de" % value
    if isinstance(value, str):
        value = value.encode("utf-8")
    if isinstance(value, (bytes, bytearray, memoryview)):
        value = bytes(value)
        return b"%d:%s" % (len(value), value)
    if isinstance(value, (list, tuple)):
        return b"l" + b"".join(bencode(item) for item in value) + b"e"
    if isinstance(value, dict):
        out = [b"d"]
        keys = []
        for key in value:
            if isinstance(key, str):
                keys.append(key.encode("utf-8"))
            elif isinstance(key, bytes):
                keys.append(key)
            else:
                raise TypeError(f"dict key must be str/bytes, got {type(key)}")
        for raw_key in sorted(keys):
            original = raw_key if raw_key in value else raw_key.decode("utf-8")
            out.append(bencode(raw_key))
            out.append(bencode(value[original]))
        out.append(b"e")
        return b"".join(out)
    raise TypeError(f"cannot bencode {type(value).__name__}")


class BencodeError(ValueError):
    pass


def _decode_at(data: bytes, pos: int) -> Tuple[Any, int]:
    if pos >= len(data):
        raise BencodeError("truncated bencode data")
    char = data[pos:pos + 1]
    if char == b"i":
        end = data.index(b"e", pos)
        text = data[pos + 1:end]
        if text in (b"", b"-") or (text.startswith(b"0") and text != b"0") or \
                text.startswith(b"-0"):
            raise BencodeError(f"invalid integer {text!r}")
        return int(text), end + 1
    if char == b"l":
        items = []
        pos += 1
        while data[pos:pos + 1] != b"e":
            item, pos = _decode_at(data, pos)
            items.append(item)
        return items, pos + 1
    if char == b"d":
        out = {}
        pos += 1
        last_key = None
        while data[pos:pos + 1] != b"e":
            key, pos = _decode_at(data, pos)
            if not isinstance(key, bytes):
                raise BencodeError("dict key must be a byte string")
            if last_key is not None and key <= last_key:
                # tolerated (some clients emit unsorted dicts) but the
                # re-encode will canonicalize
                pass
            last_key = key
            value, pos = _decode_at(data, pos)
            out[key] = value
        return out, pos + 1
    if char.isdigit():
        colon = data.index(b":", pos)
        length = int(data[pos:colon])
        start = colon + 1
        end = start + length
        if end > len(data):
            raise BencodeError("byte string exceeds buffer")
        return data[start:end], end
    raise BencodeError(f"unexpected byte {char!r} at {pos}")


def bdecode(data: bytes) -> Any:
    """Decode a single bencoded value; trailing bytes are an error."""
    value, end = _decode_at(bytes(data), 0)
    if end != len(data):
        raise BencodeError(f"{len(data) - end} trailing bytes")
    return value


def bdecode_prefix(data: bytes) -> Tuple[Any, int]:
    """Decode one value from the head of ``data``; returns (value, consumed).

    Used by ut_metadata messages, which append raw piece bytes after the
    bencoded header (BEP 9).
    """
    return _decode_at(bytes(data), 0)
