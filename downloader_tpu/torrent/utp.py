"""uTP — Micro Transport Protocol (BEP 29) over UDP.

The reference's webtorrent client dials peers over both TCP and uTP
(/root/reference/lib/download.js:19 — webtorrent bundles utp-native); uTP
matters in the real world because consumer NATs and ISP shapers often
drop or throttle bulk TCP, while uTP's LEDBAT congestion control yields
to interactive traffic and survives UDP-only NAT mappings.  This module
closes that transport capability with a from-scratch asyncio
implementation: no third-party code, standard BEP 29 wire format.

Surface: :func:`open_utp_connection` and :class:`UtpEndpoint` mirror
``asyncio.open_connection`` / ``asyncio.start_server`` closely enough
that the MSE layer (mse.py) and the peer wire protocol (wire.py) run
unchanged over uTP — the reader IS an ``asyncio.StreamReader`` and the
writer facade implements the subset the stack uses (``write``, ``drain``,
``close``, ``wait_closed``, ``is_closing``, ``get_extra_info``).

Protocol notes (BEP 29):

- 20-byte header: type/version byte, extension byte, connection id,
  32-bit microsecond timestamp, timestamp difference, advertised window,
  sequence number, ack number.  Types: ST_DATA, ST_FIN, ST_STATE,
  ST_RESET, ST_SYN.  ST_DATA/ST_FIN/ST_SYN consume sequence numbers;
  ST_STATE does not.
- Handshake: initiator sends ST_SYN with ``connection_id = conn_id_recv``
  and ``seq_nr = 1``; all later packets carry ``conn_id_send =
  conn_id_recv + 1``.  The acceptor mirrors the pair and replies with
  ST_STATE carrying a random initial ``seq_nr``.
- Selective ack (extension 1): a bitmask acking packets beyond
  ``ack_nr + 1`` so a single lost datagram doesn't stall the pipe.
- Congestion control is LEDBAT: every packet echoes the sender's
  timestamp back as ``timestamp_difference``; the one-way delay above a
  min-filtered base estimates queuing delay and the window tracks a
  100 ms target, backing off multiplicatively on loss/timeout.
"""

from __future__ import annotations

import asyncio
import random
import struct
import time
from collections import deque
from typing import Callable, Dict, Optional, Tuple, Union

ST_DATA = 0
ST_FIN = 1
ST_STATE = 2
ST_RESET = 3
ST_SYN = 4

VERSION = 1
EXT_SACK = 1

_HEADER = struct.Struct(">BBHIIIHH")
HEADER_SIZE = _HEADER.size  # 20

# conservative payload: 20-byte header under a 1400-byte UDP datagram
# clears every sane tunnel/PPPoE MTU without fragmentation
MAX_PAYLOAD = 1380
# loopback paths get large datagrams (the lo interface MTU is 64 KiB):
# throughput is bounded by per-packet processing cost, not bytes — the
# r3 payload sweep measured 27 MB/s at 1380 vs 648 MB/s at 60 KiB on
# the same code, and the full torrent swarm over uTP went 19 -> 79 MB/s
# (BASELINE.md "uTP: where the time goes")
LOOPBACK_PAYLOAD = 60 * 1024

# LEDBAT (RFC 6817 / BEP 29) parameters
TARGET_DELAY_US = 100_000
MAX_CWND_INCREASE_PER_RTT = 3000  # bytes, libutp's default gain

RECV_WINDOW = 1 << 20  # advertised receive window

MIN_RTO = 0.5
MAX_RETRANSMITS = 6  # ~0.5+1+2+4+8+16 s of backoff before giving up
FIN_LINGER = 3.0
# TIME_WAIT-style courtesy after our side retires with the peer's FIN
# still unseen: stay registered (acking the peer's data/FIN) so THEIR
# close completes in one round trip instead of retransmitting into
# silence until FIN_LINGER aborts — profiled r5: this stall made a
# loopback transfer of 32 MiB read 11 MB/s end-to-end while the data
# phase alone ran at ~1 GB/s
LAST_ACK_LINGER = 1.0

# acceptor-side state bounds: a SYN flood must not mint unbounded
# connection objects/timers, and a silent peer must not pin its slot
# forever (healthy BitTorrent connections carry 60 s keep-alives)
MAX_ACCEPTED_CONNS = 256
IDLE_TIMEOUT = 300.0

# out-of-order packets held while waiting for a retransmit; beyond this a
# hostile or badly reordered stream is dropped on the floor (the sender
# retransmits — correctness is unaffected, memory stays bounded)
MAX_OOO = 2048

# Delayed acks (r4): the r3 profile measured one ST_STATE per ST_DATA —
# roughly half the per-packet processing budget on a loopback transfer
# ("uTP: where the time goes", BASELINE.md).  Cumulative ack_nr makes
# acking every Nth in-order packet protocol-legal (BEP 29 specifies no
# ack schedule; libutp likewise delays); anything out of the ordinary —
# reordering, duplicates, FIN — still acks immediately, so dup-ack fast
# retransmit and loss recovery behave exactly as before.  The safety
# valve: the 50 ms timer tick flushes a pending ack long before the
# sender's MIN_RTO (500 ms) can fire.
DELAYED_ACK_EVERY = 2
DELAYED_ACK_TIMEOUT = 0.05


def _now_us() -> int:
    return time.monotonic_ns() // 1000 & 0xFFFFFFFF


def payload_for(host: str) -> int:
    """Path-aware packet size: loopback peers get large datagrams."""
    import ipaddress

    try:
        if ipaddress.ip_address(host).is_loopback:
            return LOOPBACK_PAYLOAD
    except ValueError:
        pass
    return MAX_PAYLOAD


def _seq_lte(a: int, b: int) -> bool:
    """True if a <= b in mod-2^16 sequence space."""
    return ((b - a) & 0xFFFF) < 0x8000


def _seq_lt(a: int, b: int) -> bool:
    return a != b and _seq_lte(a, b)


def encode_packet(ptype: int, conn_id: int, ts: int, ts_diff: int,
                  wnd: int, seq: int, ack: int,
                  sack: bytes = b"", payload: bytes = b"") -> bytes:
    ext = EXT_SACK if sack else 0
    head = _HEADER.pack((ptype << 4) | VERSION, ext, conn_id,
                        ts, ts_diff, wnd, seq, ack)
    if sack:
        # extension chain: [next_ext=0, len, bitmask]
        head += bytes((0, len(sack))) + sack
    return head + payload


class PacketError(ValueError):
    pass


def decode_packet(data: bytes):
    """-> (type, conn_id, ts, ts_diff, wnd, seq, ack, sack_mask, payload)

    The payload is a zero-copy memoryview into ``data`` (60 KiB loopback
    datagrams made the per-packet slice copy a measurable term — r4); it
    compares equal to bytes and feeds ``StreamReader.feed_data``
    directly."""
    if len(data) < HEADER_SIZE:
        raise PacketError("short packet")
    (tv, ext, conn_id, ts, ts_diff, wnd, seq, ack) = _HEADER.unpack_from(data)
    if tv & 0x0F != VERSION:
        raise PacketError("bad version")
    ptype = tv >> 4
    if ptype > ST_SYN:
        raise PacketError("bad type")
    offset = HEADER_SIZE
    sack = b""
    # walk the extension chain
    while ext:
        if offset + 2 > len(data):
            raise PacketError("truncated extension")
        next_ext = data[offset]
        length = data[offset + 1]
        if offset + 2 + length > len(data):
            raise PacketError("truncated extension body")
        if ext == EXT_SACK:
            sack = data[offset + 2:offset + 2 + length]
        ext = next_ext
        offset += 2 + length
    return (ptype, conn_id, ts, ts_diff, wnd, seq, ack, sack,
            memoryview(data)[offset:])


class _Inflight:
    """One unacked outgoing ST_DATA/ST_FIN packet."""

    __slots__ = ("seq", "ptype", "payload", "sent_at", "transmissions",
                 "need_resend")

    def __init__(self, seq: int, ptype: int, payload: bytes):
        self.seq = seq
        self.ptype = ptype
        self.payload = payload
        self.sent_at = 0.0
        self.transmissions = 0
        self.need_resend = False


class UtpWriter:
    """StreamWriter-compatible facade over a :class:`UtpConnection`."""

    def __init__(self, conn: "UtpConnection"):
        self._conn = conn

    def write(self, data: bytes) -> None:
        self._conn._write(data)

    async def drain(self) -> None:
        await self._conn._drain()

    def close(self) -> None:
        self._conn._close()

    async def wait_closed(self) -> None:
        await self._conn._wait_closed()

    def is_closing(self) -> bool:
        return self._conn._closing or self._conn._closed

    def get_extra_info(self, name: str, default=None):
        if name == "peername":
            return self._conn.remote_addr
        if name == "sockname":
            return self._conn.endpoint.local_addr
        return default


class UtpConnection:
    """One uTP connection: reliability, ordering, LEDBAT, stream bridge."""

    def __init__(self, endpoint: "UtpEndpoint",
                 remote_addr: Tuple[str, int],
                 recv_id: int, send_id: int, seq: int, *,
                 connected: bool = False):
        self.endpoint = endpoint
        self.remote_addr = remote_addr
        self.recv_id = recv_id  # conn_id on packets we RECEIVE
        self.send_id = send_id  # conn_id on packets we SEND
        self.reader = asyncio.StreamReader()
        self.writer = UtpWriter(self)

        self._seq = seq  # next sequence number WE will consume
        self._ack = 0  # last in-order sequence we received
        self._connected = asyncio.Event()
        if connected:
            self._connected.set()

        self._inflight: Dict[int, _Inflight] = {}
        # seqs in send order: cumulative acks pop from the left, so ack
        # processing is O(newly acked), not O(window) — at a 4 MB window
        # an O(window) scan per ack is the throughput ceiling
        self._order: deque = deque()
        # loss-marked packets awaiting retransmission: _flush drains this
        # instead of scanning the whole inflight dict per datagram
        self._resend: deque = deque()
        self._flight_bytes = 0
        # send queue: deque of whole buffers + consumed-prefix offset.
        # The r3 bytearray (`del buf[:60KiB]` per packet) memmoved the
        # entire remaining window left on EVERY packetization — ~270 MB
        # of memmove per 32 MiB transferred at a 1 MiB buffer; profiled
        # as a first-order term of the per-packet bound (r4)
        self._send_q: deque = deque()
        self._send_q_len = 0
        self._send_off = 0
        self._send_lo = asyncio.Event()
        self._send_lo.set()
        # path-aware packet size (loopback gets large datagrams; the
        # throughput bound is per-packet processing, not bytes)
        self.max_payload = payload_for(remote_addr[0])
        self._min_cwnd = 2 * self.max_payload
        self._cwnd = 16 * self.max_payload  # slow-start-ish initial window
        self._peer_wnd = RECV_WINDOW
        self._ooo: Dict[int, Tuple[int, bytes]] = {}  # seq -> (type, data)
        self._eof_seq: Optional[int] = None

        self._rtt = 0.0
        self._rtt_var = 0.0
        self._rto = 1.0
        self._base_delay: Optional[int] = None
        self._reply_micro = 0
        self._dup_acks = 0
        self._last_ack_seen = -1

        self._ack_scheduled = False
        self._flush_scheduled = False  # write-coalescing (one loop turn)
        self._pending_acks = 0  # in-order data packets not yet acked
        self._ack_deadline = 0.0
        self._quenched_peer = False  # we advertised < one packet of room
        self._wnd_update_at = 0.0
        self._probe_at = 0.0
        self._last_recv = time.monotonic()
        self._closing = False  # FIN queued/sent
        self._closed = False  # fully torn down
        self._fin_seq: Optional[int] = None
        self._done = asyncio.Event()
        self._timer: Optional[asyncio.Task] = None
        self._drain_timer = None  # LAST_ACK courtesy window (TimerHandle)
        self._syn_packet: Optional[bytes] = None

    # -- lifecycle ------------------------------------------------------
    def start_timer(self) -> None:
        self._timer = asyncio.create_task(self._timeout_loop())

    def abort(self, exc: Optional[BaseException] = None) -> None:
        """Hard teardown: RESET received, too many timeouts, or endpoint
        shutdown."""
        if self._closed:
            return
        self._closed = True
        if exc is not None and not self.reader.at_eof():
            self.reader.set_exception(exc)
        else:
            self.reader.feed_eof()
        self._send_lo.set()
        self._connected.set()
        self._done.set()
        if self._timer is not None:
            self._timer.cancel()
        if self._drain_timer is not None:
            self._drain_timer.cancel()
            self._drain_timer = None
        self.endpoint._unregister(self)

    async def _timeout_loop(self) -> None:
        try:
            while not self._closed:
                await asyncio.sleep(0.05)
                self._check_timeouts()
        except asyncio.CancelledError:
            pass

    def _check_timeouts(self) -> None:
        now = time.monotonic()
        if now - self._last_recv > IDLE_TIMEOUT:
            self.abort(ConnectionResetError("uTP idle timeout"))
            return
        # delayed-ack safety valve: an odd trailing packet (or a sender
        # pausing mid-window) gets its ack at the deadline, far inside
        # the sender's MIN_RTO
        if self._pending_acks and now >= self._ack_deadline:
            self._send_ack()
        if self._connected.is_set():
            self._check_zero_window(now)
        if not self._inflight:
            return
        oldest = min(self._inflight.values(), key=lambda p: p.sent_at)
        if now - oldest.sent_at < self._rto:
            # tail-loss probe (TCP TLP style): a lost LAST packet of a
            # burst generates no dup-acks, so without this the only
            # recovery is the full MIN_RTO (500 ms) — a massive stall
            # against sub-ms loopback RTTs (r5: occasional swarm runs
            # lost ~30% throughput to exactly these).  After a quiet
            # period of ~2 RTT (floored well above ack-coalescing
            # delays), re-send the NEWEST unacked packet once; if the
            # tail was lost the ack (or dup-ack chain) restarts
            # recovery, and a spurious probe costs one duplicate the
            # receiver discards.
            newest = max(self._inflight.values(), key=lambda p: p.sent_at)
            quiet = max(2 * self._rtt + 4 * self._rtt_var,
                        2 * DELAYED_ACK_TIMEOUT)
            if (now - newest.sent_at > quiet
                    and now - self._last_recv > quiet
                    and newest.transmissions == 1):
                self._transmit(newest)
            return
        if oldest.transmissions > MAX_RETRANSMITS:
            self.abort(ConnectionResetError("uTP retransmit limit"))
            return
        # timeout: multiplicative backoff, shrink to min window, resend
        # the oldest now; the rest stay marked and go out ack-clocked
        # (every arriving datagram flushes marked packets), so recovery
        # never bursts a full window into an already-lossy path
        self._rto = min(self._rto * 2, 16.0)
        self._cwnd = self._min_cwnd
        for pkt in self._inflight.values():
            if not pkt.need_resend:
                pkt.need_resend = True
                self._resend.append(pkt)
        self._transmit(oldest)

    def _check_zero_window(self, now: float) -> None:
        """Break the mutual zero-window stall.

        Acks are only ever sent in response to data, so once the receiver
        advertises wnd=0 and the sender's flight drains, neither side has
        a reason to transmit again — without this, the connection sits
        dead until IDLE_TIMEOUT.  Two complementary escapes:

        - receiver side: we quenched the sender (advertised < one packet)
          but the consumer has since drained the buffer — send an
          unsolicited ST_STATE carrying the reopened window.
        - sender side: the peer advertises no room and nothing is in
          flight — probe with ONE packet past the window (RTO-paced, like
          a TCP window probe); the forced ack carries the peer's current
          window even if the probe itself is dropped at the backstop.
        """
        if (self._quenched_peer
                and self._recv_window() >= self.max_payload
                and now - self._wnd_update_at >= max(self._rto, MIN_RTO)):
            # repeat RTO-paced until data flows again (_handle_data
            # disarms the flag): the update is a bare UDP datagram, and
            # a one-shot that gets dropped would re-create the very
            # stall it exists to break
            self._wnd_update_at = now
            self._send_ack()
        if (self._send_q_len and not self._inflight
                and self._peer_wnd < self.max_payload
                and now - self._probe_at >= max(self._rto, MIN_RTO)):
            self._probe_at = now
            # TCP-window-probe style: ONE byte past the window, so a
            # stalled receiver's buffer overshoot is bounded to ~nothing
            # (a full chunk per RTO would pile up toward the 4x backstop)
            self._send_next_chunk(limit=1)

    # -- connect (initiator side) --------------------------------------
    def send_syn(self) -> None:
        # SYN carries conn_id_recv (every other packet carries send_id)
        # and consumes seq 1; retransmission is owned by wait_connected,
        # not the regular inflight machinery
        self._syn_packet = encode_packet(
            ST_SYN, self.recv_id, _now_us(), 0, RECV_WINDOW,
            self._seq, 0,
        )
        self._seq = (self._seq + 1) & 0xFFFF
        self._transmit_raw(self._syn_packet)

    async def wait_connected(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        delay = 1.0
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.abort()
                raise TimeoutError("uTP connect timed out")
            try:
                async with asyncio.timeout(min(delay, remaining)):
                    await self._connected.wait()
            except TimeoutError:
                if self._syn_packet is not None:
                    self._transmit_raw(self._syn_packet)
                delay *= 2
                continue
            if self._closed:
                raise ConnectionRefusedError("uTP connection refused")
            return

    # -- receive path ---------------------------------------------------
    def on_datagram(self, packet) -> None:
        """Handle one already-decoded packet tuple (the endpoint decodes
        exactly once, for routing and for us — r3 decoded twice)."""
        (ptype, _cid, ts, ts_diff, wnd, seq, ack, sack, payload) = packet
        if self._closed:
            # draining (LAST_ACK courtesy): keep acking the peer's
            # remaining in-order data/FIN — payloads are discarded (our
            # reader is gone), the cumulative ack is what lets the
            # peer's own close finish without retransmit stalls
            if ptype in (ST_DATA, ST_FIN) and self._drain_timer is not None:
                self._reply_micro = (_now_us() - ts) & 0xFFFFFFFF
                if seq == ((self._ack + 1) & 0xFFFF):
                    self._ack = seq
                self._send_ack()
                if ptype == ST_FIN and seq == self._ack:
                    # both directions now closed and acked: no reason
                    # to hold the socket/routing slot for the rest of
                    # the linger (review r5 — churning swarms would
                    # accumulate a dead socket per close otherwise)
                    self._drain_timer.cancel()
                    self._unregister_after_drain()
            return
        self._last_recv = time.monotonic()
        self._reply_micro = (_now_us() - ts) & 0xFFFFFFFF
        self._peer_wnd = wnd

        if ptype == ST_RESET:
            self.abort(ConnectionResetError("uTP connection reset by peer"))
            return

        if not self._connected.is_set():
            if ptype in (ST_STATE, ST_DATA, ST_FIN):
                # acceptor's reply: its seq_nr is the next it will send
                self._ack = (seq - 1) & 0xFFFF
                self._connected.set()
            # fall through: the packet's ack/payload still matter
        self._handle_ack(ack, sack, ts_diff)

        if ptype in (ST_DATA, ST_FIN):
            in_order = self._handle_data(ptype, seq, payload)
            self._pending_acks += 1
            if self._pending_acks == 1:
                self._ack_deadline = (time.monotonic()
                                      + DELAYED_ACK_TIMEOUT)
            # immediate ack on anything irregular (dup-ack fast
            # retransmit depends on it) or every Nth in-order packet;
            # in between, the timer tick flushes (delayed ack).  The
            # call_soon coalesces a burst already queued on the loop
            # into ONE ack with SACK state as of the last packet.
            if ((not in_order or ptype == ST_FIN
                 or self._pending_acks >= DELAYED_ACK_EVERY)
                    and not self._ack_scheduled):
                self._ack_scheduled = True
                asyncio.get_running_loop().call_soon(self._flush_ack)
        elif ptype == ST_SYN:
            # duplicate SYN (our ST_STATE got lost): re-ack it
            self._send_ack()
        self._flush()
        if (self._closed and self._drain_timer is not None
                and self._eof_seq is not None):
            # this very datagram both completed our retire (its ack
            # covered our FIN) and carried the peer's FIN: once the
            # already-scheduled ack flushes (call_soon FIFO), the
            # handshake is done — end the drain instead of holding the
            # slot/socket for the linger (simultaneous-close case the
            # closed-branch early-exit above cannot see).  The timer
            # attr stays set until the deferred call so _flush_ack
            # still treats the connection as drain-alive and sends the
            # FIN's ack first.
            self._drain_timer.cancel()
            asyncio.get_running_loop().call_soon(
                self._unregister_after_drain)

    def _flush_ack(self) -> None:
        self._ack_scheduled = False
        # draining counts as alive for acking: a FIN's ack scheduled
        # just before our own retire must still go out, or the peer
        # retransmits into silence (r5)
        if self._pending_acks and (not self._closed
                                   or self._drain_timer is not None):
            self._send_ack()

    def _handle_data(self, ptype: int, seq: int, payload: bytes) -> bool:
        """Returns True for the plain in-order case (eligible for a
        delayed ack); False for anything that must be acked NOW —
        duplicates (stop the retransmitting sender), reordering (feed
        the sender's dup-ack fast retransmit), backstop drops."""
        # data arriving means the sender knows our window again; if the
        # consumer stalls once more, _recv_window re-arms the flag
        self._quenched_peer = False
        # hard backstop behind the advertised window: a sender that
        # ignores flow control must not balloon the reader buffer (the
        # dropped packet goes unacked, so a compliant-after-all sender
        # just retransmits once the consumer catches up)
        if len(self.reader._buffer) > 4 * RECV_WINDOW:  # noqa: SLF001
            return False
        nxt = (self._ack + 1) & 0xFFFF
        if _seq_lt(seq, nxt):
            return False  # duplicate
        if seq != nxt:
            if len(self._ooo) < MAX_OOO:
                self._ooo.setdefault(seq, (ptype, payload))
            return False
        filled_gap = bool(self._ooo)
        self._deliver(ptype, payload)
        self._ack = seq
        # drain any now-in-order packets
        while True:
            nxt = (self._ack + 1) & 0xFFFF
            entry = self._ooo.pop(nxt, None)
            if entry is None:
                break
            self._deliver(entry[0], entry[1])
            self._ack = nxt
        # a retransmission that fills a reordering gap must be acked NOW
        # (the cumulative ack jumps past the sacked range; delaying it
        # would hold the sender's flight bytes for up to the timer tick),
        # as must anything leaving further gaps behind
        return not filled_gap and not self._ooo

    def _deliver(self, ptype: int, payload: bytes) -> None:
        if ptype == ST_FIN:
            self._eof_seq = 1  # marker; eof fires below
            if not self.reader.at_eof():
                self.reader.feed_eof()
            # no more data will be accepted; if our FIN is also done,
            # the connection can retire
            if self._closing and not self._inflight and not self._send_q_len:
                self._retire()
            return
        if payload and self._eof_seq is None and not self._closed:
            # the _closed guard: a datagram can FIRST ack our FIN
            # (retiring us, reader EOF'd) and ALSO carry in-order data
            # — half-close with the peer still streaming; feeding a
            # finished reader raises, killing the whole recv batch
            # (review r5).  The data is discarded; the cumulative ack
            # still flows from the drain path.
            self.reader.feed_data(payload)

    # -- ack / congestion path ------------------------------------------
    def _handle_ack(self, ack: int, sack: bytes, ts_diff: int) -> None:
        acked_bytes = 0
        now = time.monotonic()
        while self._order and _seq_lte(self._order[0], ack):
            seq = self._order.popleft()
            pkt = self._inflight.pop(seq, None)
            if pkt is None:
                continue  # already sacked away
            acked_bytes += len(pkt.payload)
            self._flight_bytes -= len(pkt.payload)
            if pkt.transmissions == 1:
                self._update_rtt(now - pkt.sent_at)
        if sack:
            acked_bytes += self._handle_sack(ack, sack)
        if acked_bytes:
            self._dup_acks = 0
            self._ledbat(acked_bytes, ts_diff)
        elif ack == self._last_ack_seen and self._inflight:
            self._dup_acks += 1
            if self._dup_acks == 3:
                # fast retransmit of the earliest unacked packet
                earliest = min(self._inflight, key=lambda s: (s - ack) & 0xFFFF)
                self._transmit(self._inflight[earliest])
                self._cwnd = max(self._cwnd // 2, self._min_cwnd)
        self._last_ack_seen = ack
        if self._send_buf_low():
            self._send_lo.set()
        if (self._closing and self._fin_seq is not None
                and self._fin_seq not in self._inflight):
            self._retire()

    def _handle_sack(self, ack: int, mask: bytes) -> int:
        """Selective ack: bit n covers seq ``ack + 2 + n``.  Returns bytes
        newly acked; packets below a thrice-sacked horizon are resent."""
        acked = 0
        highest_sacked = None
        sacked_count = 0
        for n in range(len(mask) * 8):
            if not mask[n >> 3] & (1 << (n & 7)):
                continue
            seq = (ack + 2 + n) & 0xFFFF
            sacked_count += 1
            highest_sacked = seq
            pkt = self._inflight.pop(seq, None)
            if pkt is not None:
                acked += len(pkt.payload)
                self._flight_bytes -= len(pkt.payload)
        if highest_sacked is not None and sacked_count >= 3:
            for seq, pkt in self._inflight.items():
                if _seq_lt(seq, highest_sacked) and not pkt.need_resend:
                    pkt.need_resend = True
                    self._transmit(pkt)  # clears the flag; no queue entry
        return acked

    def _update_rtt(self, sample: float) -> None:
        if self._rtt == 0.0:
            self._rtt, self._rtt_var = sample, sample / 2
        else:
            delta = abs(sample - self._rtt)
            self._rtt_var += (delta - self._rtt_var) / 4
            self._rtt += (sample - self._rtt) / 8
        self._rto = max(self._rtt + 4 * self._rtt_var, MIN_RTO)

    def _ledbat(self, acked_bytes: int, ts_diff: int) -> None:
        """RFC 6817-style window update from the echoed one-way delay."""
        if ts_diff:
            if self._base_delay is None or ts_diff < self._base_delay:
                self._base_delay = ts_diff
            queuing = ts_diff - self._base_delay
            off_target = (TARGET_DELAY_US - queuing) / TARGET_DELAY_US
        else:
            off_target = 1.0
        window_factor = min(acked_bytes / max(self._cwnd, 1), 1.0)
        self._cwnd += int(
            MAX_CWND_INCREASE_PER_RTT * off_target * window_factor
        )
        self._cwnd = max(self._min_cwnd, min(self._cwnd, 4 << 20))

    # -- send path ------------------------------------------------------
    def _write(self, data: bytes) -> None:
        if self._closing or self._closed:
            raise ConnectionResetError("uTP writer is closed")
        if data:
            # bytes(bytes) is a refcount bump, not a copy; memoryview/
            # bytearray callers get the one defensive copy the old
            # bytearray-append also paid
            self._send_q.append(data if isinstance(data, bytes)
                                else bytes(data))
            self._send_q_len += len(data)
        if not self._send_buf_low():
            self._send_lo.clear()
        # packetize one loop turn later, not per write: a pipelined
        # serve loop writes many 16 KiB blocks back-to-back in one turn,
        # and flushing each immediately emitted one UNDERSIZED datagram
        # per block (~2.7k packets per 32 MiB instead of ~560 at the
        # 60 KiB loopback payload — r5 profile).  Deferring lets the
        # burst coalesce into full datagrams; ack-clocked refills
        # (_handle_ack -> _flush) stay immediate.
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._deferred_flush)

    def _deferred_flush(self) -> None:
        self._flush_scheduled = False
        if not self._closed:
            self._flush()

    def _send_buf_low(self) -> bool:
        return self._send_q_len < RECV_WINDOW // 2

    async def _drain(self) -> None:
        if self._closed and self._send_q_len:
            raise ConnectionResetError("uTP connection closed")
        await self._send_lo.wait()

    def _flush(self) -> None:
        """Packetize the send buffer up to the congestion/peer window,
        resending loss-marked packets first (they already occupy flight
        bytes, so retransmitting them never grows the window)."""
        if not self._connected.is_set() or self._closed:
            return
        while self._resend:
            pkt = self._resend.popleft()
            # stale entries: acked away since marking, or already resent
            if pkt.need_resend and pkt.seq in self._inflight:
                self._transmit(pkt)
        window = min(self._cwnd, self._peer_wnd)
        while self._send_q_len and self._flight_bytes < window:
            self._send_next_chunk()
        if self._send_buf_low():
            self._send_lo.set()
        if (self._closing and not self._send_q_len
                and self._fin_seq is None):
            self._send_fin()

    def _take_chunk(self, size: int) -> bytes:
        """Dequeue up to ``size`` bytes: whole queued buffers pass
        through with zero copies; a partially-consumed head advances an
        offset instead of memmoving the remainder."""
        parts = []
        need = size
        while need and self._send_q:
            head = self._send_q[0]
            avail = len(head) - self._send_off
            if avail <= need:
                parts.append(memoryview(head)[self._send_off:]
                             if self._send_off else head)
                self._send_q.popleft()
                self._send_off = 0
                need -= avail
            else:
                parts.append(
                    memoryview(head)[self._send_off:self._send_off + need])
                self._send_off += need
                need = 0
        self._send_q_len -= size - need
        if len(parts) == 1:
            part = parts[0]
            return part if isinstance(part, bytes) else bytes(part)
        return b"".join(parts)

    def _send_next_chunk(self, limit: Optional[int] = None) -> None:
        """Packetize and transmit one chunk off the send buffer."""
        size = self.max_payload if limit is None else min(limit, self.max_payload)
        chunk = self._take_chunk(min(size, self._send_q_len))
        pkt = _Inflight(self._seq, ST_DATA, chunk)
        self._inflight[self._seq] = pkt
        self._order.append(self._seq)
        self._seq = (self._seq + 1) & 0xFFFF
        self._flight_bytes += len(chunk)
        self._transmit(pkt)

    def _sack_mask(self) -> bytes:
        if not self._ooo:
            return b""
        mask = bytearray(8)  # 64 seqs of lookahead, multiple-of-4 length
        base = (self._ack + 2) & 0xFFFF
        for seq in self._ooo:
            n = (seq - base) & 0xFFFF
            if n < 64:
                mask[n >> 3] |= 1 << (n & 7)
        return bytes(mask)

    def _send_ack(self) -> None:
        self._pending_acks = 0  # cumulative: covers everything pending
        self._transmit_raw(encode_packet(
            ST_STATE, self.send_id, _now_us(), self._reply_micro,
            self._recv_window(), self._seq, self._ack,
            sack=self._sack_mask(),
        ))

    def _recv_window(self) -> int:
        # StreamReader buffers internally; advertise the remaining slack
        # so a stalled consumer eventually quenches the sender
        buffered = len(self.reader._buffer)  # noqa: SLF001 - stdlib attr
        wnd = max(RECV_WINDOW - buffered, 0)
        if wnd < self.max_payload:
            self._quenched_peer = True
        return wnd

    def _transmit(self, pkt: _Inflight) -> None:
        pkt.sent_at = time.monotonic()
        pkt.transmissions += 1
        pkt.need_resend = False
        self._transmit_raw(encode_packet(
            pkt.ptype, self.send_id, _now_us(), self._reply_micro,
            self._recv_window(), pkt.seq, self._ack, payload=pkt.payload,
        ))

    def _transmit_raw(self, data: bytes) -> None:
        self.endpoint._send(data, self.remote_addr)

    # -- close ----------------------------------------------------------
    def _send_fin(self) -> None:
        self._fin_seq = self._seq
        pkt = _Inflight(self._seq, ST_FIN, b"")
        self._inflight[self._seq] = pkt
        self._order.append(self._seq)
        self._seq = (self._seq + 1) & 0xFFFF
        self._transmit(pkt)

    def _close(self) -> None:
        if self._closing or self._closed:
            return
        self._closing = True
        if self._connected.is_set():
            self._flush()  # queues the FIN once the buffer drains
        else:
            self.abort()

    def _retire(self) -> None:
        """Graceful completion: our FIN is acked and the buffer is empty."""
        if self._closed:
            return
        self._closed = True
        if not self.reader.at_eof():
            self.reader.feed_eof()
        self._send_lo.set()
        self._done.set()
        if self._timer is not None:
            self._timer.cancel()
        if self._eof_seq is not None:
            # both directions finished: fully gone
            self.endpoint._unregister(self)
        else:
            # the peer hasn't closed its direction yet: drain — stay
            # registered to ack its remaining data/FIN (on_datagram's
            # closed-branch) so its close completes promptly, then
            # unregister after the courtesy window
            loop = asyncio.get_running_loop()
            self._drain_timer = loop.call_later(
                LAST_ACK_LINGER, self._unregister_after_drain)

    def _unregister_after_drain(self) -> None:
        self._drain_timer = None
        self.endpoint._unregister(self)

    async def _wait_closed(self) -> None:
        if not self._closing and not self._closed:
            return
        try:
            async with asyncio.timeout(FIN_LINGER):
                await self._done.wait()
        except TimeoutError:
            self.abort()


class _RawUdpTransport:
    """Minimal datagram transport over a nonblocking UDP socket with a
    DRAINING read loop: one event-loop wakeup processes up to
    ``RECV_BATCH`` queued datagrams instead of one.

    asyncio's ``_SelectorDatagramTransport`` does exactly one recvfrom
    per selector wakeup, so a burst of queued datagrams pays the full
    loop round-trip (callback scheduling, selector re-entry) per packet
    — profiled as a first-order share of uTP's per-packet budget.
    Draining amortizes that across the batch; the cap keeps one busy
    socket from starving the rest of the loop.  The surface mirrors the
    subset of DatagramTransport the endpoint (and the test suite's
    lossy wrappers) use: ``sendto``/``close``/``is_closing``/
    ``get_extra_info``.
    """

    RECV_BATCH = 64

    def __init__(self, loop, sock, recv_cb, error_cb):
        self._loop = loop
        self._sock = sock
        self._recv_cb = recv_cb
        self._error_cb = error_cb
        self._closing = False
        loop.add_reader(sock.fileno(), self._on_readable)

    def _on_readable(self) -> None:
        for _ in range(self.RECV_BATCH):
            if self._closing:
                return
            try:
                data, addr = self._sock.recvfrom(65536)
            except (BlockingIOError, InterruptedError):
                return
            except OSError as exc:
                # connected-UDP sockets surface ICMP errors here
                self._error_cb(exc)
                return
            self._recv_cb(data, addr)

    def sendto(self, data, addr=None) -> None:
        if self._closing:
            return
        try:
            if addr is None:
                self._sock.send(data)
            else:
                self._sock.sendto(data, addr)
        except (BlockingIOError, InterruptedError):
            # kernel send buffer full: drop — UDP semantics, the
            # reliability layer retransmits
            pass
        except OSError as exc:
            self._error_cb(exc)

    def get_extra_info(self, name: str, default=None):
        if name == "socket":
            return self._sock
        if name == "sockname":
            try:
                return self._sock.getsockname()
            except OSError:
                return default
        return default

    def is_closing(self) -> bool:
        return self._closing

    def close(self) -> None:
        if self._closing:
            return
        self._closing = True
        try:
            self._loop.remove_reader(self._sock.fileno())
        except (OSError, ValueError):
            pass
        self._sock.close()


class _FallbackDatagramProtocol(asyncio.DatagramProtocol):
    """Adapter used when the event loop has no ``add_reader`` (Windows'
    default ProactorEventLoop): routes asyncio's one-datagram-per-wakeup
    transport callbacks into the endpoint.  Slower than the draining
    raw transport, but the stack stays functional on every loop."""

    def __init__(self, endpoint: "UtpEndpoint"):
        self._endpoint = endpoint

    def datagram_received(self, data, addr) -> None:
        self._endpoint.datagram_received(data, addr)

    def error_received(self, exc) -> None:
        self._endpoint.error_received(exc)


class UtpEndpoint:
    """A UDP socket multiplexing uTP connections.

    One endpoint per listen socket (acceptor side, ``accept_cb`` invoked
    per incoming connection like ``asyncio.start_server``), or per
    outgoing connection (connected-UDP socket, so ICMP port-unreachable
    surfaces as a fast ``ConnectionRefusedError`` instead of a timeout).
    """

    def __init__(self, accept_cb: Optional[Callable] = None):
        self.accept_cb = accept_cb
        self._conns: Dict[Tuple[Tuple[str, int], int], UtpConnection] = {}
        # _RawUdpTransport normally; asyncio's DatagramTransport on
        # loops without add_reader — only the shared sendto/close/
        # is_closing/get_extra_info subset may be called on it
        self._transport: Union[_RawUdpTransport,
                               asyncio.DatagramTransport, None] = None
        # set ONLY on the fallback transport of a connected socket: the
        # stock transports need an explicit sockaddr there (proactor's
        # WSASendTo rejects addr=None; _RawUdpTransport uses send())
        self._fallback_peer: Optional[tuple] = None
        self._remote: Optional[Tuple[str, int]] = None
        self.local_addr: Optional[Tuple[str, int]] = None
        self._accept_tasks: set = set()
        self._closed = False

    @classmethod
    async def create(cls, host: str = "0.0.0.0", port: int = 0,
                     accept_cb: Optional[Callable] = None,
                     remote_addr: Optional[Tuple[str, int]] = None,
                     ) -> "UtpEndpoint":
        import socket as _socket

        self = cls(accept_cb)
        loop = asyncio.get_running_loop()
        if remote_addr is not None:
            infos = await loop.getaddrinfo(
                remote_addr[0], remote_addr[1], type=_socket.SOCK_DGRAM)
        else:
            infos = await loop.getaddrinfo(
                host, port, type=_socket.SOCK_DGRAM,
                flags=_socket.AI_PASSIVE)
        # try every addrinfo entry (create_datagram_endpoint's family
        # fallback: an IPv6-first resolution on an IPv6-disabled host
        # must fall through to AF_INET, not fail the endpoint)
        last_exc: Optional[OSError] = None
        for family, stype, proto, _cn, target in infos:
            try:
                sock = _socket.socket(family, stype, proto)
            except OSError as exc:
                last_exc = exc
                continue
            try:
                sock.setblocking(False)
                if remote_addr is not None:
                    # UDP connect: instant, enables fast ICMP errors
                    sock.connect(target)
                    self._remote = remote_addr
                else:
                    sock.bind(target)
            except OSError as exc:
                # failure must not leak the fd (the old
                # create_datagram_endpoint closed it for us)
                sock.close()
                last_exc = exc
                continue
            break
        else:
            raise last_exc or OSError("getaddrinfo returned no usable address")
        try:
            # default UDP buffers (~208 KiB) overflow under window-sized
            # bursts — the kernel drops the excess silently, which reads
            # as pathological "loss" even on loopback.  The kernel caps
            # this at net.core.{r,w}mem_max; no error when it does.
            for opt in (_socket.SO_RCVBUF, _socket.SO_SNDBUF):
                try:
                    sock.setsockopt(_socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass
            try:
                self._transport = _RawUdpTransport(
                    loop, sock, self.datagram_received, self.error_received)
            except NotImplementedError:
                # Proactor loops have no add_reader: fall back to the
                # stock datagram transport (correct, just unbatched).
                # sock= alone leaves the transport's _address unset, so
                # a connected socket must still pass an explicit peer
                # on every sendto (proactor's WSASendTo cannot take
                # addr=None; review r5)
                if remote_addr is not None:
                    self._fallback_peer = sock.getpeername()
                transport, _proto = await loop.create_datagram_endpoint(
                    lambda: _FallbackDatagramProtocol(self), sock=sock)
                self._transport = transport
            self.local_addr = sock.getsockname()[:2]
        except BaseException:
            sock.close()
            raise
        return self

    def error_received(self, exc: OSError) -> None:
        # connected-UDP sockets get ICMP unreachable here: fail fast
        if self._remote is not None:
            for conn in list(self._conns.values()):
                conn.abort(ConnectionRefusedError(str(exc)))

    def datagram_received(self, data: bytes, addr) -> None:
        addr = addr[:2]
        try:
            packet = decode_packet(data)
        except PacketError:
            return
        ptype, conn_id = packet[0], packet[1]
        if self._remote is not None:
            addr = self._remote  # connected socket: normalize the key
        conn = self._conns.get((addr, conn_id))
        if conn is not None:
            conn.on_datagram(packet)
            return
        if ptype == ST_SYN and self.accept_cb is not None:
            self._accept(packet, addr)
        elif ptype not in (ST_RESET, ST_SYN):
            # unknown connection: tell the sender to go away
            self._send(encode_packet(
                ST_RESET, conn_id, _now_us(), 0, 0, 0, 0), addr)

    def _accept(self, packet, addr) -> None:
        conn_id, seq = packet[1], packet[5]
        # SYN retransmit (our ST_STATE was lost or slow): the live
        # acceptor is registered under conn_id+1 — packets from the
        # initiator carry that id, but retransmitted SYNs still carry the
        # original.  Re-ack through the existing connection instead of
        # clobbering it with a fresh one (whose new random seq would
        # desynchronize the peer that handshook against the first).
        existing = self._conns.get((addr, (conn_id + 1) & 0xFFFF))
        if existing is not None:
            existing._send_ack()
            return
        if len(self._conns) >= MAX_ACCEPTED_CONNS:
            return  # flood bound: drop the SYN, no state minted
        conn = UtpConnection(
            self, addr,
            recv_id=(conn_id + 1) & 0xFFFF, send_id=conn_id,
            seq=random.randrange(1 << 16), connected=True,
        )
        conn._ack = seq  # the SYN consumed seq 1
        self._conns[(addr, conn.recv_id)] = conn
        conn.start_timer()
        conn._send_ack()  # ST_STATE completes the handshake
        task = asyncio.ensure_future(
            self.accept_cb(conn.reader, conn.writer))
        self._accept_tasks.add(task)
        task.add_done_callback(self._accept_tasks.discard)

    # -- dialing --------------------------------------------------------
    async def connect(self, host: str, port: int, timeout: float = 10.0,
                      ) -> Tuple[asyncio.StreamReader, UtpWriter]:
        recv_id = random.randrange(1 << 16)
        conn = UtpConnection(
            self, (host, port),
            recv_id=recv_id, send_id=(recv_id + 1) & 0xFFFF, seq=1,
        )
        self._conns[((host, port), recv_id)] = conn
        conn.start_timer()
        conn.send_syn()
        await conn.wait_connected(timeout)
        return conn.reader, conn.writer

    # -- plumbing -------------------------------------------------------
    def _send(self, data: bytes, addr) -> None:
        if self._transport is None or self._transport.is_closing():
            return
        if self._remote is not None:
            # connected socket: no addr for the raw transport (it uses
            # send()); the fallback transports need the explicit peer
            self._transport.sendto(data, self._fallback_peer)
        else:
            self._transport.sendto(data, addr)

    def _unregister(self, conn: UtpConnection) -> None:
        self._conns.pop((conn.remote_addr, conn.recv_id), None)
        if self._remote is not None and not self._closed:
            # single-connection outgoing endpoint: retire the socket
            self.close()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for conn in list(self._conns.values()):
            conn.abort(ConnectionResetError("endpoint closed"))
        for task in list(self._accept_tasks):
            task.cancel()
        if self._transport is not None:
            self._transport.close()


class _OwningWriter(UtpWriter):
    """Writer for one-shot outgoing connections: closing the stream also
    retires the ephemeral endpoint/socket behind it (matches the lifetime
    callers expect from ``asyncio.open_connection``)."""

    def __init__(self, conn: UtpConnection, endpoint: UtpEndpoint):
        super().__init__(conn)
        self._endpoint = endpoint

    async def wait_closed(self) -> None:
        await super().wait_closed()
        if self._conn._drain_timer is None:
            self._endpoint.close()
        # else: the LAST_ACK drain window owns the endpoint now — its
        # expiry unregisters the connection, which retires the
        # single-connection socket; closing here would slam the socket
        # shut before the peer's FIN can be acked (r5)


async def open_utp_connection(host: str, port: int, *,
                              timeout: float = 10.0,
                              ) -> Tuple[asyncio.StreamReader, UtpWriter]:
    """Dial ``host:port`` over uTP; drop-in for ``asyncio.open_connection``.

    Creates a dedicated connected-UDP socket so ICMP errors fail fast."""
    endpoint = await UtpEndpoint.create(remote_addr=(host, port))
    try:
        reader, writer = await endpoint.connect(host, port, timeout=timeout)
    except BaseException:
        endpoint.close()
        raise
    return reader, _OwningWriter(writer._conn, endpoint)
