"""BitTorrent peer wire protocol (BEP 3) + extension protocol (BEP 10) +
metadata exchange (BEP 9) + peer exchange (BEP 11).

One :class:`PeerWire` wraps an asyncio stream pair and is used by both sides:
the leeching client and the in-package seeder.
"""

from __future__ import annotations

import asyncio
import dataclasses
import socket
import struct
from typing import Iterable, List, Optional, Tuple

from .bencode import bdecode_prefix, bencode

PSTR = b"BitTorrent protocol"
# reserved byte 5, bit 0x10: extension protocol (BEP 10);
# reserved byte 7, bit 0x04: fast extension (BEP 6)
RESERVED = bytes([0, 0, 0, 0, 0, 0x10, 0, 0x04])

MSG_CHOKE = 0
MSG_UNCHOKE = 1
MSG_INTERESTED = 2
MSG_NOT_INTERESTED = 3
MSG_HAVE = 4
MSG_BITFIELD = 5
MSG_REQUEST = 6
MSG_PIECE = 7
MSG_CANCEL = 8
# BEP 6 fast extension
MSG_SUGGEST_PIECE = 13
MSG_HAVE_ALL = 14
MSG_HAVE_NONE = 15
MSG_REJECT_REQUEST = 16
MSG_ALLOWED_FAST = 17
MSG_EXTENDED = 20

EXT_HANDSHAKE_ID = 0
UT_METADATA = b"ut_metadata"
UT_PEX = b"ut_pex"
METADATA_PIECE_SIZE = 1 << 14

# ut_metadata msg_type values (BEP 9)
MD_REQUEST = 0
MD_DATA = 1
MD_REJECT = 2

MAX_MESSAGE = 1 << 21  # sanity bound: piece messages are ~16 KiB + header


class WireError(ConnectionError):
    pass


@dataclasses.dataclass
class Handshake:
    info_hash: bytes
    peer_id: bytes
    supports_extensions: bool
    supports_fast: bool = False


class PeerWire:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        # negotiated extension ids: ours (what we told the peer) and theirs
        self.our_ut_metadata = 1
        self.our_ut_pex = 2
        self.peer_ut_metadata: Optional[int] = None
        self.peer_ut_pex: Optional[int] = None
        self.peer_metadata_size: Optional[int] = None
        # the peer's advertised listen port (``p`` in the BEP 10 handshake)
        self.peer_listen_port: Optional[int] = None

    # -- handshake ------------------------------------------------------
    async def send_handshake(self, info_hash: bytes, peer_id: bytes) -> None:
        self.writer.write(
            bytes([len(PSTR)]) + PSTR + RESERVED + info_hash + peer_id
        )
        await self.writer.drain()

    async def recv_handshake(self) -> Handshake:
        header = await self.reader.readexactly(1)
        pstrlen = header[0]
        pstr = await self.reader.readexactly(pstrlen)
        if pstr != PSTR:
            raise WireError(f"unknown protocol {pstr!r}")
        reserved = await self.reader.readexactly(8)
        info_hash = await self.reader.readexactly(20)
        peer_id = await self.reader.readexactly(20)
        return Handshake(
            info_hash=info_hash,
            peer_id=peer_id,
            supports_extensions=bool(reserved[5] & 0x10),
            supports_fast=bool(reserved[7] & 0x04),
        )

    # -- framing --------------------------------------------------------
    async def send_message(self, msg_id: int, payload: bytes = b"") -> None:
        frame = struct.pack(">IB", len(payload) + 1, msg_id) + payload
        self.writer.write(frame)
        await self.writer.drain()

    async def send_keepalive(self) -> None:
        self.writer.write(b"\x00\x00\x00\x00")
        await self.writer.drain()

    async def recv_message(self) -> Tuple[Optional[int], bytes]:
        """Returns (msg_id, payload); (None, b'') for a keep-alive."""
        raw_len = await self.reader.readexactly(4)
        (length,) = struct.unpack(">I", raw_len)
        if length == 0:
            return None, b""
        if length > MAX_MESSAGE:
            raise WireError(f"oversized message ({length} bytes)")
        body = await self.reader.readexactly(length)
        return body[0], body[1:]

    # -- core messages --------------------------------------------------
    async def send_bitfield(self, have: "bytes") -> None:
        await self.send_message(MSG_BITFIELD, have)

    async def send_request(self, index: int, begin: int, length: int) -> None:
        await self.send_message(MSG_REQUEST, struct.pack(">III", index, begin, length))

    async def send_cancel(self, index: int, begin: int, length: int) -> None:
        await self.send_message(MSG_CANCEL, struct.pack(">III", index, begin, length))

    async def send_piece(self, index: int, begin: int, data: bytes) -> None:
        await self.send_message(MSG_PIECE, struct.pack(">II", index, begin) + data)

    async def send_have(self, index: int) -> None:
        await self.send_message(MSG_HAVE, struct.pack(">I", index))

    # -- fast extension (BEP 6) -----------------------------------------
    async def send_have_all(self) -> None:
        await self.send_message(MSG_HAVE_ALL)

    async def send_have_none(self) -> None:
        await self.send_message(MSG_HAVE_NONE)

    async def send_reject_request(self, index: int, begin: int,
                                  length: int) -> None:
        await self.send_message(
            MSG_REJECT_REQUEST, struct.pack(">III", index, begin, length)
        )

    # -- extension protocol ---------------------------------------------
    async def send_ext_handshake(self, metadata_size: Optional[int] = None,
                                 listen_port: Optional[int] = None) -> None:
        payload: dict = {b"m": {
            UT_METADATA: self.our_ut_metadata,
            UT_PEX: self.our_ut_pex,
        }}
        if metadata_size is not None:
            payload[b"metadata_size"] = metadata_size
        if listen_port is not None:
            payload[b"p"] = listen_port
        await self.send_message(
            MSG_EXTENDED, bytes([EXT_HANDSHAKE_ID]) + bencode(payload)
        )

    def handle_ext_handshake(self, payload: bytes) -> None:
        data, _ = bdecode_prefix(payload)
        m = data.get(b"m", {})
        if UT_METADATA in m:
            self.peer_ut_metadata = m[UT_METADATA]
        if UT_PEX in m:
            self.peer_ut_pex = m[UT_PEX]
        if b"metadata_size" in data:
            self.peer_metadata_size = data[b"metadata_size"]
        port = data.get(b"p")
        if isinstance(port, int) and 0 < port < 65536:
            self.peer_listen_port = port

    async def send_metadata_request(self, piece: int) -> None:
        if self.peer_ut_metadata is None:
            raise WireError("peer does not support ut_metadata")
        msg = bencode({b"msg_type": MD_REQUEST, b"piece": piece})
        await self.send_message(
            MSG_EXTENDED, bytes([self.peer_ut_metadata]) + msg
        )

    def _their_ut_metadata(self) -> int:
        # BEP 10: outgoing extended messages use the id the RECEIVER
        # advertised in its handshake; fall back to ours for peers that
        # requested before handshaking
        return self.peer_ut_metadata or self.our_ut_metadata

    async def send_metadata_data(self, piece: int, total_size: int, data: bytes) -> None:
        header = bencode(
            {b"msg_type": MD_DATA, b"piece": piece, b"total_size": total_size}
        )
        await self.send_message(
            MSG_EXTENDED, bytes([self._their_ut_metadata()]) + header + data
        )

    async def send_metadata_reject(self, piece: int) -> None:
        msg = bencode({b"msg_type": MD_REJECT, b"piece": piece})
        await self.send_message(
            MSG_EXTENDED, bytes([self._their_ut_metadata()]) + msg
        )

    # -- peer exchange (BEP 11) -----------------------------------------
    async def send_pex(self, added: Iterable[Tuple[str, int]],
                       dropped: Iterable[Tuple[str, int]] = ()) -> None:
        if self.peer_ut_pex is None:
            raise WireError("peer does not support ut_pex")
        msg = bencode({
            b"added": pack_compact_peers(added),
            b"added.f": b"",
            b"dropped": pack_compact_peers(dropped),
        })
        await self.send_message(
            MSG_EXTENDED, bytes([self.peer_ut_pex]) + msg
        )

    async def close(self) -> None:
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (ConnectionError, OSError):
            pass


def parse_bitfield(payload: bytes, num_pieces: int) -> set:
    have = set()
    for i in range(num_pieces):
        if payload[i // 8] & (0x80 >> (i % 8)):
            have.add(i)
    return have


def build_bitfield(have, num_pieces: int) -> bytes:
    out = bytearray((num_pieces + 7) // 8)
    for i in have:
        out[i // 8] |= 0x80 >> (i % 8)
    return bytes(out)


def pack_compact_peers(addrs: Iterable[Tuple[str, int]]) -> bytes:
    """IPv4 (host, port) pairs -> BEP 11/23 compact 6-byte entries.
    Non-IPv4 hosts are skipped on the send side (we gossip only ``added``;
    incoming ``added6`` is parsed by :func:`parse_pex`)."""
    out = bytearray()
    for host, port in addrs:
        try:
            out += socket.inet_aton(host) + struct.pack(">H", port)
        except OSError:
            continue
    return bytes(out)


def parse_pex(body: bytes) -> List[Tuple[str, int]]:
    """Extract usable (host, port) peers from a ut_pex message body
    (both the IPv4 ``added`` and IPv6 ``added6`` lists — same compact
    forms as tracker responses, so the tracker module's parsers own the
    decode)."""
    from .tracker import parse_compact_peers, parse_compact_peers6

    data, _ = bdecode_prefix(body)
    if not isinstance(data, dict):  # untrusted wire bytes
        return []
    out: List[Tuple[str, int]] = []
    added = data.get(b"added", b"")
    if isinstance(added, bytes):
        out.extend((p.host, p.port) for p in parse_compact_peers(added))
    added6 = data.get(b"added6", b"")
    if isinstance(added6, bytes):
        out.extend((p.host, p.port) for p in parse_compact_peers6(added6))
    return out
