"""A serving peer: pieces, ut_metadata, and peer exchange over the wire.

webtorrent both leeches and seeds (/root/reference/lib/download.js:19 keeps
one long-lived client); this is the serving half.  It doubles as the hermetic
swarm for tests (no network egress needed) and as the listen socket a
leeching :class:`~.client.TorrentClient` runs so replicas downloading the
same torrent can trade pieces (seed-while-leech).

Supports partially-available content: construct with ``have`` (a live,
possibly shared set of piece indices) and call :meth:`add_piece` as pieces
verify — connected peers get ``HAVE`` broadcasts (BEP 3).  Peers that
advertise a listen port in their BEP 10 handshake are gossiped to the rest
of the swarm via ut_pex (BEP 11).
"""

from __future__ import annotations

import asyncio
import os
import random
import struct
from typing import Dict, Optional, Set, Tuple

from . import mse, wire
from .metainfo import Metainfo
from .storage import TorrentStorage


class Seeder:
    """Serves one torrent's pieces from ``root`` on a local TCP port.

    ``have`` is the set of piece indices available to serve; ``None`` means
    the content is complete.  The set may be shared with (and mutated by) a
    downloading client — :meth:`add_piece` announces new pieces to every
    connected peer.
    """

    def __init__(self, meta: Metainfo, root: Optional[str] = None,
                 peer_id: Optional[bytes] = None,
                 storage: Optional[TorrentStorage] = None,
                 have: Optional[Set[int]] = None,
                 unchoke_slots: int = 4,
                 rotate_interval: float = 10.0,
                 optimistic_interval: float = 30.0,
                 crypto: str = "prefer"):
        if storage is None:
            if root is None:
                raise ValueError("need root or storage")
            storage = TorrentStorage(meta, root)
        self.meta = meta
        self.storage = storage
        self.have = have  # live reference; None = everything
        self.peer_id = peer_id or (b"-DT0001-" + os.urandom(6).hex().encode())
        self._server: Optional[asyncio.base_events.Server] = None
        self._utp = None  # UtpEndpoint once started (uTP accept path)
        self.port: Optional[int] = None
        self.connections: int = 0
        self.bytes_served: int = 0
        self._conn_tasks: Set[asyncio.Task] = set()
        self._peers: Set[wire.PeerWire] = set()
        # peers that advertised a listen port: PeerWire -> (host, port)
        self._listen_addrs: Dict[wire.PeerWire, Tuple[str, int]] = {}
        # -- choking (tit-for-tat + optimistic, like webtorrent's engine;
        # /root/reference/lib/download.js:9,19 — its torrent-stream core
        # slot-limits uploads so one peer cannot monopolize a seeder).
        # Regular slots go to the interested peers we served the most
        # bytes in the last rotation window (a seed reciprocates to the
        # peers actually draining it); one extra optimistic slot rotates
        # through the remaining interested peers so newcomers get a
        # chance to prove themselves.
        self.unchoke_slots = unchoke_slots
        self.rotate_interval = rotate_interval
        self.optimistic_interval = optimistic_interval
        # MSE acceptor policy: "require" = RC4-only payload; anything
        # else selects plaintext-after-handshake when the initiator
        # allows it (mse.accept docstring)
        self.crypto = crypto
        self._interested: Set[wire.PeerWire] = set()
        self._unchoked: Set[wire.PeerWire] = set()
        self._optimistic: Optional[wire.PeerWire] = None
        self._served_window: Dict[wire.PeerWire, int] = {}
        self._choker_task: Optional[asyncio.Task] = None

    def _available(self, index: int) -> bool:
        return self.have is None or index in self.have

    def _have_indices(self):
        return range(self.meta.num_pieces) if self.have is None else self.have

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    utp: bool = True) -> int:
        self._server = await asyncio.start_server(self._on_connect, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        if utp:
            # uTP listener on the SAME port number over UDP (BEP 29
            # convention — webtorrent serves both transports on one port,
            # /root/reference/lib/download.js:19).  The accept path is
            # shared, so uTP peers get MSE sniffing, ut_pex, the lot.
            from .utp import UtpEndpoint

            try:
                self._utp = await UtpEndpoint.create(
                    host, self.port, accept_cb=self._on_connect)
            except OSError:
                self._utp = None  # UDP port taken: TCP-only is still fine
        self._choker_task = asyncio.create_task(self._choke_loop())
        return self.port

    async def stop(self) -> None:
        if self._choker_task is not None:
            self._choker_task.cancel()
            try:
                await self._choker_task
            except (asyncio.CancelledError, Exception):
                pass
            self._choker_task = None
        if self._utp is not None:
            self._utp.close()
            self._utp = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in list(self._conn_tasks):
            task.cancel()
        for task in list(self._conn_tasks):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self.storage.close()  # drop cached fds (reopen-on-use if shared)

    async def add_piece(self, index: int) -> None:
        """Record a newly available piece and HAVE-broadcast it (BEP 3).

        Broadcasts run as background tasks: one stalled connection (a peer
        that stops reading, filling our write buffer) must not block the
        caller — for the seed-while-leech path the caller is the download's
        control loop.
        """
        if self.have is not None:
            self.have.add(index)
        for peer in list(self._peers):
            task = asyncio.create_task(self._quiet_send(peer.send_have(index)))
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)

    @staticmethod
    async def _quiet_send(coro) -> None:
        """Await a peer send, swallowing death-of-connection errors —
        the peer's own serve loop does the cleanup."""
        try:
            await coro
        except (ConnectionError, OSError, wire.WireError):
            pass

    # -- choking --------------------------------------------------------
    def is_unchoked(self, peer: wire.PeerWire) -> bool:
        return peer in self._unchoked

    async def _choke_loop(self) -> None:
        """Periodic tit-for-tat recompute; every ``optimistic_interval``
        the optimistic slot moves to a different interested-but-choked
        peer (the classic 10 s / 30 s cadence at the defaults)."""
        loop = asyncio.get_running_loop()
        next_optimistic = loop.time()  # first pass seats an optimistic
        while True:
            await asyncio.sleep(self.rotate_interval)
            rotate = loop.time() >= next_optimistic
            if rotate:
                next_optimistic = loop.time() + self.optimistic_interval
            await self._recompute_chokes(rotate_optimistic=rotate)

    async def _recompute_chokes(self, rotate_optimistic: bool = False) -> None:
        interested = [p for p in self._peers if p in self._interested]
        # reciprocate to the peers that actually drained us last window;
        # ties (fresh swarm) keep whoever is already unchoked seated so
        # the steady state doesn't churn
        ranked = sorted(
            interested,
            key=lambda p: (self._served_window.get(p, 0),
                           p in self._unchoked),
            reverse=True,
        )
        regular = set(ranked[:self.unchoke_slots])
        if (rotate_optimistic or self._optimistic not in interested
                or self._optimistic in regular):
            candidates = [p for p in interested
                          if p not in regular and p is not self._optimistic]
            if candidates:
                self._optimistic = random.choice(candidates)
            elif (self._optimistic not in interested
                    or self._optimistic in regular):
                self._optimistic = None
        target = set(regular)
        if self._optimistic is not None:
            target.add(self._optimistic)
        for peer in list(self._unchoked - target):
            self._unchoked.discard(peer)
            await self._quiet_send(peer.send_message(wire.MSG_CHOKE))
        for peer in list(target - self._unchoked):
            self._unchoked.add(peer)
            await self._quiet_send(peer.send_message(wire.MSG_UNCHOKE))
        self._served_window = {}

    async def _maybe_decrypt(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter):
        """Sniff the first bytes: plaintext BT handshake passes through
        (with the consumed prefix replayed), anything else must complete
        the MSE accept handshake.  ``crypto="require"`` refuses the
        plaintext path entirely (libtorrent's require posture: drop
        unencrypted inbound, review r5) and forces RC4 in the MSE
        negotiation."""
        first = b""
        verdict = None
        async with asyncio.timeout(mse.HANDSHAKE_TIMEOUT):
            while verdict is None:
                first += await reader.readexactly(1)
                verdict = mse.looks_like_plaintext_bt(first)
        require_rc4 = self.crypto == "require"
        if verdict:
            if require_rc4:
                raise mse.MSEError("plaintext peer refused (crypto=require)")
            return mse.MSEReader(reader, None, plain_prefix=first), writer
        enc_reader, enc_writer, _method = await mse.accept(
            reader, writer, self.meta.info_hash, first_bytes=first,
            allow_plaintext=not require_rc4,
            prefer_plaintext=not require_rc4,
        )
        return enc_reader, enc_writer

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        # MSE/PE auto-detect (mse.py): a plaintext BitTorrent handshake
        # starts \x13"BitTorrent protocol"; anything else is treated as an
        # incoming MSE exchange.  Both kinds of peer are served.
        try:
            reader, writer = await self._maybe_decrypt(reader, writer)
        except (mse.MSEError, ConnectionError, OSError,
                asyncio.IncompleteReadError, TimeoutError):
            writer.close()
            return
        peer = wire.PeerWire(reader, writer)
        try:
            handshake = await peer.recv_handshake()
            if handshake.info_hash != self.meta.info_hash:
                await peer.close()
                return
            self.connections += 1
            peer.supports_fast = handshake.supports_fast
            await peer.send_handshake(self.meta.info_hash, self.peer_id)
            if handshake.supports_extensions:
                await peer.send_ext_handshake(
                    metadata_size=len(self.meta.info_bytes)
                )
            # register BEFORE snapshotting the bitfield, with no await in
            # between: a piece verified mid-handshake is then either in the
            # bitfield or HAVE-broadcast (never silently missed), and the
            # broadcast task cannot run before the bitfield is buffered
            self._peers.add(peer)
            if handshake.supports_fast and self.have is None:
                await peer.send_have_all()  # BEP 6: 5 bytes, any piece count
            elif handshake.supports_fast and not self.have:
                await peer.send_have_none()
            else:
                await peer.send_bitfield(wire.build_bitfield(
                    self._have_indices(), self.meta.num_pieces
                ))
            await self._serve(peer)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._peers.discard(peer)
            self._listen_addrs.pop(peer, None)
            self._interested.discard(peer)
            freed = peer in self._unchoked
            self._unchoked.discard(peer)
            self._served_window.pop(peer, None)
            if peer is self._optimistic:
                self._optimistic = None
            if freed and self._interested:
                # departure freed a seat: promote a waiting peer now
                # (background — this connection's teardown must not
                # block on other peers' writes)
                task = asyncio.create_task(self._recompute_chokes())
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
            await peer.close()

    async def _serve(self, peer: wire.PeerWire) -> None:
        while True:
            msg_id, payload = await peer.recv_message()
            if msg_id is None:
                continue
            if msg_id == wire.MSG_INTERESTED:
                self._interested.add(peer)
                # a free slot (regular or the optimistic seat) unchokes
                # immediately — small swarms never wait for a rotation
                if len(self._unchoked) < self.unchoke_slots + 1:
                    self._unchoked.add(peer)
                    await peer.send_message(wire.MSG_UNCHOKE)
            elif msg_id == wire.MSG_NOT_INTERESTED:
                self._interested.discard(peer)
                if peer is self._optimistic:
                    self._optimistic = None
                if peer in self._unchoked:
                    # a freed seat promotes a waiting peer NOW — idling
                    # capacity until the next rotation wastes up to
                    # rotate_interval of upload time (review r5); the
                    # recompute also chokes this no-longer-interested
                    # peer via the target diff
                    await self._recompute_chokes()
            elif msg_id == wire.MSG_REQUEST:
                index, begin, length = struct.unpack(">III", payload)
                if (index >= self.meta.num_pieces or length > (1 << 17)
                        or begin + length > self.meta.piece_size(index)):
                    # malformed geometry is a protocol violation from any
                    # peer — fast extension or not, disconnect (a polite
                    # reject would let a hostile peer spin forever)
                    raise wire.WireError("bad request")
                if peer not in self._unchoked:
                    # choked peers receive NO blocks (BEP 3: a choke
                    # voids the request queue; a peer requesting anyway
                    # is either racing our choke or abusive) — fast
                    # peers get an explicit reject, legacy peers are
                    # ignored per spec
                    if getattr(peer, "supports_fast", False):
                        await peer.send_reject_request(index, begin, length)
                    continue
                if not self._available(index):
                    # valid request for a piece we haven't advertised
                    # (or a race against an in-flight HAVE): BEP 6 lets
                    # us reject politely; legacy peers get dropped since
                    # serving would leak preallocated zeros as content
                    if getattr(peer, "supports_fast", False):
                        await peer.send_reject_request(index, begin, length)
                        continue
                    raise wire.WireError("bad request")
                data = self.storage.read(
                    index * self.meta.piece_length + begin, length
                )
                await peer.send_piece(index, begin, data)
                self.bytes_served += len(data)
                self._served_window[peer] = (
                    self._served_window.get(peer, 0) + len(data))
            elif msg_id == wire.MSG_EXTENDED:
                await self._serve_extended(peer, payload)
            # choke/have/bitfield/cancel from a leech need no reply here

    async def _serve_extended(self, peer: wire.PeerWire, payload: bytes) -> None:
        ext_id, body = payload[0], payload[1:]
        if ext_id == wire.EXT_HANDSHAKE_ID:
            peer.handle_ext_handshake(body)
            await self._register_pex(peer)
            return
        # ut_metadata request addressed to the id we advertised
        from .bencode import bdecode_prefix

        header, _consumed = bdecode_prefix(body)
        if header.get(b"msg_type") == wire.MD_REQUEST:
            piece = header[b"piece"]
            total = len(self.meta.info_bytes)
            start = piece * wire.METADATA_PIECE_SIZE
            if start >= total:
                await peer.send_metadata_reject(piece)
                return
            chunk = self.meta.info_bytes[start:start + wire.METADATA_PIECE_SIZE]
            await peer.send_metadata_data(piece, total, chunk)

    # -- peer exchange (BEP 11) -----------------------------------------
    async def _register_pex(self, peer: wire.PeerWire) -> None:
        """After a peer's extended handshake: tell it about the swarm, and
        gossip its listen address (if advertised) to everyone else."""
        known = [a for p, a in self._listen_addrs.items() if p is not peer]
        if known and peer.peer_ut_pex is not None:
            try:
                await peer.send_pex(known)
            except (ConnectionError, OSError, wire.WireError):
                return
        if peer.peer_listen_port is None:
            return
        host = peer.writer.get_extra_info("peername")
        if host is None:
            return
        addr = (host[0], peer.peer_listen_port)
        self._listen_addrs[peer] = addr
        for other in list(self._peers):
            if other is peer or other.peer_ut_pex is None:
                continue
            try:
                await other.send_pex([addr])
            except (ConnectionError, OSError, wire.WireError):
                pass
