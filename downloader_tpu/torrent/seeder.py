"""A seeding peer: serves pieces and ut_metadata over the wire protocol.

webtorrent both leeches and seeds (/root/reference/lib/download.js:19 keeps
one long-lived client); this is the seeding half, and doubles as the hermetic
swarm for tests (no network egress needed).
"""

from __future__ import annotations

import asyncio
import os
import struct
from typing import Optional, Set

from . import wire
from .metainfo import Metainfo
from .storage import TorrentStorage


class Seeder:
    """Serves one torrent's pieces from ``root`` on a local TCP port."""

    def __init__(self, meta: Metainfo, root: str, peer_id: Optional[bytes] = None):
        self.meta = meta
        self.storage = TorrentStorage(meta, root)
        self.peer_id = peer_id or (b"-DT0001-" + os.urandom(6).hex().encode())
        self._server: Optional[asyncio.base_events.Server] = None
        self.port: Optional[int] = None
        self.connections: int = 0
        self._conn_tasks: Set[asyncio.Task] = set()

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_connect, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        for task in list(self._conn_tasks):
            task.cancel()
        for task in list(self._conn_tasks):
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        peer = wire.PeerWire(reader, writer)
        try:
            handshake = await peer.recv_handshake()
            if handshake.info_hash != self.meta.info_hash:
                await peer.close()
                return
            self.connections += 1
            await peer.send_handshake(self.meta.info_hash, self.peer_id)
            if handshake.supports_extensions:
                await peer.send_ext_handshake(
                    metadata_size=len(self.meta.info_bytes)
                )
            await peer.send_bitfield(
                wire.build_bitfield(range(self.meta.num_pieces), self.meta.num_pieces)
            )
            await self._serve(peer)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            await peer.close()

    async def _serve(self, peer: wire.PeerWire) -> None:
        while True:
            msg_id, payload = await peer.recv_message()
            if msg_id is None:
                continue
            if msg_id == wire.MSG_INTERESTED:
                await peer.send_message(wire.MSG_UNCHOKE)
            elif msg_id == wire.MSG_REQUEST:
                index, begin, length = struct.unpack(">III", payload)
                if index >= self.meta.num_pieces or length > (1 << 17):
                    raise wire.WireError("bad request")
                data = self.storage.read(
                    index * self.meta.piece_length + begin, length
                )
                await peer.send_piece(index, begin, data)
            elif msg_id == wire.MSG_EXTENDED:
                await self._serve_extended(peer, payload)
            # choke/have/bitfield/cancel from a leech need no reply here

    async def _serve_extended(self, peer: wire.PeerWire, payload: bytes) -> None:
        ext_id, body = payload[0], payload[1:]
        if ext_id == wire.EXT_HANDSHAKE_ID:
            peer.handle_ext_handshake(body)
            return
        # ut_metadata request addressed to the id we advertised
        from .bencode import bdecode_prefix

        header, _consumed = bdecode_prefix(body)
        if header.get(b"msg_type") == wire.MD_REQUEST:
            piece = header[b"piece"]
            total = len(self.meta.info_bytes)
            start = piece * wire.METADATA_PIECE_SIZE
            if start >= total:
                await peer.send_metadata_reject(piece)
                return
            chunk = self.meta.info_bytes[start:start + wire.METADATA_PIECE_SIZE]
            await peer.send_metadata_data(piece, total, chunk)
