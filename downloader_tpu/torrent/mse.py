"""MSE/PE — Message Stream Encryption / Protocol Encryption.

The obfuscation handshake most real swarms expect (the reference's
webtorrent stack negotiates it via its transport layer,
/root/reference/lib/download.js:19; VERDICT r1 missing-item 5).  Wire
protocol per the Vuze/Azureus MSE specification:

- 768-bit Diffie-Hellman exchange (fixed safe prime, g=2), each public
  key followed by 0-511 bytes of random padding so the stream never has
  a fixed signature
- initiator proves knowledge of the torrent (SKEY = info_hash) via
  ``HASH('req2', SKEY) xor HASH('req3', S)``; the receiver syncs on
  ``HASH('req1', S)``
- RC4-drop1024 stream ciphers keyed ``HASH('keyA'|'keyB', S, SKEY)``
  (RC4 via OpenSSL when the ``cryptography`` wheel is present — it is in
  this image — with a pure-Python fallback)
- crypto negotiation: we offer and accept both RC4 (0x02) and plaintext
  (0x01); the selected method applies to the payload stream while the
  handshake tail is always RC4.  The acceptor selects plaintext when the
  initiator allows it (libtorrent's default posture: obfuscated
  handshake, no payload-cipher tax) unless constructed RC4-only
  (TORRENT_CRYPTO=require)

Both sides return plain ``(reader, writer)``-compatible wrappers
(:class:`MSEReader` / :class:`MSEWriter`) so :class:`~.wire.PeerWire`
runs unmodified on top.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import struct
from typing import Optional, Tuple

# The MSE 768-bit prime (2^768 - 2^704 - 1 + 2^64 * (floor(2^638 pi) +
# 149686)) — the constant every MSE implementation ships.
DH_PRIME = int(
    "FFFFFFFFFFFFFFFFC90FDAA22168C234C4C6628B80DC1CD129024E088A67CC74"
    "020BBEA63B139B22514A08798E3404DDEF9519B3CD3A431B302B0A6DF25F1437"
    "4FE1356D6D51C245E485B576625E7EC6F44C42E9A63A36210000000000090563",
    16,
)
DH_GENERATOR = 2
KEY_BYTES = 96  # 768 bits

VC = b"\x00" * 8
CRYPTO_PLAINTEXT = 0x01
CRYPTO_RC4 = 0x02
MAX_PAD = 512
RC4_DROP = 1024

HANDSHAKE_TIMEOUT = 20.0


class MSEError(ConnectionError):
    pass


def _sha1(*parts: bytes) -> bytes:
    return hashlib.sha1(b"".join(parts)).digest()


def _xor(a: bytes, b: bytes) -> bytes:
    return bytes(x ^ y for x, y in zip(a, b))


# ---------------------------------------------------------------------- RC4

class _RC4Python:
    """Pure-Python ARC4 fallback (loopback tests / minimal images)."""

    def __init__(self, key: bytes):
        s = list(range(256))
        j = 0
        klen = len(key)
        for i in range(256):
            j = (j + s[i] + key[i % klen]) & 0xFF
            s[i], s[j] = s[j], s[i]
        self._s = s
        self._i = 0
        self._j = 0

    def crypt(self, data: bytes) -> bytes:
        s = self._s
        i, j = self._i, self._j
        out = bytearray(len(data))
        for n, byte in enumerate(data):
            i = (i + 1) & 0xFF
            j = (j + s[i]) & 0xFF
            s[i], s[j] = s[j], s[i]
            out[n] = byte ^ s[(s[i] + s[j]) & 0xFF]
        self._i, self._j = i, j
        return bytes(out)


def _make_rc4(key: bytes):
    """OpenSSL-backed ARC4 when available (orders of magnitude faster on
    the piece stream), else the Python fallback."""
    try:
        from cryptography.hazmat.decrepit.ciphers.algorithms import ARC4
        from cryptography.hazmat.primitives.ciphers import Cipher

        class _RC4OpenSSL:
            def __init__(self) -> None:
                self._ctx = Cipher(ARC4(key), mode=None).encryptor()

            def crypt(self, data: bytes) -> bytes:
                return self._ctx.update(data)

        return _RC4OpenSSL()
    except Exception:
        return _RC4Python(key)


def new_cipher(prefix: bytes, secret: bytes, skey: bytes):
    """RC4-drop1024 keyed ``SHA1(prefix + S + SKEY)`` per the MSE spec."""
    cipher = _make_rc4(_sha1(prefix, secret, skey))
    cipher.crypt(b"\x00" * RC4_DROP)
    return cipher


# ------------------------------------------------------------- stream shims

class MSEReader:
    """StreamReader-compatible ``readexactly`` over an optional cipher.

    ``plain_prefix`` is already-decrypted data to serve first (e.g. the
    initiator's IA payload); ``raw_prefix`` is ciphertext consumed from
    the socket during sync but not yet decrypted.
    """

    def __init__(self, reader: asyncio.StreamReader, cipher=None,
                 plain_prefix: bytes = b"", raw_prefix: bytes = b""):
        self._reader = reader
        self._cipher = cipher
        self._plain = bytearray(plain_prefix)
        self._raw = bytearray(raw_prefix)

    async def readexactly(self, n: int) -> bytes:
        out = bytearray()
        if self._plain:
            take = min(n, len(self._plain))
            out += self._plain[:take]
            del self._plain[:take]
        need = n - len(out)
        if need > 0:
            raw = bytearray()
            if self._raw:
                take = min(need, len(self._raw))
                raw += self._raw[:take]
                del self._raw[:take]
            if need - len(raw) > 0:
                raw += await self._reader.readexactly(need - len(raw))
            out += self._cipher.crypt(bytes(raw)) if self._cipher else raw
        return bytes(out)

    async def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            chunks = bytearray()
            while True:
                chunk = await self.read(1 << 16)
                if not chunk:
                    return bytes(chunks)
                chunks += chunk
        if self._plain or self._raw:
            take = min(n, len(self._plain) + len(self._raw))
            return await self.readexactly(take)
        data = await self._reader.read(n)
        return self._cipher.crypt(data) if (self._cipher and data) else data

    def at_eof(self) -> bool:
        return (not self._plain and not self._raw
                and self._reader.at_eof())


class MSEWriter:
    """StreamWriter-compatible facade encrypting on ``write``."""

    def __init__(self, writer: asyncio.StreamWriter, cipher=None):
        self._writer = writer
        self._cipher = cipher

    def write(self, data: bytes) -> None:
        self._writer.write(self._cipher.crypt(data) if self._cipher else data)

    async def drain(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        self._writer.close()

    async def wait_closed(self) -> None:
        await self._writer.wait_closed()

    def is_closing(self) -> bool:
        return self._writer.is_closing()

    def get_extra_info(self, name: str, default=None):
        return self._writer.get_extra_info(name, default)


# ------------------------------------------------------------ DH + padding

def _dh_keypair() -> Tuple[int, bytes]:
    private = int.from_bytes(os.urandom(20), "big")  # 160-bit per spec
    public = pow(DH_GENERATOR, private, DH_PRIME)
    return private, public.to_bytes(KEY_BYTES, "big")


def _pad() -> bytes:
    return os.urandom(int.from_bytes(os.urandom(2), "big") % MAX_PAD)


async def _find_sync(reader: asyncio.StreamReader, marker: bytes,
                     already: bytes = b"", limit: int = 628) -> bytes:
    """Consume the stream until ``marker``; returns bytes AFTER it.

    ``limit`` bounds total bytes examined (spec: the sync point must
    appear within the permitted padding window).
    """
    buf = bytearray(already)
    while True:
        pos = buf.find(marker)
        if pos >= 0:
            return bytes(buf[pos + len(marker):])
        if len(buf) >= limit:
            raise MSEError("MSE sync marker not found")
        chunk = await reader.read(1 << 12)
        if not chunk:
            raise MSEError("connection closed during MSE sync")
        buf += chunk


# -------------------------------------------------------------- initiator

async def initiate(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    info_hash: bytes,
    allow_plaintext: bool = True,
) -> Tuple[MSEReader, MSEWriter, int]:
    """Outgoing MSE handshake.  Returns (reader, writer, selected_method);
    the wrapped streams are ready for the BitTorrent handshake."""
    async with asyncio.timeout(HANDSHAKE_TIMEOUT):
        return await _initiate(reader, writer, info_hash, allow_plaintext)


async def _initiate(reader, writer, info_hash, allow_plaintext):
    private, public = _dh_keypair()
    writer.write(public + _pad())
    await writer.drain()

    yb = await reader.readexactly(KEY_BYTES)
    secret_int = pow(int.from_bytes(yb, "big"), private, DH_PRIME)
    s = secret_int.to_bytes(KEY_BYTES, "big")
    if secret_int in (0, 1):  # degenerate peer key: no secrecy
        raise MSEError("degenerate DH public key")

    out_cipher = new_cipher(b"keyA", s, info_hash)
    in_cipher_probe_key = _sha1(b"keyB", s, info_hash)

    provide = CRYPTO_RC4 | (CRYPTO_PLAINTEXT if allow_plaintext else 0)
    tail = VC + struct.pack(">I", provide) + struct.pack(">H", 0)  # no PadC
    tail += struct.pack(">H", 0)  # len(IA) = 0: BT handshake after the MSE one
    writer.write(
        _sha1(b"req1", s)
        + _xor(_sha1(b"req2", info_hash), _sha1(b"req3", s))
        + out_cipher.crypt(tail)
    )
    await writer.drain()

    # B replies PadB-remainder + RC4(VC ...): find the offset where a fresh
    # keyB cipher decrypts to VC.  An offset that failed once can never
    # match later (its 8 bytes are fixed), so keep a cursor — without it a
    # byte-trickling peer forces a full re-scan (each probe re-runs the
    # RC4 key schedule + 1024-byte drop) per arriving chunk.
    buf = bytearray()
    in_cipher = None
    next_offset = 0
    while in_cipher is None:
        chunk = await reader.read(1 << 12)
        if not chunk:
            raise MSEError("connection closed during MSE reply")
        buf += chunk
        for offset in range(next_offset, len(buf) - len(VC) + 1):
            probe = _make_rc4(in_cipher_probe_key)
            probe.crypt(b"\x00" * RC4_DROP)
            if probe.crypt(bytes(buf[offset:offset + len(VC)])) == VC:
                in_cipher = probe  # already advanced past VC
                del buf[:offset + len(VC)]
                break
        else:
            next_offset = max(0, len(buf) - len(VC) + 1)
        if in_cipher is None and len(buf) > MAX_PAD + KEY_BYTES + len(VC):
            raise MSEError("MSE VC not found in reply")

    async def read_dec(n: int) -> bytes:
        nonlocal buf
        while len(buf) < n:
            chunk = await reader.read(1 << 12)
            if not chunk:
                raise MSEError("connection closed during MSE reply")
            buf += chunk
        piece = bytes(buf[:n])
        del buf[:n]
        return in_cipher.crypt(piece)

    (select,) = struct.unpack(">I", await read_dec(4))
    (pad_d_len,) = struct.unpack(">H", await read_dec(2))
    if pad_d_len > MAX_PAD:
        raise MSEError("oversized PadD")
    await read_dec(pad_d_len)

    if select == CRYPTO_RC4:
        return (
            MSEReader(reader, in_cipher, raw_prefix=bytes(buf)),
            MSEWriter(writer, out_cipher),
            select,
        )
    if select == CRYPTO_PLAINTEXT and allow_plaintext:
        return (
            MSEReader(reader, None, plain_prefix=bytes(buf)),
            MSEWriter(writer, None),
            select,
        )
    raise MSEError(f"peer selected unsupported crypto {select:#x}")


# --------------------------------------------------------------- acceptor

async def accept(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    info_hash: bytes,
    first_bytes: bytes = b"",
    *,
    allow_plaintext: bool = True,
    prefer_plaintext: bool = True,
) -> Tuple[MSEReader, MSEWriter, int]:
    """Incoming MSE handshake (``first_bytes``: data already consumed by
    protocol sniffing).  Returns (reader, writer, selected_method).

    ``prefer_plaintext`` (default, matching libtorrent's default
    ``prefer_rc4=false``): when the initiator provides both methods,
    select plaintext — the handshake is still fully obfuscated (DH +
    RC4-encrypted negotiation), but the payload skips the stream-cipher
    tax (VERDICT r4 weak-item 5: RC4 halves swarm throughput).
    ``allow_plaintext=False`` (TORRENT_CRYPTO=require) never selects
    plaintext and rejects initiators that provide nothing else."""
    async with asyncio.timeout(HANDSHAKE_TIMEOUT):
        return await _accept(reader, writer, info_hash, first_bytes,
                             allow_plaintext, prefer_plaintext)


async def _accept(reader, writer, info_hash, first_bytes,
                  allow_plaintext=True, prefer_plaintext=True):
    buf = bytearray(first_bytes)
    while len(buf) < KEY_BYTES:
        chunk = await reader.read(1 << 12)
        if not chunk:
            raise MSEError("connection closed during MSE exchange")
        buf += chunk
    ya = bytes(buf[:KEY_BYTES])
    rest = bytes(buf[KEY_BYTES:])

    private, public = _dh_keypair()
    writer.write(public + _pad())
    await writer.drain()

    secret_int = pow(int.from_bytes(ya, "big"), private, DH_PRIME)
    if secret_int in (0, 1):
        raise MSEError("degenerate DH public key")
    s = secret_int.to_bytes(KEY_BYTES, "big")

    # sync on HASH('req1', S), then verify the SKEY proof
    after = await _find_sync(reader, _sha1(b"req1", s), already=rest)
    buf = bytearray(after)

    async def read_raw(n: int) -> bytes:
        nonlocal buf
        while len(buf) < n:
            chunk = await reader.read(1 << 12)
            if not chunk:
                raise MSEError("connection closed during MSE exchange")
            buf += chunk
        piece = bytes(buf[:n])
        del buf[:n]
        return piece

    proof = await read_raw(20)
    expected = _xor(_sha1(b"req2", info_hash), _sha1(b"req3", s))
    if proof != expected:
        raise MSEError("MSE SKEY proof mismatch (unknown torrent)")

    in_cipher = new_cipher(b"keyA", s, info_hash)
    out_cipher = new_cipher(b"keyB", s, info_hash)

    async def read_dec(n: int) -> bytes:
        return in_cipher.crypt(await read_raw(n))

    if await read_dec(len(VC)) != VC:
        raise MSEError("bad MSE VC from initiator")
    (provide,) = struct.unpack(">I", await read_dec(4))
    (pad_c_len,) = struct.unpack(">H", await read_dec(2))
    if pad_c_len > MAX_PAD:
        raise MSEError("oversized PadC")
    await read_dec(pad_c_len)
    (ia_len,) = struct.unpack(">H", await read_dec(2))
    ia_plain = await read_dec(ia_len) if ia_len else b""

    plain_ok = bool(provide & CRYPTO_PLAINTEXT) and allow_plaintext
    rc4_ok = bool(provide & CRYPTO_RC4)
    if plain_ok and (prefer_plaintext or not rc4_ok):
        select = CRYPTO_PLAINTEXT
    elif rc4_ok:
        select = CRYPTO_RC4
    else:
        raise MSEError(f"initiator provided no acceptable crypto {provide:#x}")

    writer.write(out_cipher.crypt(
        VC + struct.pack(">I", select) + struct.pack(">H", 0)
    ))
    await writer.drain()

    if select == CRYPTO_RC4:
        return (
            MSEReader(reader, in_cipher, plain_prefix=ia_plain,
                      raw_prefix=bytes(buf)),
            MSEWriter(writer, out_cipher),
            select,
        )
    return (
        MSEReader(reader, None, plain_prefix=ia_plain + bytes(buf)),
        MSEWriter(writer, None),
        select,
    )


def looks_like_plaintext_bt(first_bytes: bytes) -> Optional[bool]:
    """Protocol sniff for the accept side: True = plaintext BitTorrent
    handshake, False = something else (treat as MSE), None = need more
    bytes.  The BT handshake starts \\x13"BitTorrent protocol"."""
    from .wire import PSTR

    probe = bytes([len(PSTR)]) + PSTR
    if len(first_bytes) < len(probe):
        return None if probe.startswith(first_bytes) else False
    return first_bytes.startswith(probe)
