"""Piece <-> file mapping: read/write the torrent's linear byte stream across
its (possibly many) files on disk."""

from __future__ import annotations

import errno
import os
from typing import List, Tuple

# positioned IO where the platform has it (Unix); Windows falls back to
# lseek+read/write on the same cached fds.  The fd cache is NOT
# thread-safe; the invariant that protects it is strict sequencing, not
# instance isolation: the resume scan runs the SHARED storage in a
# worker thread, but the event loop awaits it to completion before the
# seeder or any download writer touches storage (client.py download
# flow).  Overlapping loop-thread calls with a scan would race the
# cache dict and, on the lseek fallback, the seek pointer.
_HAS_PREAD = hasattr(os, "pread")

from .metainfo import Metainfo


class TorrentStorage:
    """Maps absolute stream offsets onto files under ``root``.

    The reference hands webtorrent a download directory and lets it lay the
    torrent's files out inside it (/root/reference/lib/download.js:64-66);
    this does the same: ``<root>/<file.path>``.
    """

    # bound on cached open file handles (a torrent rarely has more
    # files than this; evicting the oldest keeps pathological
    # many-file torrents from exhausting the process fd budget)
    MAX_CACHED_FDS = 64

    def __init__(self, meta: Metainfo, root: str):
        self.meta = meta
        self.root = os.path.abspath(root)
        # path -> O_RDWR fd.  The swarm serve path reads 16 KiB blocks;
        # re-opening the file per block was >2k opens per 32 MiB
        # transfer (profiled r5).  Positioned pread/pwrite keeps the
        # handles stateless, so concurrent serve/verify paths never
        # fight over a seek pointer.
        self._fds: dict = {}

    def _fd(self, path: str, write: bool = False) -> int:
        entry = self._fds.pop(path, None)
        if entry is not None and write and not entry[1]:
            os.close(entry[0])  # cached read-only, writer needs more
            entry = None
        if entry is None:
            flags = getattr(os, "O_BINARY", 0)  # Windows: no CRLF mangling
            if write:
                entry = (os.open(path, os.O_RDWR | flags), True)
            else:
                # fall back to read-only so seeding from write-protected
                # media libraries keeps working (the old per-call open
                # used "rb" here); EROFS (read-only mount) is a plain
                # OSError, not PermissionError (review r5)
                try:
                    entry = (os.open(path, os.O_RDWR | flags), True)
                except OSError as exc:
                    if exc.errno not in (errno.EACCES, errno.EPERM,
                                         errno.EROFS):
                        raise
                    entry = (os.open(path, os.O_RDONLY | flags), False)
            while len(self._fds) >= self.MAX_CACHED_FDS:
                old_path = next(iter(self._fds))
                os.close(self._fds.pop(old_path)[0])
        self._fds[path] = entry  # re-insert = LRU touch
        return entry[0]

    def close(self) -> None:
        """Release cached handles (idempotent; reopened on next use)."""
        fds, self._fds = self._fds, {}
        for fd, _writable in fds.values():
            try:
                os.close(fd)
            except OSError:
                pass

    def __del__(self):  # safety net; close() is the real lifecycle
        self.close()

    def file_path(self, entry_path: str) -> str:
        parts = [p for p in entry_path.split("/") if p not in ("", ".", "..")]
        return os.path.join(self.root, *parts)

    def _ranges(self, offset: int, length: int) -> List[Tuple[str, int, int, int]]:
        """(path, file_offset, stream_start, chunk_len) per touched file."""
        out = []
        end = offset + length
        for entry in self.meta.files:
            file_start = entry.offset
            file_end = entry.offset + entry.length
            lo = max(offset, file_start)
            hi = min(end, file_end)
            if lo < hi:
                out.append(
                    (self.file_path(entry.path), lo - file_start, lo - offset, hi - lo)
                )
        return out

    def preallocate(self) -> None:
        for entry in self.meta.files:
            path = self.file_path(entry.path)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if not os.path.exists(path) or os.path.getsize(path) != entry.length:
                with open(path, "wb") as fh:
                    fh.truncate(entry.length)

    @staticmethod
    def _pwrite(fd: int, chunk, pos: int) -> int:
        if _HAS_PREAD:
            return os.pwrite(fd, chunk, pos)
        os.lseek(fd, pos, os.SEEK_SET)
        return os.write(fd, chunk)

    @staticmethod
    def _pread(fd: int, n: int, pos: int) -> bytes:
        if _HAS_PREAD:
            return os.pread(fd, n, pos)
        os.lseek(fd, pos, os.SEEK_SET)
        return os.read(fd, n)

    def write(self, offset: int, data: bytes) -> None:
        view = memoryview(data)
        for path, file_off, rel, length in self._ranges(offset, len(data)):
            fd = self._fd(path, write=True)
            pos = file_off
            chunk = view[rel:rel + length]
            while chunk:
                n = self._pwrite(fd, chunk, pos)
                pos += n
                chunk = chunk[n:]

    def read(self, offset: int, length: int) -> bytes:
        out = bytearray(length)
        for path, file_off, rel, chunk_len in self._ranges(offset, length):
            fd = self._fd(path)
            got = 0
            while got < chunk_len:
                piece = self._pread(fd, chunk_len - got, file_off + got)
                if not piece:
                    break  # short file: leave zeros, like the old read
                out[rel + got:rel + got + len(piece)] = piece
                got += len(piece)
        return bytes(out)

    def read_piece(self, index: int) -> bytes:
        return self.read(
            index * self.meta.piece_length, self.meta.piece_size(index)
        )

    def write_piece(self, index: int, data: bytes) -> None:
        self.write(index * self.meta.piece_length, data)
