"""Piece <-> file mapping: read/write the torrent's linear byte stream across
its (possibly many) files on disk."""

from __future__ import annotations

import os
from typing import List, Tuple

from .metainfo import Metainfo


class TorrentStorage:
    """Maps absolute stream offsets onto files under ``root``.

    The reference hands webtorrent a download directory and lets it lay the
    torrent's files out inside it (/root/reference/lib/download.js:64-66);
    this does the same: ``<root>/<file.path>``.
    """

    def __init__(self, meta: Metainfo, root: str):
        self.meta = meta
        self.root = os.path.abspath(root)

    def file_path(self, entry_path: str) -> str:
        parts = [p for p in entry_path.split("/") if p not in ("", ".", "..")]
        return os.path.join(self.root, *parts)

    def _ranges(self, offset: int, length: int) -> List[Tuple[str, int, int, int]]:
        """(path, file_offset, stream_start, chunk_len) per touched file."""
        out = []
        end = offset + length
        for entry in self.meta.files:
            file_start = entry.offset
            file_end = entry.offset + entry.length
            lo = max(offset, file_start)
            hi = min(end, file_end)
            if lo < hi:
                out.append(
                    (self.file_path(entry.path), lo - file_start, lo - offset, hi - lo)
                )
        return out

    def preallocate(self) -> None:
        for entry in self.meta.files:
            path = self.file_path(entry.path)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            if not os.path.exists(path) or os.path.getsize(path) != entry.length:
                with open(path, "wb") as fh:
                    fh.truncate(entry.length)

    def write(self, offset: int, data: bytes) -> None:
        for path, file_off, rel, length in self._ranges(offset, len(data)):
            with open(path, "r+b") as fh:
                fh.seek(file_off)
                fh.write(data[rel:rel + length])

    def read(self, offset: int, length: int) -> bytes:
        out = bytearray(length)
        for path, file_off, rel, chunk_len in self._ranges(offset, length):
            with open(path, "rb") as fh:
                fh.seek(file_off)
                out[rel:rel + chunk_len] = fh.read(chunk_len)
        return bytes(out)

    def read_piece(self, index: int) -> bytes:
        return self.read(
            index * self.meta.piece_length, self.meta.piece_size(index)
        )

    def write_piece(self, index: int, data: bytes) -> None:
        self.write(index * self.meta.piece_length, data)
