"""The leeching client: magnet/.torrent -> files on disk.

Behavioral parity with the reference's webtorrent wrapper
(/root/reference/lib/download.js:43-123):

- accepts magnet URIs, ``.torrent`` URLs, and local ``.torrent`` paths
  (the http method chains ``.torrent`` URLs here, lib/download.js:144-155)
- 240 s metadata timeout -> ``Metadata fetch stalled``
  (lib/download.js:47-50)
- 240 s no-progress watchdog -> error with ``code == 'ERRDLSTALL'``
  (lib/download.js:90-101)
- progress callback on a 30 s cadence (lib/download.js:78-88)
- resumes from pieces already on disk (webtorrent reuses ``downloadPath``
  contents; SURVEY.md §5 "checkpoint/resume")
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import random
import struct
from collections import Counter, deque
from typing import Awaitable, Callable, Dict, List, Optional, Set

import aiohttp

from ..utils.watchdog import MetadataTimeoutError, StallWatchdog
from . import mse
from . import resume as resume_mod
from . import tracker as tracker_mod
from . import utp
from . import wire
from .magnet import parse_magnet
from .metainfo import BLOCK_SIZE, Metainfo, parse_info_dict, parse_torrent_bytes
from .storage import TorrentStorage

ProgressCb = Callable[[float], Awaitable[None]]


class _MSERejected(Exception):
    """Internal marker: the MSE exchange itself failed (fallback-eligible).

    Carries the underlying error so "require" mode and exhausted retries
    re-raise the real cause."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause

CONNECT_TIMEOUT = 10.0
# outstanding 16 KiB requests per peer: 64 = 1 MiB in flight, measured
# fastest on the loopback swarm (sweep: 64 > 32 > 128 > 16) and in line
# with what mainstream clients keep queued
PIPELINE_DEPTH = 64
MAX_PEERS = 8
# biggest file we'll accept from a webseed that ignores Range requests —
# without ranges every piece re-streams the file prefix (quadratic)
WEBSEED_NO_RANGE_MAX = 32 << 20
# worker/session cap, like MAX_PEERS — a hostile url-list must not be able
# to spawn one task + HTTP session per entry
MAX_WEBSEEDS = 4
# pieces a peer worker assembles concurrently: claiming the next piece
# before the current one's tail blocks land keeps the request pipeline
# full across piece boundaries
MAX_ACTIVE_CLAIMS = 2


class _Assembly:
    """In-flight piece reassembly for one (worker, piece)."""

    __slots__ = ("buffer", "received", "requested", "rejects", "pending")

    def __init__(self, size: int):
        self.buffer = bytearray(size)
        self.received: Set[int] = set()
        self.requested: Set[int] = set()
        # unchoked REJECT_REQUEST counts per block (BEP 6)
        self.rejects: dict = {}
        # block offsets not yet requested, in order — the pump pops from
        # here (O(1) per request) instead of rescanning every block
        self.pending = deque(range(0, size, BLOCK_SIZE))

    def requeue(self, begin: int) -> None:
        """A request for ``begin`` was lost (reject): offer it again.

        Only offsets we actually requested re-enter the queue — a forged
        reject for a bogus offset must not reach the pump (a negative
        computed length would kill the connection; a misaligned one would
        wedge the piece)."""
        if begin not in self.requested:
            return
        self.requested.discard(begin)
        if begin not in self.received:
            self.pending.append(begin)

    def rebuild_pending(self) -> None:
        """After a choke wiped the peer's request queue: everything not
        yet received must be re-requested."""
        self.requested &= self.received
        size = len(self.buffer)
        self.pending = deque(
            b for b in range(0, size, BLOCK_SIZE)
            if b not in self.received
        )


class TorrentError(RuntimeError):
    pass


class _FileCompletion:
    """Per-file piece accounting: which files just became fully durable.

    A file is durable once every piece overlapping its byte range is
    verified and written (each such piece's ``storage.write_piece`` —
    including the slice that lands in this file — happens before its
    ``finish``, and finished pieces are never rewritten, so no write can
    touch the file afterwards).  ``mark`` is O(files overlapping the
    piece); completed file indices queue in ``completed`` for the drive
    loop to drain into the caller's ``on_file_complete`` callback.
    """

    __slots__ = ("_left", "_by_piece", "completed")

    def __init__(self, meta: Metainfo):
        self._left: List[int] = []
        self._by_piece: Dict[int, List[int]] = {}
        self.completed: deque = deque()
        for index, entry in enumerate(meta.files):
            if entry.length == 0:
                self._left.append(0)
                self.completed.append(index)  # nothing to transfer
                continue
            first = entry.offset // meta.piece_length
            last = (entry.offset + entry.length - 1) // meta.piece_length
            for piece in range(first, last + 1):
                self._by_piece.setdefault(piece, []).append(index)
            self._left.append(last - first + 1)

    def mark(self, piece: int) -> None:
        """Record ``piece`` done; queues any file it completed."""
        for index in self._by_piece.pop(piece, ()):
            self._left[index] -= 1
            if self._left[index] == 0:
                self.completed.append(index)


class _Swarm:
    """Shared download state across peer workers.

    Piece selection is rarest-first (classic BitTorrent: pick the piece the
    fewest connected peers advertise, so rare pieces replicate before their
    holders leave), with piece index as the deterministic tie-break.  When
    every piece is either done or in flight, the swarm enters endgame mode
    (BEP 3): idle workers duplicate-request in-flight pieces so one slow
    peer cannot stall the tail of the download.
    """

    def __init__(self, meta: Metainfo):
        self.meta = meta
        self.pending: Set[int] = set(range(meta.num_pieces))
        self.claimed: Set[int] = set()
        self.done: Set[int] = set()
        self.bytes_done = 0
        self.piece_event = asyncio.Event()
        # piece index -> number of connected peers advertising it
        self.availability: Counter = Counter()
        self.endgame = False
        # ut_pex gossip: (host, port) addresses workers hear about
        self.discovered: asyncio.Queue = asyncio.Queue()
        # our serving socket, advertised to peers (BEP 10 ``p``)
        self.listen_port: Optional[int] = None
        # accounting for observability (surfaced via download(stats_out=))
        self.hash_failures = 0
        self.bytes_resumed = 0
        self.bytes_from_webseeds = 0
        # optional per-file completion tracker (download(on_file_complete=)):
        # finish() feeds it; the drive loop drains its queue
        self.completion: "Optional[_FileCompletion]" = None

    @property
    def complete(self) -> bool:
        return len(self.done) == self.meta.num_pieces

    def _rarest(self, candidates: Set[int]) -> int:
        return min(candidates, key=lambda p: (self.availability[p], p))

    def claim(self, have: Set[int]) -> Optional[int]:
        candidates = self.pending & have
        if candidates:
            piece = self._rarest(candidates)
            self.pending.discard(piece)
            self.claimed.add(piece)
            return piece
        if not self.pending and self.claimed:
            # endgame: everything is in flight — duplicate-request an
            # unfinished claimed piece this peer has (requests for it stay
            # live on both workers; the loser cancels on the finish event)
            in_flight = (self.claimed - self.done) & have
            if in_flight:
                self.endgame = True
                return self._rarest(in_flight)
        return None

    def release(self, piece: int) -> None:
        if piece in self.done:
            return  # endgame duplicate: another worker already finished it
        self.claimed.discard(piece)
        self.pending.add(piece)

    def finish(self, piece: int) -> bool:
        """Mark ``piece`` verified+written. False if it was already done
        (an endgame duplicate landed second — caller must not re-write)."""
        if piece in self.done:
            return False
        self.claimed.discard(piece)
        # a dying endgame duplicate may have release()d it back to pending
        # before the winner finished — don't let it be claimed again
        self.pending.discard(piece)
        self.done.add(piece)
        self.bytes_done += self.meta.piece_size(piece)
        if self.completion is not None:
            self.completion.mark(piece)
        self.piece_event.set()
        return True


class TorrentClient:
    def __init__(self, logger=None, peer_id: Optional[bytes] = None,
                 dht=None, rate_limiter=None, crypto: str = "prefer",
                 transport: str = "auto", tracker_retries: int = 1):
        """``dht`` is an optional started :class:`~.dht.DHTNode`; when set,
        it is queried as an additional peer source next to trackers (the
        reference's webtorrent does the same via bittorrent-dht,
        /root/reference/lib/download.js:19,64).  ``rate_limiter`` is an
        optional token bucket (``await consume(n)``) charged for every
        payload byte received from peers and webseeds.

        ``crypto`` controls outgoing MSE/PE obfuscation (the reference's
        webtorrent transport negotiates the same handshake,
        lib/download.js:19): ``"prefer"`` (default) attempts the MSE
        handshake and falls back to plaintext against peers that reject
        it, ``"require"`` drops peers that won't encrypt, ``"plaintext"``
        never initiates MSE.  Incoming connections (the seeder) always
        auto-detect both.

        ``transport`` picks the outgoing dial: ``"auto"`` (default,
        webtorrent parity — it dials TCP and uTP, lib/download.js:19)
        tries TCP and falls back to uTP (BEP 29) on the same port;
        ``"tcp"`` / ``"utp"`` pin one transport.  Incoming connections
        accept both regardless (the seeder listens on TCP and UDP)."""
        if crypto not in ("plaintext", "prefer", "require"):
            raise ValueError(f"unknown crypto mode {crypto!r}")
        if transport not in ("tcp", "utp", "auto"):
            raise ValueError(f"unknown transport mode {transport!r}")
        self.crypto = crypto
        self.transport = transport
        self.logger = logger
        self.rate_limiter = rate_limiter
        self.peer_id = peer_id or (
            b"-DT0001-" + bytes(random.randrange(48, 58) for _ in range(12))
        )
        self.dht = dht
        # quick per-tracker retries of transient announce failures
        # (timeouts, 5xx, resets) — concurrent across trackers, so a
        # flaky tracker backs off without serializing the healthy ones
        # (platform/errors.py taxonomy; config ``retry.tracker``)
        self.tracker_retries = max(int(tracker_retries), 0)
        # lingering seed servers: info_hash -> (Seeder, expiry task)
        self._lingering: dict = {}

    def serving_port(self, info_hash: bytes) -> Optional[int]:
        """Port of the lingering seed server for ``info_hash``, if any."""
        entry = self._lingering.get(info_hash)
        return entry[0].port if entry else None

    @property
    def is_seeding(self) -> bool:
        """True while any post-download server is still lingering."""
        return bool(self._lingering)

    async def close(self) -> None:
        """Stop any servers still seeding past their download (webtorrent's
        ``client.destroy()`` analogue — the reference keeps one long-lived
        client whose torrents seed until removed, lib/download.js:19,103)."""
        for server, expiry, unregister in list(self._lingering.values()):
            expiry.cancel()
            await server.stop()
            await unregister()
        self._lingering.clear()

    # ------------------------------------------------------------------
    async def download(
        self,
        uri: str,
        download_path: str,
        *,
        metadata_timeout: float = 240.0,
        stall_timeout: float = 240.0,
        progress_interval: float = 30.0,
        on_progress: Optional[ProgressCb] = None,
        peers: Optional[List[tracker_mod.Peer]] = None,
        listen: bool = True,
        listen_host: str = "0.0.0.0",
        seed_linger: float = 0.0,
        stats_out: Optional[dict] = None,
        cancel=None,
        progress_sink=None,
        on_file_complete=None,
        extra_webseeds=None,
    ) -> Metainfo:
        """Fetch the torrent behind ``uri`` into ``download_path``.

        While downloading, verified pieces are served back to the swarm on
        a listen socket (seed-while-leech, like the reference's webtorrent:
        concurrent replicas staging the same torrent trade pieces instead
        of all hammering the origin).  ``listen=False`` disables serving.

        ``seed_linger`` keeps the serve socket up for that many seconds
        AFTER the download completes (in the background — this call still
        returns immediately), so sibling replicas mid-download don't lose
        their source; :meth:`close` reaps lingering servers early.

        ``cancel`` is an optional control-plane token
        (:class:`~..control.cancel.CancelToken`): the drive loop checks
        it between piece batches, so a cancelled job stops requesting
        pieces within one scheduling tick and unwinds through the same
        orderly teardown as any other drive error (fast-resume sidecar
        saved, workers gathered, storage closed).

        ``progress_sink`` is an optional callable fed the cumulative
        verified byte count on every watchdog feed — the download
        stage's live flight-recorder transfer counter rides it.

        ``on_file_complete`` is an optional ``async (path, FileEntry)``
        callback invoked — from the drive loop, between piece batches —
        the moment an individual file's bytes are durable (every piece
        overlapping it verified and written; finished pieces are never
        rewritten).  The streaming staging pipeline rides it to upload
        early files while later ones still download.  Resumed/already-
        on-disk files are announced too, so a redelivered job streams
        its whole inventory.

        ``extra_webseeds`` is an optional list of additional BEP 19
        HTTP(S) webseed base URLs, merged (de-duplicated) with the ones
        the magnet/metainfo already carries — the origin plane's
        webseed/HTTP-mirror equivalence: a torrent job's
        ``Download.mirrors`` become always-on HTTP origins for the same
        piece-verified content.
        """
        meta, peers = await self._resolve(uri, peers, metadata_timeout)
        self._log("metainfo resolved", name=meta.name, pieces=meta.num_pieces)

        storage = TorrentStorage(meta, download_path)
        await asyncio.to_thread(self._preflight_disk, storage)
        await asyncio.to_thread(storage.preallocate)
        swarm = _Swarm(meta)
        if on_file_complete is not None:
            # installed BEFORE any piece can finish so finish() feeds it;
            # resume-scanned pieces (added to done directly) are marked
            # right after the scan below
            swarm.completion = _FileCompletion(meta)
        await self._resume_from_disk(storage, swarm)
        if swarm.completion is not None:
            for piece in swarm.done:
                swarm.completion.mark(piece)

        if swarm.complete:
            self._log("all pieces already on disk")
            await self._drain_file_completions(swarm, storage,
                                               on_file_complete)
            # a hash-scan proved the data: record it so the NEXT restart
            # is stat-only
            await asyncio.to_thread(
                resume_mod.save_resume, storage.root, meta, set(swarm.done)
            )
            if stats_out is not None:
                stats_out.update(self._swarm_stats(swarm, None))
            if on_progress is not None:
                await on_progress(1.0)
            return meta

        webseeds = self._webseed_urls(uri, meta)
        for url in extra_webseeds or ():
            if url not in webseeds:
                webseeds.append(url)
        if not peers and not webseeds:
            raise TorrentError("no peers available")

        server = None
        if listen:
            from .seeder import Seeder

            # share swarm.done by reference: the serve side's availability
            # tracks verified pieces with no extra bookkeeping
            server = Seeder(meta, storage=storage, have=swarm.done,
                            peer_id=self.peer_id, crypto=self.crypto)
            try:
                swarm.listen_port = await server.start(host=listen_host)
                self._log("serving swarm", port=swarm.listen_port)
            except OSError as err:
                self._log("listen socket failed; leech-only", error=str(err))
                server = None

        watchdog = StallWatchdog(stall_timeout, on_feed=progress_sink)
        watchdog.feed(swarm.bytes_done)

        completed = False
        try:
            await watchdog.watch(
                self._drive(swarm, storage, peers or [], webseeds, server,
                            progress_interval, on_progress, watchdog,
                            cancel=cancel, on_file_complete=on_file_complete)
            )
            completed = True
            # close the live counter: a fast download can finish between
            # reporter ticks, and the final total must reach the sink
            watchdog.feed(swarm.bytes_done)
        finally:
            if server is not None:
                if completed and seed_linger > 0:
                    self._linger(meta, server, seed_linger,
                                 swarm.listen_port)
                else:
                    await server.stop()
            if stats_out is not None:
                stats_out.update(self._swarm_stats(swarm, server))
            # all writers are stopped (the drive's finally gathered them),
            # so file mtimes are final: record the verified bitfield for
            # fast resume — on success AND on orderly failure (a stalled
            # job the queue redelivers resumes instantly instead of
            # re-hashing everything it already fetched)
            await asyncio.to_thread(
                resume_mod.save_resume, storage.root, meta, set(swarm.done)
            )
            # release cached file handles; a lingering background seeder
            # sharing this storage just reopens lazily
            storage.close()

        if on_progress is not None:
            await on_progress(1.0)
        return meta

    @staticmethod
    def _preflight_disk(storage: TorrentStorage) -> None:
        """Fail fast with a clear error when the volume can't hold the
        torrent — losing a multi-GB transfer to ENOSPC at piece N is the
        worst way to find out.  ALLOCATED bytes count as resume credit:
        preallocation sparse-truncates files to full apparent size, so
        ``st_size`` would claim a crashed first attempt already holds
        everything and reduce this check to a no-op on every retry.
        """
        from ..utils.disk import allocated_bytes, ensure_disk_space

        have = sum(
            allocated_bytes(storage.file_path(entry.path))
            for entry in storage.meta.files
        )
        os.makedirs(storage.root, exist_ok=True)
        ensure_disk_space(storage.root, storage.meta.total_length - have)

    @staticmethod
    def _swarm_stats(swarm: _Swarm, server) -> dict:
        """Per-download accounting for the caller's metrics."""
        return {
            "pieces": len(swarm.done),
            "bytes_total": swarm.bytes_done,
            "bytes_resumed": swarm.bytes_resumed,
            "bytes_from_webseeds": swarm.bytes_from_webseeds,
            "bytes_from_peers": (swarm.bytes_done - swarm.bytes_resumed
                                 - swarm.bytes_from_webseeds),
            "hash_failures": swarm.hash_failures,
            "bytes_served": server.bytes_served if server is not None else 0,
        }

    def _linger(self, meta: Metainfo, server, seconds: float,
                port: int) -> None:
        """Keep ``server`` seeding for ``seconds`` in the background; when
        it stops, tell the trackers (event=stopped) so they stop handing
        out our now-dead address."""
        info_hash = meta.info_hash

        async def _unregister() -> None:
            try:
                async with asyncio.timeout(5.0):  # dead trackers: bounded
                    await self._announce_all(meta.trackers, info_hash,
                                             left=0, port=port,
                                             event="stopped")
            except Exception as err:  # best-effort
                self._log("tracker unregister failed", error=str(err))

        async def _expire() -> None:
            # the finally owns teardown so every exit — natural expiry,
            # close(), or replacement by a re-download's new server —
            # stops the socket and withdraws the tracker registration
            try:
                await asyncio.sleep(seconds)
            finally:
                await server.stop()
                await _unregister()
                entry = self._lingering.get(info_hash)
                if entry is not None and entry[0] is server:
                    self._lingering.pop(info_hash, None)

        old = self._lingering.pop(info_hash, None)
        if old is not None:
            old[1].cancel()  # its finally retires the old server
        self._lingering[info_hash] = (
            server, asyncio.create_task(_expire()), _unregister
        )

    async def _drain_file_completions(self, swarm: _Swarm,
                                      storage: TorrentStorage,
                                      on_file_complete) -> None:
        """Announce files whose last piece just landed (download(
        on_file_complete=)); callback errors propagate like any other
        drive error so a broken consumer fails the download loudly."""
        completion = swarm.completion
        if completion is None or on_file_complete is None:
            return
        while completion.completed:
            index = completion.completed.popleft()
            entry = swarm.meta.files[index]
            await on_file_complete(storage.file_path(entry.path), entry)

    async def _drive(self, swarm: _Swarm, storage: TorrentStorage,
                     peers: List[tracker_mod.Peer], webseeds: List[str],
                     server, progress_interval: float,
                     on_progress: Optional[ProgressCb],
                     watchdog: StallWatchdog, cancel=None,
                     on_file_complete=None) -> None:
        """Run the download: a dynamic worker pool (seeded from trackers/
        DHT/x.pe, grown from ut_pex gossip), HAVE re-broadcast of finished
        pieces, and a best-effort DHT announce of our serving socket."""
        meta = swarm.meta
        reporter = asyncio.create_task(
            self._report_progress(swarm, watchdog, progress_interval,
                                  on_progress)
        )
        seen = {(p.host, p.port) for p in peers}
        backlog = list(peers)
        # separate pools: webseed workers must not consume MAX_PEERS slots
        ws_workers = [
            asyncio.create_task(self._webseed_worker(url, storage, swarm))
            for url in webseeds[:MAX_WEBSEEDS]
        ]
        workers: List[asyncio.Task] = []
        announce_task = None
        if server is not None:
            announce_task = asyncio.create_task(self._advertise(swarm))
        announced = set(swarm.done)  # resume pieces are in the bitfield
        try:
            while not swarm.complete:
                # cooperative cancellation, between piece batches: the
                # workers' in-flight block requests die with the cancel
                # in the finally below
                if cancel is not None:
                    cancel.raise_if_cancelled()
                # grow the pool from ut_pex gossip
                while not swarm.discovered.empty():
                    host, port = swarm.discovered.get_nowait()
                    if (host, port) not in seen:
                        seen.add((host, port))
                        backlog.append(tracker_mod.Peer(host, port))
                        self._log("pex peer discovered", host=host, port=port)
                peer_slots = MAX_PEERS - sum(
                    1 for w in workers if not w.done()
                )
                while backlog and peer_slots > 0:
                    addr = backlog.pop(0)
                    workers.append(asyncio.create_task(
                        self._peer_worker(addr, storage, swarm)
                    ))
                    peer_slots -= 1
                if (all(w.done() for w in workers)
                        and all(w.done() for w in ws_workers)
                        and not backlog):
                    raise TorrentError(
                        "all peer/webseed sources failed with pieces "
                        "remaining"
                    )
                try:
                    async with asyncio.timeout(0.5):
                        await swarm.piece_event.wait()
                except TimeoutError:
                    pass
                swarm.piece_event.clear()
                # stream per-file completion to the staging pipeline as
                # soon as a file's last piece lands — the whole point of
                # the overlap: egress starts while ingress continues
                await self._drain_file_completions(swarm, storage,
                                                   on_file_complete)
                if server is not None:
                    for index in swarm.done - announced:
                        announced.add(index)
                        await server.add_piece(index)
            # the loop exits the tick the last piece finishes, so any
            # files it completed are still queued — announce them before
            # returning control to the caller
            await self._drain_file_completions(swarm, storage,
                                               on_file_complete)
            # download complete: give the discovery registration a bounded
            # grace — a fast download must not cancel the re-announce that
            # makes the lingering seed findable by sibling replicas
            if announce_task is not None and not announce_task.done():
                try:
                    async with asyncio.timeout(5.0):
                        await announce_task
                except TimeoutError:
                    pass
        finally:
            reporter.cancel()
            if announce_task is not None:
                announce_task.cancel()
            for w in workers + ws_workers:
                w.cancel()
            await asyncio.gather(reporter, *workers, *ws_workers,
                                 return_exceptions=True)

    async def _advertise(self, swarm: _Swarm) -> None:
        """Register our serving socket with every discovery channel
        (best-effort): the DHT, and a tracker re-announce carrying the real
        listen port — real trackers hand that address to other announcers,
        so replicas staging the same torrent find each other.  Peers the
        re-announce returns feed the worker pool like ut_pex gossip.

        Channels run concurrently: a slow DHT walk or one dead tracker
        must not starve the others inside the completion grace window."""
        meta = swarm.meta
        port = swarm.listen_port
        left = max(meta.total_length - swarm.bytes_done, 0)

        async def _dht() -> None:
            try:
                ok = await self.dht.announce(meta.info_hash, port)
                self._log("dht announce", confirmed_by=ok)
            except Exception as err:
                self._log("dht announce failed", error=str(err))

        jobs = [self._announce_all(meta.trackers, meta.info_hash, left,
                                   port=port)]
        if self.dht is not None:
            jobs.append(_dht())
        results = await asyncio.gather(*jobs)
        for peer in results[0]:
            swarm.discovered.put_nowait((peer.host, peer.port))

    # ------------------------------------------------------------------
    async def _resolve(self, uri: str, peers, metadata_timeout: float):
        """uri -> (Metainfo, peers)."""
        if uri.startswith("magnet:"):
            magnet = parse_magnet(uri)
            if peers is None:
                # trackers and DHT are independent sources — overlap them
                # so slow/dead trackers don't serialize in front of the DHT
                tracker_peers, dht_peers = await asyncio.gather(
                    self._announce_all(
                        magnet.trackers, magnet.info_hash, left=1
                    ),
                    self._dht_peers(magnet.info_hash),
                )
                peers = self._merge_peers(
                    tracker_peers,
                    [tracker_mod.Peer(h, p) for h, p in magnet.peer_addrs],
                    dht_peers,
                )
            if not peers:
                raise TorrentError(
                    "magnet link needs reachable peers (trackers, DHT, or "
                    "x.pe all came up empty)"
                )
            try:
                async with asyncio.timeout(metadata_timeout):
                    meta = await self._fetch_metadata(magnet, peers)
            except TimeoutError:
                raise MetadataTimeoutError("Metadata fetch stalled") from None
            return meta, peers

        if uri.startswith(("http://", "https://")):
            async with aiohttp.ClientSession(trust_env=True) as session:
                async with session.get(uri) as resp:
                    resp.raise_for_status()
                    data = await resp.read()
            meta = parse_torrent_bytes(data)
        else:
            path = uri[len("file://"):] if uri.startswith("file://") else uri
            # graftlint: disable=blocking-call-in-async -- .torrent metainfo is KBs (bounded by piece-hash list)
            with open(path, "rb") as fh:
                meta = parse_torrent_bytes(fh.read())

        if peers is None:
            tracker_peers, dht_peers = await asyncio.gather(
                self._announce_all(
                    meta.trackers, meta.info_hash, left=meta.total_length
                ),
                self._dht_peers(meta.info_hash),
            )
            peers = self._merge_peers(tracker_peers, dht_peers)
        return meta, peers

    async def _dht_peers(self, info_hash: bytes) -> List[tracker_mod.Peer]:
        if self.dht is None:
            return []
        try:
            found = await self.dht.get_peers(info_hash)
        except Exception as err:
            self._log("dht lookup failed", error=str(err))
            return []
        if found:
            self._log("dht peers found", count=len(found))
        return found

    @staticmethod
    def _merge_peers(*groups) -> List[tracker_mod.Peer]:
        seen = set()
        out: List[tracker_mod.Peer] = []
        for group in groups:
            for peer in group:
                if (peer.host, peer.port) not in seen:
                    seen.add((peer.host, peer.port))
                    out.append(peer)
        return out

    async def _announce_all(self, trackers: List[str], info_hash: bytes,
                            left: int, port: int = 0,
                            event: str = "started") -> List[tracker_mod.Peer]:
        """Announce to every tracker concurrently (dead trackers must not
        serialize their timeouts) and pool the peers they return — dedup
        is owned by _merge_peers at the call sites.

        ``port=0`` marks a discover-only announce: we are not (yet)
        listening, and registering trackers must not hand our address out
        (0 is the BEP 23 "not connectable" convention).  The re-announce
        from :meth:`_advertise` passes the real serve port.
        """
        async def _one(url: str) -> List[tracker_mod.Peer]:
            try:
                return await tracker_mod.announce_with_retry(
                    url, info_hash, self.peer_id, port=port, left=left,
                    event=event, retries=self.tracker_retries,
                )
            except Exception as err:
                self._log("tracker announce failed", tracker=url,
                          event=event, error=str(err))
                return []

        groups = await asyncio.gather(*(_one(u) for u in trackers))
        return [peer for group in groups for peer in group]

    # -- metadata over ut_metadata (BEP 9) ------------------------------
    async def _fetch_metadata(self, magnet, peers) -> Metainfo:
        last_error: Optional[Exception] = None
        for peer_addr in peers:
            try:
                return await self._fetch_metadata_from(magnet, peer_addr)
            except (ConnectionError, OSError, asyncio.IncompleteReadError,
                    wire.WireError) as err:
                last_error = err
                continue
        raise TorrentError(f"metadata fetch failed from all peers: {last_error}")

    async def _fetch_metadata_from(self, magnet, peer_addr) -> Metainfo:
        peer = await self._connect(peer_addr, magnet.info_hash)
        try:
            # wait for their extended handshake
            while peer.peer_metadata_size is None:
                msg_id, payload = await peer.recv_message()
                if msg_id == wire.MSG_EXTENDED and payload[0] == wire.EXT_HANDSHAKE_ID:
                    peer.handle_ext_handshake(payload[1:])
            total = peer.peer_metadata_size
            num_pieces = (total + wire.METADATA_PIECE_SIZE - 1) // wire.METADATA_PIECE_SIZE
            chunks: dict = {}
            for i in range(num_pieces):
                await peer.send_metadata_request(i)
            while len(chunks) < num_pieces:
                msg_id, payload = await peer.recv_message()
                if msg_id != wire.MSG_EXTENDED or payload[0] == wire.EXT_HANDSHAKE_ID:
                    continue
                from .bencode import bdecode_prefix

                header, consumed = bdecode_prefix(payload[1:])
                if header.get(b"msg_type") == wire.MD_DATA:
                    chunks[header[b"piece"]] = payload[1 + consumed:]
                elif header.get(b"msg_type") == wire.MD_REJECT:
                    raise wire.WireError("peer rejected metadata request")
            info_bytes = b"".join(chunks[i] for i in range(num_pieces))[:total]
            if hashlib.sha1(info_bytes).digest() != magnet.info_hash:
                raise wire.WireError("metadata hash mismatch")
            return parse_info_dict(info_bytes, magnet.trackers)
        finally:
            await peer.close()

    # -- webseeds (BEP 19) ----------------------------------------------
    @staticmethod
    def _webseed_urls(uri: str, meta: Metainfo) -> List[str]:
        """HTTP seed URLs: ``url-list`` from the .torrent plus ``ws=`` from
        the magnet (both deduped, http(s) only)."""
        urls = list(meta.webseeds)
        if uri.startswith("magnet:"):
            try:
                for url in parse_magnet(uri).webseeds:
                    if url not in urls:
                        urls.append(url)
            except ValueError:
                pass
        return [u for u in urls if u.startswith(("http://", "https://"))]

    @staticmethod
    def _webseed_file_url(base: str, meta: Metainfo, entry) -> str:
        """BEP 19 URL construction: a base ending in ``/`` is a directory —
        append the torrent-relative path (which already starts with the
        torrent name); otherwise, for single-file torrents, the URL IS the
        file."""
        from urllib.parse import quote

        if len(meta.files) == 1 and not base.endswith("/"):
            return base
        prefix = base if base.endswith("/") else base + "/"
        return prefix + "/".join(quote(part) for part in entry.path.split("/"))

    async def _fetch_webseed_piece(self, session, base: str, meta: Metainfo,
                                   piece: int) -> bytes:
        """Fetch one piece over HTTP Range requests, spanning file
        boundaries in multi-file torrents."""
        start = piece * meta.piece_length
        end = start + meta.piece_size(piece)
        out = bytearray()
        for entry in meta.files:
            lo = max(start, entry.offset)
            hi = min(end, entry.offset + entry.length)
            if lo >= hi:
                continue
            url = self._webseed_file_url(base, meta, entry)
            file_lo, file_hi = lo - entry.offset, hi - entry.offset
            headers = {"Range": f"bytes={file_lo}-{file_hi - 1}"}
            async with asyncio.timeout(60):
                async with session.get(url, headers=headers) as resp:
                    if resp.status not in (200, 206):
                        raise OSError(f"webseed HTTP {resp.status} for {url}")
                    if resp.status == 206:
                        # bounded read: a hostile seed answering a ranged
                        # request with a huge body must not buffer into RAM
                        body = await self._read_bounded(resp, hi - lo)
                    else:
                        # server ignored Range: stream-slice the span out of
                        # the full body (bounded memory) and abort the rest.
                        # Viable only for small files — per-piece prefix
                        # re-transfer is quadratic, so retire big seeds.
                        if entry.length > WEBSEED_NO_RANGE_MAX:
                            raise OSError(
                                f"webseed ignores Range and file is "
                                f"{entry.length} bytes; retiring {url}"
                            )
                        body = await self._stream_slice(resp, file_lo, file_hi)
            if len(body) != hi - lo:
                raise OSError(
                    f"webseed short read: wanted {hi - lo}, got {len(body)}"
                )
            out += body
        return bytes(out)

    @staticmethod
    async def _read_bounded(resp, want: int) -> bytes:
        """Read exactly up to ``want`` bytes; error out (instead of
        buffering) if the server sends more."""
        got = bytearray()
        async for chunk in resp.content.iter_chunked(1 << 16):
            got += chunk
            if len(got) > want:
                raise OSError(
                    f"webseed overlong body: wanted {want}, got >{len(got)}"
                )
        return bytes(got)

    @staticmethod
    async def _stream_slice(resp, lo: int, hi: int) -> bytes:
        """Collect bytes [lo, hi) from a streaming response body without
        buffering the whole payload; closes the connection early once hi is
        reached."""
        got = bytearray()
        offset = 0
        async for chunk in resp.content.iter_chunked(1 << 16):
            start = max(lo - offset, 0)
            end = min(hi - offset, len(chunk))
            if start < end:
                got += chunk[start:end]
            offset += len(chunk)
            if offset >= hi:
                break
        return bytes(got)

    async def _webseed_worker(self, base_url: str, storage: TorrentStorage,
                              swarm: _Swarm) -> None:
        """Drains the swarm from an HTTP seed; participates in claim/release
        and endgame exactly like a peer worker (have = everything)."""
        meta = swarm.meta
        have = set(range(meta.num_pieces))
        failures = 0
        async with aiohttp.ClientSession(trust_env=True) as session:
            while not swarm.complete:
                piece = swarm.claim(have)
                if piece is None:
                    await asyncio.sleep(0.2)  # wait for a release or endgame
                    continue
                try:
                    data = await self._fetch_webseed_piece(
                        session, base_url, meta, piece
                    )
                    if self.rate_limiter is not None:
                        await self.rate_limiter.consume(len(data))
                except (aiohttp.ClientError, TimeoutError, OSError) as err:
                    swarm.release(piece)
                    failures += 1
                    self._log("webseed fetch failed", url=base_url,
                              piece=piece, error=str(err))
                    if failures >= 3:
                        return  # dead seed: leave the swarm to the peers
                    await asyncio.sleep(min(2 ** failures, 10.0))
                    continue
                if hashlib.sha1(data).digest() == meta.piece_hashes[piece]:
                    failures = 0  # consecutive, not cumulative: a healthy
                    # seed must survive rare transient errors over a long
                    # webseed-only download
                    if piece not in swarm.done:  # endgame duplicate guard
                        storage.write_piece(piece, data)
                        swarm.finish(piece)
                        swarm.bytes_from_webseeds += meta.piece_size(piece)
                else:
                    self._log("webseed piece hash mismatch", piece=piece,
                              url=base_url)
                    swarm.hash_failures += 1
                    swarm.release(piece)
                    failures += 1
                    if failures >= 3:
                        return

    # -- resume ---------------------------------------------------------
    async def _resume_from_disk(self, storage: TorrentStorage, swarm: _Swarm) -> None:
        meta = swarm.meta

        # fast path: the ``.dt-resume`` sidecar (resume.py) names pieces
        # verified before the last orderly exit whose files' size+mtime
        # fingerprints still match — those skip the hash entirely, so a
        # restart of a big torrent costs stat calls, not a full re-read
        trusted = await asyncio.to_thread(
            resume_mod.load_resume, storage.root, meta
        ) or set()

        def _scan() -> list:
            # runs in a worker thread: hashing a multi-GB torrent must not
            # block the event loop
            intact = []
            for index in range(meta.num_pieces):
                if index in trusted:
                    continue
                data = storage.read_piece(index)
                if hashlib.sha1(data).digest() == meta.piece_hashes[index]:
                    intact.append(index)
            return intact

        hashed = await asyncio.to_thread(_scan)
        for index in list(trusted) + hashed:
            swarm.pending.discard(index)
            swarm.done.add(index)
            swarm.bytes_done += meta.piece_size(index)
            swarm.bytes_resumed += meta.piece_size(index)
        if swarm.done:
            self._log("resumed pieces from disk", count=len(swarm.done),
                      fast_resume=len(trusted), rehashed=len(hashed))

    # -- progress -------------------------------------------------------
    async def _report_progress(self, swarm: _Swarm, watchdog: StallWatchdog,
                               interval: float, on_progress: Optional[ProgressCb]):
        total = swarm.meta.total_length or 1
        # the watchdog (and any progress_sink riding its feed) ticks on a
        # short cadence: the stall check only compares across its own
        # 240 s windows, but the flight-recorder profiler samples the
        # fed counters every few seconds and must not see a 30 s-flat
        # counter as a stalled transfer.  on_progress keeps the
        # reference's coarser telemetry cadence (lib/download.js:88).
        tick = min(interval, 1.0)
        elapsed = 0.0
        while True:
            await asyncio.sleep(tick)
            elapsed += tick
            watchdog.feed(swarm.bytes_done)
            if on_progress is not None and elapsed + 1e-9 >= interval:
                elapsed = 0.0
                await on_progress(swarm.bytes_done / total)

    # -- peer plumbing ---------------------------------------------------
    async def _connect(self, peer_addr, info_hash: bytes,
                       listen_port: Optional[int] = None) -> wire.PeerWire:
        # MSE/PE: "prefer" tries the encrypted handshake first and retries
        # plaintext on a fresh connection if the peer rejects it (the
        # handshake is unrecoverable mid-stream); "require" never falls
        # back; "plaintext" never initiates.  Only a failure DURING the MSE
        # exchange triggers the retry — a dead address (TCP connect
        # failure) or an error after encryption is already up propagates
        # immediately, so dead peers are not dialed twice and an
        # encryption-capable peer is never silently downgraded.
        attempts = {"plaintext": [False], "prefer": [True, False],
                    "require": [True]}[self.crypto]
        for use_mse in attempts:
            last_attempt = use_mse is attempts[-1]
            try:
                return await self._connect_once(
                    peer_addr, info_hash, listen_port, use_mse
                )
            except _MSERejected as rejected:
                if last_attempt:
                    raise rejected.cause
                if self.logger is not None:
                    self.logger.debug(
                        "MSE handshake rejected; retrying plaintext",
                        peer=str(peer_addr), error=str(rejected.cause),
                    )
        raise AssertionError("unreachable")  # pragma: no cover

    async def _open_stream(self, peer_addr):
        """Dial the peer per the transport policy.  ``auto`` gives TCP
        the first 60% of the budget, then falls back to uTP on the same
        port — a NAT'd or TCP-filtered peer is usually still reachable
        over UDP (the reference's webtorrent dials both in parallel;
        sequential-with-fallback avoids double-connecting the common
        case)."""
        if self.transport == "tcp":
            async with asyncio.timeout(CONNECT_TIMEOUT):
                return await asyncio.open_connection(
                    peer_addr.host, peer_addr.port)
        if self.transport == "utp":
            return await utp.open_utp_connection(
                peer_addr.host, peer_addr.port, timeout=CONNECT_TIMEOUT)
        try:
            async with asyncio.timeout(CONNECT_TIMEOUT * 0.6):
                return await asyncio.open_connection(
                    peer_addr.host, peer_addr.port)
        except (OSError, TimeoutError) as err:
            if self.logger is not None:
                self.logger.debug(
                    "tcp dial failed; falling back to uTP",
                    peer=str(peer_addr), error=str(err),
                )
            return await utp.open_utp_connection(
                peer_addr.host, peer_addr.port,
                timeout=CONNECT_TIMEOUT * 0.4)

    async def _connect_once(self, peer_addr, info_hash: bytes,
                            listen_port: Optional[int],
                            use_mse: bool) -> wire.PeerWire:
        reader, writer = await self._open_stream(peer_addr)
        if use_mse:
            try:
                # bound the whole exchange with the connect budget: a peer
                # that reads our DH bytes but never answers (e.g. a
                # plaintext-only implementation waiting for more
                # "handshake") must not pin the dial for the full
                # mse.HANDSHAKE_TIMEOUT
                async with asyncio.timeout(CONNECT_TIMEOUT):
                    reader, writer, _method = await mse.initiate(
                        reader, writer, info_hash,
                        allow_plaintext=self.crypto != "require",
                    )
            except (mse.MSEError, EOFError, ConnectionError,
                    TimeoutError) as err:
                writer.close()
                raise _MSERejected(err) from err
            except BaseException:
                writer.close()
                raise
        peer = wire.PeerWire(reader, writer)
        try:
            await peer.send_handshake(info_hash, self.peer_id)
            handshake = await peer.recv_handshake()
            if handshake.info_hash != info_hash:
                raise wire.WireError("infohash mismatch in handshake")
            if handshake.peer_id == self.peer_id:
                # tracker/pex can echo our own advertised address back
                raise wire.WireError("connected to self")
            if handshake.supports_extensions:
                await peer.send_ext_handshake(listen_port=listen_port)
            return peer
        except BaseException:
            # close on ANY failure (including cancellation from the caller's
            # metadata timeout) — a leaked open connection keeps the remote
            # peer's transport alive indefinitely
            await peer.close()
            raise

    async def _peer_worker(self, peer_addr, storage: TorrentStorage,
                           swarm: _Swarm) -> None:
        meta = swarm.meta
        try:
            peer = await self._connect(peer_addr, meta.info_hash,
                                       listen_port=swarm.listen_port)
        except Exception as err:
            self._log("peer connect failed", peer=str(peer_addr), error=str(err))
            return
        have: Set[int] = set()
        choked = True
        interested_sent = False

        # per-piece assembly state: up to MAX_ACTIVE_CLAIMS pieces are in
        # flight at once, so the request pipeline never drains while the
        # tail blocks of one piece are still in transit (a single-claim
        # worker stalls at every piece boundary)
        active: Dict[int, _Assembly] = {}

        async def _add_have(indices: Set[int]) -> None:
            nonlocal interested_sent
            fresh = indices - have
            have.update(fresh)
            swarm.availability.update(fresh)
            if not interested_sent:
                await peer.send_message(wire.MSG_INTERESTED)
                interested_sent = True

        def _blocks(piece: int) -> List[int]:
            return list(range(0, meta.piece_size(piece), BLOCK_SIZE))

        async def _abandon_if_done_elsewhere() -> None:
            # endgame: another worker finished one of our pieces first —
            # cancel its in-flight requests (BEP 3) and free the slot
            for piece in [p for p in active if p in swarm.done]:
                asm = active.pop(piece)
                for begin in asm.requested - asm.received:
                    length = min(BLOCK_SIZE, meta.piece_size(piece) - begin)
                    await peer.send_cancel(piece, begin, length)

        async def _pump_requests() -> None:
            await _abandon_if_done_elsewhere()
            if choked:
                return
            outstanding = sum(
                len(a.requested - a.received) for a in active.values()
            )
            while outstanding < PIPELINE_DEPTH:
                for piece, asm in list(active.items()):
                    while asm.pending and outstanding < PIPELINE_DEPTH:
                        begin = asm.pending.popleft()
                        if begin in asm.requested or begin in asm.received:
                            continue
                        length = min(
                            BLOCK_SIZE, meta.piece_size(piece) - begin
                        )
                        await peer.send_request(piece, begin, length)
                        asm.requested.add(begin)
                        outstanding += 1
                if outstanding >= PIPELINE_DEPTH:
                    return
                if len(active) >= MAX_ACTIVE_CLAIMS:
                    return
                piece = swarm.claim(have)
                if piece is None or piece in active:
                    # nothing claimable — or endgame handed back one of
                    # our own in-flight pieces
                    return
                active[piece] = _Assembly(meta.piece_size(piece))

        idle_rounds = 0
        try:
            while not swarm.complete:
                try:
                    # bounded recv so an idle (unchoked but messageless)
                    # connection still re-pumps requests — e.g. to pick up a
                    # piece another worker released
                    async with asyncio.timeout(5.0):
                        msg_id, payload = await peer.recv_message()
                    idle_rounds = 0
                except TimeoutError:
                    idle_rounds += 1
                    if idle_rounds % 12 == 0:  # ~60 s idle: BEP 3 keep-alive
                        await peer.send_keepalive()
                    await _pump_requests()
                    continue
                if msg_id is None:
                    continue
                if msg_id == wire.MSG_BITFIELD:
                    await _add_have(wire.parse_bitfield(payload,
                                                       meta.num_pieces))
                elif msg_id == wire.MSG_HAVE:
                    (index,) = struct.unpack(">I", payload)
                    await _add_have({index})
                elif msg_id == wire.MSG_HAVE_ALL:  # BEP 6
                    await _add_have(set(range(meta.num_pieces)))
                elif msg_id == wire.MSG_HAVE_NONE:  # BEP 6
                    swarm.availability.subtract(have)
                    have.clear()
                elif msg_id == wire.MSG_REJECT_REQUEST:  # BEP 6
                    index, begin, _length = struct.unpack(">III", payload)
                    asm = active.get(index)
                    if asm is None:
                        continue
                    asm.requeue(begin)
                    if choked:
                        # BEP 6: fast peers reject all in-flight requests
                        # when choking — the piece is fine, the unchoke
                        # re-pump re-requests it; the blocks we already
                        # hold stay held
                        continue
                    asm.rejects[begin] = asm.rejects.get(begin, 0) + 1
                    if asm.rejects[begin] >= 2:
                        # repeatedly refused while unchoked: this peer
                        # won't serve the piece — hand it to the others
                        if index in have:
                            have.discard(index)
                            swarm.availability[index] -= 1
                        swarm.release(index)
                        active.pop(index, None)
                    await _pump_requests()
                elif msg_id == wire.MSG_UNCHOKE:
                    choked = False
                    await _pump_requests()
                elif msg_id == wire.MSG_CHOKE:
                    choked = True
                    # BEP 3: a choke discards the peer's request queue, so
                    # undelivered requests must be re-sent after unchoke
                    for asm in active.values():
                        asm.rebuild_pending()
                elif msg_id == wire.MSG_EXTENDED:
                    if payload[0] == wire.EXT_HANDSHAKE_ID:
                        peer.handle_ext_handshake(payload[1:])
                    elif payload[0] == peer.our_ut_pex:
                        # ut_pex gossip (BEP 11): hand new addresses to the
                        # pool manager in download()
                        for addr in wire.parse_pex(payload[1:]):
                            swarm.discovered.put_nowait(addr)
                elif msg_id == wire.MSG_PIECE:
                    if self.rate_limiter is not None:
                        await self.rate_limiter.consume(len(payload))
                    index, begin = struct.unpack(">II", payload[:8])
                    data = payload[8:]
                    asm = active.get(index)
                    if asm is None:
                        continue
                    if (begin % BLOCK_SIZE
                            or begin + len(data) > len(asm.buffer)):
                        # untrusted wire bytes: a misaligned or oversized
                        # block would silently grow the buffer (bytearray
                        # slice assignment appends past the end) and
                        # poison the completion check
                        continue
                    asm.buffer[begin:begin + len(data)] = data
                    asm.received.add(begin)
                    if asm.received == set(_blocks(index)):
                        piece_bytes = bytes(asm.buffer)
                        digest = hashlib.sha1(piece_bytes).digest()
                        if digest == meta.piece_hashes[index]:
                            # skip when an endgame duplicate landed second —
                            # the winner already wrote it (no await between
                            # the check and finish, so this is atomic)
                            if index not in swarm.done:
                                storage.write_piece(index, piece_bytes)
                                swarm.finish(index)
                        else:
                            self._log("piece hash mismatch", piece=index)
                            swarm.hash_failures += 1
                            swarm.release(index)
                        active.pop(index, None)
                    await _pump_requests()
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                wire.WireError, struct.error, IndexError, ValueError,
                AttributeError, TypeError) as err:
            # struct/Index/Value/Attribute/Type errors come from malformed
            # frames (e.g. a bencoded non-dict where a dict belongs) —
            # untrusted wire bytes, so treat them like a dead peer
            self._log("peer connection lost", peer=str(peer_addr), error=str(err))
        finally:
            for piece in active:
                swarm.release(piece)
            # this peer's copies no longer count toward piece availability
            swarm.availability.subtract(have)
            await peer.close()

    def _log(self, msg: str, **extra) -> None:
        if self.logger is not None:
            self.logger.info(msg, **extra)
