"""Torrent metainfo: parse/build ``.torrent`` info dicts (BEP 3).

Supports single-file and multi-file torrents.  The infohash is SHA-1 of the
canonically re-encoded ``info`` dict — the identity the whole protocol keys
on (handshakes, tracker announces, magnet links).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import List, Optional

from .bencode import bdecode, bencode

BLOCK_SIZE = 1 << 14  # 16 KiB, the universal request granularity


@dataclasses.dataclass(frozen=True)
class FileEntry:
    path: str          # relative path inside the torrent (''/'-joined)
    length: int
    offset: int        # absolute byte offset in the torrent's linear stream


@dataclasses.dataclass(frozen=True)
class Metainfo:
    info_hash: bytes           # 20-byte SHA-1
    name: str
    piece_length: int
    piece_hashes: List[bytes]  # 20 bytes each
    files: List[FileEntry]
    info_bytes: bytes          # canonical bencoded info dict (for ut_metadata)
    trackers: List[str] = dataclasses.field(default_factory=list)
    # BEP 19 HTTP seeds (``url-list`` in .torrent / ``ws=`` in magnets)
    webseeds: List[str] = dataclasses.field(default_factory=list)

    @property
    def total_length(self) -> int:
        return sum(f.length for f in self.files)

    @property
    def num_pieces(self) -> int:
        return len(self.piece_hashes)

    def piece_size(self, index: int) -> int:
        if index == self.num_pieces - 1:
            remainder = self.total_length - self.piece_length * index
            return remainder
        return self.piece_length

    def to_torrent_bytes(self) -> bytes:
        """Serialize back to a ``.torrent`` file."""
        data: dict = {b"info": bdecode(self.info_bytes)}
        if self.trackers:
            data[b"announce"] = self.trackers[0].encode()
            if len(self.trackers) > 1:
                data[b"announce-list"] = [[t.encode()] for t in self.trackers]
        if self.webseeds:
            data[b"url-list"] = [u.encode() for u in self.webseeds]
        return bencode(data)


def parse_info_dict(info_bytes: bytes, trackers: Optional[List[str]] = None,
                    webseeds: Optional[List[str]] = None) -> Metainfo:
    """Build a :class:`Metainfo` from a bencoded info dict."""
    info = bdecode(info_bytes)
    canonical = bencode(info)
    info_hash = hashlib.sha1(canonical).digest()
    name = info[b"name"].decode("utf-8", "surrogateescape")
    piece_length = info[b"piece length"]
    pieces_blob = info[b"pieces"]
    if len(pieces_blob) % 20 != 0:
        raise ValueError("pieces blob not a multiple of 20 bytes")
    piece_hashes = [pieces_blob[i:i + 20] for i in range(0, len(pieces_blob), 20)]

    files: List[FileEntry] = []
    if b"files" in info:  # multi-file: paths nest under the torrent name
        offset = 0
        for entry in info[b"files"]:
            rel = "/".join(
                part.decode("utf-8", "surrogateescape") for part in entry[b"path"]
            )
            files.append(FileEntry(path=f"{name}/{rel}", length=entry[b"length"],
                                   offset=offset))
            offset += entry[b"length"]
    else:
        files.append(FileEntry(path=name, length=info[b"length"], offset=0))

    expected = sum(f.length for f in files)
    max_len = piece_length * len(piece_hashes)
    if not (max_len - piece_length < expected <= max_len):
        raise ValueError(
            f"length {expected} inconsistent with {len(piece_hashes)} pieces "
            f"of {piece_length}"
        )
    return Metainfo(
        info_hash=info_hash,
        name=name,
        piece_length=piece_length,
        piece_hashes=piece_hashes,
        files=files,
        info_bytes=canonical,
        trackers=list(trackers or []),
        webseeds=list(webseeds or []),
    )


def parse_torrent_bytes(data: bytes) -> Metainfo:
    """Parse a ``.torrent`` file's bytes."""
    outer = bdecode(data)
    trackers: List[str] = []
    if b"announce-list" in outer:
        for tier in outer[b"announce-list"]:
            for tracker in tier:
                url = tracker.decode()
                if url not in trackers:
                    trackers.append(url)
    if b"announce" in outer:
        url = outer[b"announce"].decode()
        if url not in trackers:
            trackers.insert(0, url)
    webseeds: List[str] = []
    url_list = outer.get(b"url-list", [])
    if isinstance(url_list, bytes):  # BEP 19 allows a bare string
        url_list = [url_list]
    for entry in url_list:
        if isinstance(entry, bytes):
            url = entry.decode("utf-8", "surrogateescape")
            if url not in webseeds:
                webseeds.append(url)
    return parse_info_dict(bencode(outer[b"info"]), trackers, webseeds)


def make_metainfo(
    root: str,
    name: Optional[str] = None,
    piece_length: int = 1 << 18,
    trackers: Optional[List[str]] = None,
    webseeds: Optional[List[str]] = None,
) -> Metainfo:
    """Create metainfo for a file or directory on disk (the seeding side).

    Directory sources become multi-file torrents with deterministic
    (sorted) file order.
    """
    if piece_length < BLOCK_SIZE:
        # non-positive values would spin _feed forever; tiny ones break
        # the universal 16 KiB request granularity
        raise ValueError(
            f"piece_length {piece_length} < BLOCK_SIZE {BLOCK_SIZE}"
        )
    root = os.path.abspath(root)
    name = name or os.path.basename(root)

    paths: List[str] = []
    if os.path.isdir(root):
        for dirpath, _dirnames, filenames in os.walk(root):
            for filename in filenames:
                paths.append(os.path.join(dirpath, filename))
        paths.sort()
    else:
        paths.append(root)

    hasher = hashlib.sha1()
    piece_hashes: List[bytes] = []
    in_piece = 0

    def _feed(chunk: bytes) -> None:
        nonlocal hasher, in_piece
        view = memoryview(chunk)
        while view:
            take = min(len(view), piece_length - in_piece)
            hasher.update(view[:take])
            in_piece += take
            view = view[take:]
            if in_piece == piece_length:
                piece_hashes.append(hasher.digest())
                hasher = hashlib.sha1()
                in_piece = 0

    entries = []
    for path in paths:
        length = os.path.getsize(path)
        with open(path, "rb") as fh:
            while True:
                chunk = fh.read(1 << 20)
                if not chunk:
                    break
                _feed(chunk)
        entries.append((path, length))
    if in_piece:
        piece_hashes.append(hasher.digest())

    pieces_blob = b"".join(piece_hashes)
    if os.path.isdir(root):
        info = {
            b"name": name.encode(),
            b"piece length": piece_length,
            b"pieces": pieces_blob,
            b"files": [
                {
                    b"length": length,
                    b"path": [
                        part.encode()
                        for part in os.path.relpath(path, root).split(os.sep)
                    ],
                }
                for path, length in entries
            ],
        }
    else:
        info = {
            b"name": name.encode(),
            b"piece length": piece_length,
            b"pieces": pieces_blob,
            b"length": entries[0][1],
        }
    return parse_info_dict(bencode(info), trackers, webseeds)
