"""Mainline DHT (BEP 5): trackerless peer discovery over KRPC/UDP.

The reference's webtorrent client discovers peers through the mainline DHT
in addition to trackers (/root/reference/lib/download.js:19,64 — webtorrent
bundles ``bittorrent-dht``).  This module is a from-scratch asyncio
implementation of the same protocol:

- a KRPC node (bencoded queries/responses over UDP) answering ``ping``,
  ``find_node``, ``get_peers`` and ``announce_peer``
- a k-bucket routing table (k=8) over the 160-bit XOR metric
- iterative lookups (``alpha``-parallel) for ``get_peers``
- write-token validation for ``announce_peer`` (rotating HMAC secret,
  tokens accepted for up to ~10 minutes per BEP 5)
- a bounded per-infohash peer store for the server side

The torrent client uses :meth:`DHTNode.get_peers` as an additional peer
source next to tracker announces, covering magnets with no (or dead)
trackers.  :meth:`DHTNode.announce` is the write side: the client calls it
(best-effort) once its seed-while-leech listen socket is up, registering
that socket so other DHT nodes can find and leech from it.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hashlib
import hmac
import os
import socket
import struct
import time
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .bencode import bdecode, bencode
from .tracker import Peer, parse_compact_peers

K = 8                    # bucket size / closest-set size (BEP 5)
ALPHA = 3                # lookup concurrency
QUERY_TIMEOUT = 3.0      # per-query UDP timeout
LOOKUP_DEADLINE = 20.0   # hard wall-clock bound on one iterative lookup
MAX_LOOKUP_QUERIES = 64  # hard bound on nodes contacted per lookup
TOKEN_ROTATE_S = 300.0   # secret rotation period; previous secret stays valid
MAX_PEERS_PER_HASH = 256
MAX_STORED_HASHES = 1024


class DHTError(RuntimeError):
    pass


@dataclasses.dataclass(frozen=True)
class NodeInfo:
    node_id: bytes
    host: str
    port: int

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)


def xor_distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


def pack_nodes(nodes: Iterable[NodeInfo]) -> bytes:
    """BEP 5 compact node info: 20-byte id + 4-byte IP + 2-byte port each."""
    out = bytearray()
    for node in nodes:
        try:
            ip = socket.inet_aton(node.host)
        except OSError:
            continue  # non-IPv4 (e.g. hostname): not representable
        out += node.node_id + ip + struct.pack(">H", node.port)
    return bytes(out)


def unpack_nodes(blob: bytes) -> List[NodeInfo]:
    nodes = []
    for off in range(0, len(blob) - len(blob) % 26, 26):
        node_id = blob[off:off + 20]
        host = socket.inet_ntoa(blob[off + 20:off + 24])
        (port,) = struct.unpack(">H", blob[off + 24:off + 26])
        if port:
            nodes.append(NodeInfo(node_id, host, port))
    return nodes


def pack_peers(peers: Iterable[Tuple[str, int]]) -> List[bytes]:
    """BEP 5 ``values``: list of 6-byte compact peer addresses."""
    out = []
    for host, port in peers:
        try:
            ip = socket.inet_aton(host)
        except OSError:
            continue
        out.append(ip + struct.pack(">H", port))
    return out


def unpack_peers(values: Iterable[bytes]) -> List[Peer]:
    """BEP 5 ``values`` (list of 6-byte compact addresses) -> peers.

    Delegates the per-entry decoding to the tracker module's
    :func:`~.tracker.parse_compact_peers` so all compact-peer surfaces
    (HTTP/UDP tracker, DHT) share one parser.
    """
    peers: List[Peer] = []
    for blob in values:
        if isinstance(blob, bytes) and len(blob) == 6:
            peers.extend(parse_compact_peers(blob))
    return peers


class RoutingTable:
    """k-bucket table over the XOR metric.

    Buckets are indexed by the position of the highest differing bit from
    our own id (i.e. shared-prefix length), each holding at most ``K``
    nodes, least-recently-seen first.  Full buckets drop new nodes unless a
    stale resident can be evicted — the standard BEP 5 policy favoring
    long-lived nodes.
    """

    def __init__(self, own_id: bytes, k: int = K):
        self.own_id = own_id
        self.k = k
        self.buckets: List[List[NodeInfo]] = [[] for _ in range(160)]
        self.last_seen: Dict[bytes, float] = {}

    def _bucket_index(self, node_id: bytes) -> int:
        dist = xor_distance(self.own_id, node_id)
        if dist == 0:
            return 0
        return 160 - dist.bit_length()

    def add(self, node: NodeInfo) -> None:
        if node.node_id == self.own_id or len(node.node_id) != 20:
            return
        bucket = self.buckets[self._bucket_index(node.node_id)]
        for i, existing in enumerate(bucket):
            if existing.node_id == node.node_id:
                # move to tail (most recently seen), refresh address
                bucket.pop(i)
                bucket.append(node)
                self.last_seen[node.node_id] = time.monotonic()
                return
        if len(bucket) < self.k:
            bucket.append(node)
            self.last_seen[node.node_id] = time.monotonic()
            return
        # full: evict the least-recently-seen node if it has gone quiet
        oldest = bucket[0]
        if time.monotonic() - self.last_seen.get(oldest.node_id, 0) > 15 * 60:
            self.last_seen.pop(oldest.node_id, None)
            bucket.pop(0)
            bucket.append(node)
            self.last_seen[node.node_id] = time.monotonic()

    def remove(self, node_id: bytes) -> None:
        bucket = self.buckets[self._bucket_index(node_id)]
        for i, existing in enumerate(bucket):
            if existing.node_id == node_id:
                bucket.pop(i)
                self.last_seen.pop(node_id, None)
                return

    def closest(self, target: bytes, count: int = K) -> List[NodeInfo]:
        everyone = [n for bucket in self.buckets for n in bucket]
        everyone.sort(key=lambda n: xor_distance(n.node_id, target))
        return everyone[:count]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets)


class _Protocol(asyncio.DatagramProtocol):
    def __init__(self, node: "DHTNode"):
        self.node = node
        self.transport: Optional[asyncio.DatagramTransport] = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        self.node._on_datagram(data, addr)


class DHTNode:
    """One mainline-DHT participant: client (lookups) + server (storage)."""

    def __init__(self, node_id: Optional[bytes] = None, logger=None):
        self.node_id = node_id or os.urandom(20)
        self.logger = logger
        self.table = RoutingTable(self.node_id)
        self.transport: Optional[asyncio.DatagramTransport] = None
        # txn -> (future, addr the query was sent to)
        self._pending: Dict[bytes, Tuple[asyncio.Future, Tuple[str, int]]] = {}
        self._secret = os.urandom(16)
        self._prev_secret = self._secret
        self._secret_rotated = time.monotonic()
        # info_hash -> {(host, port): announced_at}
        self._peer_store: Dict[bytes, Dict[Tuple[str, int], float]] = {}

    # -- lifecycle -------------------------------------------------------
    async def start(self, host: str = "0.0.0.0", port: int = 0) -> None:
        loop = asyncio.get_running_loop()
        self.transport, _ = await loop.create_datagram_endpoint(
            lambda: _Protocol(self), local_addr=(host, port)
        )

    @property
    def port(self) -> int:
        if self.transport is None:
            raise DHTError("node not started")
        return self.transport.get_extra_info("sockname")[1]

    async def close(self) -> None:
        if self.transport is not None:
            self.transport.close()
            self.transport = None
        for fut, _addr in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()

    # -- routing-table persistence ---------------------------------------
    def dump_nodes(self, limit: int = 200) -> List[Tuple[str, int]]:
        """Known-good node addresses, most-recently-seen first — feed them
        back into :meth:`bootstrap` on the next start so a restarted
        service rejoins the DHT without waiting on the public routers."""
        nodes = [
            node for bucket in self.table.buckets for node in bucket
        ]
        nodes.sort(
            key=lambda n: self.table.last_seen.get(n.node_id, 0.0),
            reverse=True,
        )
        return [(n.host, n.port) for n in nodes[:limit]]

    def save_nodes(self, path: str) -> int:
        """Persist :meth:`dump_nodes` as JSON; returns the count saved."""
        import json

        nodes = self.dump_nodes()
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(nodes, fh)
        os.replace(tmp, path)
        return len(nodes)

    @staticmethod
    def load_nodes(path: str) -> List[Tuple[str, int]]:
        """Addresses previously saved with :meth:`save_nodes`; empty on
        any problem (a corrupt cache must not block bootstrap)."""
        import json

        try:
            with open(path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
            return [
                (str(host), int(port))
                for host, port in raw
                if 0 < int(port) < 65536
            ]
        except (OSError, ValueError, TypeError):
            return []

    async def bootstrap(self, nodes: Iterable[Tuple[str, int]]) -> int:
        """Ping the given routers and walk toward our own id to fill the
        table.  Returns the resulting routing-table size."""
        async def _ping(addr) -> None:
            try:
                await self._query(addr, b"ping", {})
            except (DHTError, asyncio.TimeoutError, OSError):
                pass

        # independent UDP round-trips: ping in parallel so dead routers
        # don't serialize their timeouts (this also runs under the
        # cross-job dht lock in the download stage)
        await asyncio.gather(*(_ping(addr) for addr in nodes))
        if len(self.table):
            await self._lookup(self.node_id, want_peers=False)
        return len(self.table)

    # -- public API ------------------------------------------------------
    async def get_peers(self, info_hash: bytes) -> List[Peer]:
        """Iterative BEP 5 lookup: returns peers announced for ``info_hash``."""
        peers, _ = await self._lookup(info_hash, want_peers=True)
        return peers

    async def announce(self, info_hash: bytes, port: int) -> int:
        """Announce ourselves as a peer for ``info_hash``.

        Runs a get_peers lookup to collect write tokens, then sends
        announce_peer to the closest responding nodes.  Returns the number
        of successful announces.
        """
        _, closest = await self._lookup(info_hash, want_peers=True)
        ok = 0
        for node, token in closest[:K]:
            if token is None:
                continue
            try:
                await self._query(node.addr, b"announce_peer", {
                    b"info_hash": info_hash,
                    b"port": port,
                    b"token": token,
                    b"implied_port": 0,
                })
                ok += 1
            except (DHTError, asyncio.TimeoutError, OSError):
                continue
        return ok

    # -- iterative lookup ------------------------------------------------
    async def _lookup(
        self, target: bytes, want_peers: bool
    ) -> Tuple[List[Peer], List[Tuple[NodeInfo, Optional[bytes]]]]:
        """Converging alpha-parallel lookup toward ``target``.

        Terminates on the standard Kademlia rule — the ``K`` closest nodes
        seen have all been queried (no unqueried candidate is closer than
        the current K-th closest response) — with hard caps on wall-clock
        (``LOOKUP_DEADLINE``) and total nodes contacted
        (``MAX_LOOKUP_QUERIES``) so a big or adversarial network can never
        hang a download: the caller sits outside the torrent stall
        watchdog.

        Returns (peers found, [(responding node, its write token)] sorted by
        distance to target).
        """
        shortlist: Dict[bytes, NodeInfo] = {
            n.node_id: n for n in self.table.closest(target, K)
        }
        queried: Set[Tuple[str, int]] = set()
        tokens: Dict[bytes, Optional[bytes]] = {}
        responded: Dict[bytes, NodeInfo] = {}
        peers: Dict[Tuple[str, int], Peer] = {}
        deadline = time.monotonic() + LOOKUP_DEADLINE

        while time.monotonic() < deadline and len(queried) < MAX_LOOKUP_QUERIES:
            candidates = sorted(
                (n for n in shortlist.values() if n.addr not in queried),
                key=lambda n: xor_distance(n.node_id, target),
            )[:ALPHA]
            if not candidates:
                break
            if len(responded) >= K:
                kth_best = sorted(
                    xor_distance(node_id, target) for node_id in responded
                )[K - 1]
                if xor_distance(candidates[0].node_id, target) >= kth_best:
                    break  # converged: nothing unqueried can improve the top K
            for node in candidates:
                queried.add(node.addr)

            async def _ask(node: NodeInfo):
                method = b"get_peers" if want_peers else b"find_node"
                args = (
                    {b"info_hash": target} if want_peers
                    else {b"target": target}
                )
                try:
                    resp = await self._query(node.addr, method, args)
                except (DHTError, asyncio.TimeoutError, OSError):
                    return
                node_id = resp.get(b"id", node.node_id)
                if not (isinstance(node_id, bytes) and len(node_id) == 20):
                    # untrusted wire data: a non-bytes/odd-length id would
                    # blow up xor_distance below — fall back to what we knew
                    node_id = node.node_id
                info = NodeInfo(node_id, node.host, node.port)
                responded[node_id] = info
                tokens[node_id] = resp.get(b"token")
                for peer in unpack_peers(resp.get(b"values", [])):
                    peers[(peer.host, peer.port)] = peer
                for found in unpack_nodes(resp.get(b"nodes", b"")):
                    shortlist.setdefault(found.node_id, found)

            await asyncio.gather(*(_ask(n) for n in candidates))

        ranked = sorted(
            responded.values(), key=lambda n: xor_distance(n.node_id, target)
        )
        return list(peers.values()), [
            (n, tokens.get(n.node_id)) for n in ranked
        ]

    # -- KRPC client -----------------------------------------------------
    def _next_txn(self) -> bytes:
        # random (not sequential) so off-path attackers can't predict the
        # next transaction id and forge responses
        while True:
            txn = os.urandom(2)
            if txn not in self._pending:
                return txn

    async def _query(self, addr: Tuple[str, int], method: bytes,
                     args: dict) -> dict:
        if self.transport is None:
            raise DHTError("node not started")
        addr = await self._resolve_addr(addr)
        txn = self._next_txn()
        payload = dict(args)
        payload[b"id"] = self.node_id
        msg = bencode({b"t": txn, b"y": b"q", b"q": method, b"a": payload})
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        # remember who we asked: responses are only accepted from that addr
        self._pending[txn] = (fut, addr)
        try:
            self.transport.sendto(msg, addr)
            async with asyncio.timeout(QUERY_TIMEOUT):
                resp = await fut
        except TimeoutError:
            raise asyncio.TimeoutError(f"DHT query to {addr} timed out")
        finally:
            self._pending.pop(txn, None)
        node_id = resp.get(b"id")
        if isinstance(node_id, bytes) and len(node_id) == 20:
            self.table.add(NodeInfo(node_id, addr[0], addr[1]))
        return resp

    @staticmethod
    async def _resolve_addr(addr: Tuple[str, int]) -> Tuple[str, int]:
        """Hostname -> literal IP, so reply-source matching works (datagram
        sources always arrive as literal addresses)."""
        try:
            socket.inet_aton(addr[0])
            return addr
        except OSError:
            pass
        infos = await asyncio.get_running_loop().getaddrinfo(
            addr[0], addr[1], type=socket.SOCK_DGRAM, family=socket.AF_INET
        )
        return infos[0][4][0], addr[1]

    # -- KRPC server -----------------------------------------------------
    def _on_datagram(self, data: bytes, addr) -> None:
        try:
            msg = bdecode(data)
        except ValueError:
            return
        if not isinstance(msg, dict):
            return
        kind = msg.get(b"y")
        if kind == b"r":
            self._on_response(msg, addr)
        elif kind == b"q":
            try:
                self._on_query(msg, addr)
            except Exception as err:  # malformed queries must not kill the loop
                self._log("dht query handling failed", error=str(err))
        elif kind == b"e":
            fut = self._match_pending(msg, addr)
            if fut is not None:
                err = msg.get(b"e", [201, b"error"])
                fut.set_exception(DHTError(f"remote error {err!r}"))

    def _match_pending(self, msg: dict, addr) -> Optional[asyncio.Future]:
        """Resolve a reply to its pending query — only if the source address
        matches where the query went (BEP 5 forgery defence)."""
        txn = msg.get(b"t")
        entry = self._pending.get(txn) if isinstance(txn, bytes) else None
        if entry is None:
            return None
        fut, queried_addr = entry
        if (addr[0], addr[1]) != queried_addr:
            self._log("dht reply from unexpected address dropped",
                      expected=str(queried_addr), got=str(addr))
            return None
        return fut if not fut.done() else None

    def _on_response(self, msg: dict, addr) -> None:
        fut = self._match_pending(msg, addr)
        if fut is None:
            return
        resp = msg.get(b"r")
        if isinstance(resp, dict):
            fut.set_result(resp)
        else:
            fut.set_exception(DHTError("malformed response"))

    def _on_query(self, msg: dict, addr) -> None:
        if self.transport is None:
            return
        txn = msg.get(b"t", b"")
        method = msg.get(b"q")
        args = msg.get(b"a", {})
        if not isinstance(args, dict):
            args = {}
        sender_id = args.get(b"id")
        if isinstance(sender_id, bytes) and len(sender_id) == 20:
            self.table.add(NodeInfo(sender_id, addr[0], addr[1]))

        def reply(body: dict) -> None:
            body[b"id"] = self.node_id
            self.transport.sendto(
                bencode({b"t": txn, b"y": b"r", b"r": body}), addr
            )

        def error(code: int, text: str) -> None:
            self.transport.sendto(
                bencode({b"t": txn, b"y": b"e",
                         b"e": [code, text.encode()]}), addr
            )

        if method == b"ping":
            reply({})
        elif method == b"find_node":
            target = args.get(b"target", b"")
            reply({b"nodes": pack_nodes(self.table.closest(target, K))})
        elif method == b"get_peers":
            info_hash = args.get(b"info_hash", b"")
            body: dict = {b"token": self._make_token(addr)}
            stored = self._peer_store.get(info_hash)
            if stored:
                body[b"values"] = pack_peers(stored.keys())
            else:
                body[b"nodes"] = pack_nodes(self.table.closest(info_hash, K))
            reply(body)
        elif method == b"announce_peer":
            token = args.get(b"token", b"")
            if not self._check_token(addr, token):
                error(203, "bad token")
                return
            info_hash = args.get(b"info_hash", b"")
            if not isinstance(info_hash, bytes) or len(info_hash) != 20:
                error(203, "bad info_hash")
                return
            port = args.get(b"port", 0)
            if args.get(b"implied_port"):
                port = addr[1]
            if not isinstance(port, int) or not (0 < port < 65536):
                error(203, "bad port")
                return
            self._store_peer(info_hash, (addr[0], port))
            reply({})
        else:
            error(204, "method unknown")

    # -- tokens (BEP 5: opaque write token bound to requester IP) --------
    def _rotate_secrets(self) -> None:
        now = time.monotonic()
        elapsed = now - self._secret_rotated
        if elapsed > 2 * TOKEN_ROTATE_S:
            # idle gap longer than a full rotation cycle: a single-step
            # rotation would keep arbitrarily old tokens valid via
            # _prev_secret — retire both secrets outright
            self._secret = os.urandom(16)
            self._prev_secret = self._secret
            self._secret_rotated = now
        elif elapsed > TOKEN_ROTATE_S:
            self._prev_secret = self._secret
            self._secret = os.urandom(16)
            self._secret_rotated = now

    def _make_token(self, addr) -> bytes:
        self._rotate_secrets()
        return hmac.new(
            self._secret, addr[0].encode(), hashlib.sha1
        ).digest()[:8]

    def _check_token(self, addr, token: bytes) -> bool:
        self._rotate_secrets()
        for secret in (self._secret, self._prev_secret):
            want = hmac.new(secret, addr[0].encode(), hashlib.sha1).digest()[:8]
            if isinstance(token, bytes) and hmac.compare_digest(token, want):
                return True
        return False

    # -- peer store ------------------------------------------------------
    def _store_peer(self, info_hash: bytes, peer: Tuple[str, int]) -> None:
        if (info_hash not in self._peer_store
                and len(self._peer_store) >= MAX_STORED_HASHES):
            return
        store = self._peer_store.setdefault(info_hash, {})
        store[peer] = time.monotonic()
        if len(store) > MAX_PEERS_PER_HASH:
            oldest = min(store, key=store.get)
            store.pop(oldest, None)

    def _log(self, msg: str, **extra) -> None:
        if self.logger is not None:
            self.logger.info(msg, **extra)


def parse_bootstrap(spec: str) -> List[Tuple[str, int]]:
    """``host:port,host:port`` -> [(host, port)] (config/env format)."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        try:
            out.append((host, int(port)))
        except ValueError:
            raise DHTError(f"bad bootstrap node {part!r}") from None
    return out
