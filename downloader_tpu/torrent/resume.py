"""Fast-resume sidecar: skip the full re-hash when nothing changed.

Without resume data, every restart hashes every piece on disk before a
single byte moves (``TorrentClient._resume_from_disk``) — minutes for a
large torrent.  Mainstream clients (libtorrent et al.) persist a resume
record instead; webtorrent relied on re-hashing, so this is a capability
the rebuild adds on top of the reference (which restarted jobs from
zero anyway, SURVEY.md §5 "checkpoint/resume").

The record (``.dt-resume`` JSON in the download directory) holds the
info-hash, the verified-piece bitfield, and each file's (size,
mtime_ns) captured AFTER the last write.  On load, a piece is trusted
only when every file it touches still matches its recorded size and
mtime; anything else falls back to hashing that piece.  The check is
deliberately conservative: a crash mid-write leaves mtimes newer than
the record, so the affected files re-hash; an orderly exit — completed
download, stall-watchdog abort that the queue will redeliver, SIGTERM
drain — resumes instantly.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Dict, Optional, Set

from .metainfo import Metainfo

RESUME_NAME = ".dt-resume"
_VERSION = 1


def _resume_path(root: str) -> str:
    return os.path.join(os.path.abspath(root), RESUME_NAME)


def _pack_bitfield(done: Set[int], num_pieces: int) -> str:
    bits = bytearray((num_pieces + 7) // 8)
    for index in done:
        bits[index >> 3] |= 0x80 >> (index & 7)
    return base64.b64encode(bytes(bits)).decode("ascii")


def _unpack_bitfield(blob: str, num_pieces: int) -> Set[int]:
    bits = base64.b64decode(blob)
    return {
        index for index in range(num_pieces)
        if index >> 3 < len(bits) and bits[index >> 3] & (0x80 >> (index & 7))
    }


def save_resume(root: str, meta: Metainfo, done: Set[int]) -> None:
    """Record the verified bitfield + file fingerprints (best-effort:
    resume data is an optimization, never worth failing a download
    over)."""
    from .storage import TorrentStorage

    storage = TorrentStorage(meta, root)
    files = []
    try:
        for entry in meta.files:
            st = os.stat(storage.file_path(entry.path))
            files.append({
                "path": entry.path,
                "size": st.st_size,
                "mtime_ns": st.st_mtime_ns,
            })
        record = {
            "version": _VERSION,
            "info_hash": meta.info_hash.hex(),
            "num_pieces": meta.num_pieces,
            "bitfield": _pack_bitfield(done, meta.num_pieces),
            "files": files,
        }
        tmp = _resume_path(root) + ".tmp"
        with open(tmp, "w", encoding="ascii") as fh:
            json.dump(record, fh)
        os.replace(tmp, _resume_path(root))
    except OSError:
        pass


def load_resume(root: str, meta: Metainfo) -> Optional[Set[int]]:
    """Trusted verified-piece set, or None when there is no usable record.

    Pieces touching a file whose (size, mtime_ns) changed since the
    record was written are dropped from the returned set — they go back
    through the hash check like any other on-disk data."""
    from .storage import TorrentStorage

    try:
        with open(_resume_path(root), "r", encoding="ascii") as fh:
            record = json.load(fh)
    except (OSError, ValueError):
        return None
    if (record.get("version") != _VERSION
            or record.get("info_hash") != meta.info_hash.hex()
            or record.get("num_pieces") != meta.num_pieces):
        return None

    storage = TorrentStorage(meta, root)
    recorded: Dict[str, dict] = {
        f.get("path"): f for f in record.get("files", [])
    }
    intact_files = set()
    for entry in meta.files:
        info = recorded.get(entry.path)
        if info is None:
            continue
        try:
            st = os.stat(storage.file_path(entry.path))
        except OSError:
            continue
        if (st.st_size == info.get("size")
                and st.st_mtime_ns == info.get("mtime_ns")):
            intact_files.add(entry.path)

    try:
        done = _unpack_bitfield(record["bitfield"], meta.num_pieces)
    except (KeyError, ValueError):
        return None

    piece_len = meta.piece_length
    trusted = set()
    for index in done:
        start = index * piece_len
        end = start + meta.piece_size(index)
        touched_ok = all(
            entry.path in intact_files
            for entry in meta.files
            if entry.offset < end and entry.offset + entry.length > start
        )
        if touched_ok:
            trusted.add(index)
    return trusted
