"""Magnet URI parsing (BEP 9 §magnet): ``magnet:?xt=urn:btih:<hash>&dn=...&tr=...``."""

from __future__ import annotations

import base64
import dataclasses
import urllib.parse
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class MagnetLink:
    info_hash: bytes            # 20-byte SHA-1
    display_name: Optional[str]
    trackers: List[str]
    # x.pe direct peer addresses (BEP 9) as (host, port)
    peer_addrs: tuple = ()
    # ws= webseed URLs (BEP 19 via magnet)
    webseeds: tuple = ()

    @property
    def info_hash_hex(self) -> str:
        return self.info_hash.hex()


def parse_magnet(uri: str) -> MagnetLink:
    parsed = urllib.parse.urlparse(uri)
    if parsed.scheme != "magnet":
        raise ValueError(f"not a magnet URI: {uri[:40]!r}")
    params = urllib.parse.parse_qs(parsed.query)

    info_hash: Optional[bytes] = None
    for xt in params.get("xt", []):
        if xt.startswith("urn:btih:"):
            raw = xt[len("urn:btih:"):]
            if len(raw) == 40:  # hex
                info_hash = bytes.fromhex(raw)
            elif len(raw) == 32:  # base32
                info_hash = base64.b32decode(raw.upper())
            else:
                raise ValueError(f"bad btih length {len(raw)}")
            break
    if info_hash is None:
        raise ValueError("magnet URI has no urn:btih exact topic")

    names = params.get("dn", [])
    peer_addrs = []
    for pe in params.get("x.pe", []):
        host, _, port = pe.rpartition(":")
        try:
            port_num = int(port)
        except ValueError:
            continue
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]  # bracketed IPv6 literal
        if host and 0 < port_num < 65536:  # unconnectable ports waste a
            peer_addrs.append((host, port_num))  # MAX_PEERS worker slot
    return MagnetLink(
        info_hash=info_hash,
        display_name=names[0] if names else None,
        trackers=params.get("tr", []),
        peer_addrs=tuple(peer_addrs),
        webseeds=tuple(params.get("ws", [])),
    )


def make_magnet(info_hash: bytes, name: Optional[str] = None,
                trackers: Optional[List[str]] = None) -> str:
    parts = [f"xt=urn:btih:{info_hash.hex()}"]
    if name:
        parts.append("dn=" + urllib.parse.quote(name, safe=""))
    for tracker in trackers or []:
        parts.append("tr=" + urllib.parse.quote(tracker, safe=""))
    return "magnet:?" + "&".join(parts)
