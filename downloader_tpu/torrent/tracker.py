"""HTTP tracker announce (BEP 3) with compact peer lists (BEP 23)."""

from __future__ import annotations

import dataclasses
import socket
import struct
import urllib.parse
from typing import List

import aiohttp
import yarl

from .bencode import bdecode


@dataclasses.dataclass(frozen=True)
class Peer:
    host: str
    port: int


class TrackerError(RuntimeError):
    pass


async def announce(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: str = "started",
    session: aiohttp.ClientSession | None = None,
) -> List[Peer]:
    """Announce to an HTTP tracker and return its peer list."""
    query = urllib.parse.urlencode(
        {
            "info_hash": info_hash,
            "peer_id": peer_id,
            "port": port,
            "uploaded": uploaded,
            "downloaded": downloaded,
            "left": left,
            "compact": 1,
            "event": event,
        },
        quote_via=urllib.parse.quote,
    )
    sep = "&" if "?" in tracker_url else "?"
    url = f"{tracker_url}{sep}{query}"

    owned = session is None
    session = session or aiohttp.ClientSession()
    try:
        # pre-encoded: the percent-encoded binary info_hash must reach the
        # wire untouched (yarl would otherwise re-quote it)
        async with session.get(yarl.URL(url, encoded=True)) as resp:
            if resp.status != 200:
                raise TrackerError(f"tracker answered {resp.status}")
            body = await resp.read()
    finally:
        if owned:
            await session.close()

    data = bdecode(body)
    if b"failure reason" in data:
        raise TrackerError(data[b"failure reason"].decode("utf-8", "replace"))

    peers = data.get(b"peers", b"")
    out: List[Peer] = []
    if isinstance(peers, bytes):  # compact: 6 bytes per peer
        for i in range(0, len(peers) - len(peers) % 6, 6):
            host = socket.inet_ntoa(peers[i:i + 4])
            (peer_port,) = struct.unpack(">H", peers[i + 4:i + 6])
            out.append(Peer(host, peer_port))
    else:  # non-compact dict form
        for entry in peers:
            out.append(
                Peer(entry[b"ip"].decode(), entry[b"port"])
            )
    return out
