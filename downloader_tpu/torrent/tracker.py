"""Tracker announce: HTTP (BEP 3) + compact peers (BEP 23) + UDP (BEP 15)
+ WebSocket (the webtorrent JSON protocol, ws/wss).

The reference's webtorrent client announces to http(s), udp AND
WebSocket trackers (/root/reference/lib/download.js:9,19,64-66 via
bittorrent-tracker); ``announce()`` dispatches on the URL scheme so the
client treats them uniformly.  wss swarm peers are WebRTC-only, so the
ws announce contributes registration + stats, not dialable addresses
(PARITY.md "WebSocket trackers").
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import socket
import struct
import urllib.parse
from typing import List, Optional

import aiohttp
import yarl

from .bencode import bdecode


@dataclasses.dataclass(frozen=True)
class Peer:
    host: str
    port: int


class TrackerError(RuntimeError):
    pass


def _permanent(message: str) -> TrackerError:
    """A tracker rejection that repeats deterministically (bad scheme,
    explicit failure reason, 4xx): tagged so the retry layer
    (platform/errors.py classify) fails fast instead of burning its
    backoff budget re-sending the same request."""
    err = TrackerError(message)
    err.fault_class = "permanent"
    return err


_EVENT_CODES = {"none": 0, "completed": 1, "started": 2, "stopped": 3}


async def announce(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: str = "started",
    session: aiohttp.ClientSession | None = None,
    udp_timeout: float = 5.0,
    udp_retries: int = 2,
) -> List[Peer]:
    """Announce to a tracker (http/https/udp) and return its peer list."""
    # fault-injection seam (platform/faults.py): tracker timeout storms
    # are a chaos-drill staple, and this hook makes them deterministic
    from ..platform import faults

    if faults.enabled():
        await faults.fire("tracker.announce", key=tracker_url)
    scheme = urllib.parse.urlsplit(tracker_url).scheme.lower()
    if scheme == "udp":
        return await announce_udp(
            tracker_url, info_hash, peer_id, port,
            uploaded=uploaded, downloaded=downloaded, left=left, event=event,
            timeout=udp_timeout, retries=udp_retries,
        )
    if scheme in ("http", "https"):
        return await announce_http(
            tracker_url, info_hash, peer_id, port,
            uploaded=uploaded, downloaded=downloaded, left=left, event=event,
            session=session,
        )
    if scheme in ("ws", "wss"):
        return await announce_ws(
            tracker_url, info_hash, peer_id, port,
            uploaded=uploaded, downloaded=downloaded, left=left, event=event,
            session=session,
        )
    raise _permanent(f"unsupported tracker scheme: {scheme!r}")


async def announce_with_retry(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    retries: int = 1,
    backoff: float = 0.2,
    **kwargs,
) -> List[Peer]:
    """:func:`announce` with bounded transient retries.

    A tracker blip (timeout, 5xx, connection reset) gets ``retries``
    further attempts with a doubling pause; failures the error taxonomy
    (platform/errors.py) calls permanent — bad scheme, a bencoded
    ``failure reason`` — re-raise immediately.  The torrent client runs
    this per tracker *concurrently*, so a retrying tracker never delays
    its healthy siblings.
    """
    from ..platform.errors import TRANSIENT, classify

    delay = backoff
    for attempt in range(retries + 1):
        try:
            return await announce(tracker_url, info_hash, peer_id, port,
                                  **kwargs)
        except Exception as err:
            if attempt >= retries or classify(err) != TRANSIENT:
                raise
            await asyncio.sleep(delay)
            delay *= 2
    raise AssertionError("unreachable: announce retry loop returns/raises")


async def announce_http(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: str = "started",
    session: aiohttp.ClientSession | None = None,
) -> List[Peer]:
    """Announce to an HTTP tracker and return its peer list."""
    query = urllib.parse.urlencode(
        {
            "info_hash": info_hash,
            "peer_id": peer_id,
            "port": port,
            "uploaded": uploaded,
            "downloaded": downloaded,
            "left": left,
            "compact": 1,
            "event": event,
        },
        quote_via=urllib.parse.quote,
    )
    sep = "&" if "?" in tracker_url else "?"
    url = f"{tracker_url}{sep}{query}"

    owned = session is None
    session = session or aiohttp.ClientSession(trust_env=True)
    try:
        # pre-encoded: the percent-encoded binary info_hash must reach the
        # wire untouched (yarl would otherwise re-quote it)
        async with session.get(yarl.URL(url, encoded=True)) as resp:
            if resp.status != 200:
                # 5xx/408/429 are outage-shaped (retryable); other 4xx
                # repeat deterministically
                if resp.status >= 500 or resp.status in (408, 429):
                    raise TrackerError(f"tracker answered {resp.status}")
                raise _permanent(f"tracker answered {resp.status}")
            body = await resp.read()
    finally:
        if owned:
            await session.close()

    data = bdecode(body)
    if b"failure reason" in data:
        # the tracker ANSWERED and rejected the announce (bad infohash,
        # banned client): retrying re-sends the same request
        raise _permanent(
            data[b"failure reason"].decode("utf-8", "replace")
        )

    peers = data.get(b"peers", b"")
    out: List[Peer] = []
    if isinstance(peers, bytes):  # compact: 6 bytes per peer
        out.extend(parse_compact_peers(peers))
    else:  # non-compact dict form
        for entry in peers:
            out.append(
                Peer(entry[b"ip"].decode(), entry[b"port"])
            )
    # BEP 7: IPv6 peers arrive in a parallel compact list
    peers6 = data.get(b"peers6", b"")
    if isinstance(peers6, bytes):
        out.extend(parse_compact_peers6(peers6))
    return out


# -- WebSocket trackers (the webtorrent wss announce protocol) ----------
#
# The reference's engine also announces to ws:// and wss:// trackers
# (/root/reference/lib/download.js:9,19 — webtorrent via
# bittorrent-tracker).  The wire protocol is JSON text frames over a
# WebSocket; 20-byte binary fields (info_hash, peer_id) travel as
# latin-1 strings ("binary" encoding in Node terms).  WSS trackers
# coordinate BROWSER peers: peer addresses are exchanged as WebRTC
# offers/answers signalled through the tracker, never as ip:port pairs,
# so a server-side announce yields swarm membership + stats but no
# dialable peers (ICE/DTLS/SCTP stays out of scope — PARITY.md
# "WebSocket trackers"; offer messages are counted and ignored).

_WS_TIMEOUT = 15.0


def _ws_binary(raw: bytes) -> str:
    return raw.decode("latin-1")


async def _ws_roundtrip(tracker_url: str, payload: dict, want_action: str,
                        session: aiohttp.ClientSession | None = None,
                        timeout: float = _WS_TIMEOUT,
                        ssl_ctx=None) -> dict:
    """One request/response over a fresh (or caller-shared) WebSocket:
    send ``payload``, return the first ``want_action`` reply for our
    info_hash, skipping interleaved offer/answer signalling traffic."""
    import json

    owned = session is None
    session = session or aiohttp.ClientSession(trust_env=True)
    try:
        async with asyncio.timeout(timeout):
            kwargs = {} if ssl_ctx is None else {"ssl": ssl_ctx}
            async with session.ws_connect(tracker_url, **kwargs) as ws:
                await ws.send_str(json.dumps(payload))
                async for msg in ws:
                    if msg.type != aiohttp.WSMsgType.TEXT:
                        continue
                    try:
                        reply = json.loads(msg.data)
                    except ValueError:
                        continue  # not ours; tolerate tracker chatter
                    if "failure reason" in reply:
                        raise _permanent(str(reply["failure reason"]))
                    if reply.get("action") != want_action:
                        continue
                    if "offer" in reply or "answer" in reply:
                        # WebRTC signalling fan-out ALSO uses action
                        # "announce" (bittorrent-tracker wire shape);
                        # we carry no ICE/DTLS stack — skip it
                        continue
                    ih = reply.get("info_hash")
                    if ih is not None and ih != payload.get("info_hash") \
                            and want_action != "scrape":
                        continue
                    return reply
        raise TrackerError("tracker closed the socket without answering")
    except aiohttp.ClientError as err:
        raise TrackerError(f"ws tracker failed: {err}") from err
    except TimeoutError as err:
        # a hung tracker is the failure mode operators actually hit;
        # str(TimeoutError()) is empty, so name it (review r5)
        raise TrackerError(
            f"ws tracker timed out after {timeout:.0f}s") from err
    finally:
        if owned:
            await session.close()


async def announce_ws(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: str = "started",
    session: aiohttp.ClientSession | None = None,
    timeout: float = _WS_TIMEOUT,
    ssl_ctx=None,
) -> List[Peer]:
    """Announce to a ws/wss tracker (webtorrent protocol).

    Registers us in the swarm and returns an (always empty) peer list —
    wss swarm peers are WebRTC-only; ``scrape_ws`` exposes the stats the
    announce reply carries."""
    payload = {
        "action": "announce",
        "info_hash": _ws_binary(info_hash),
        "peer_id": _ws_binary(peer_id),
        "numwant": 0,  # no offers attached -> nothing to hand out
        "uploaded": uploaded,
        "downloaded": downloaded,
        "left": left,
        "event": event,
        "offers": [],
    }
    await _ws_roundtrip(tracker_url, payload, "announce",
                        session=session, timeout=timeout, ssl_ctx=ssl_ctx)
    return []  # wss peers are WebRTC-only; stats live in scrape_ws


async def scrape_ws(tracker_url: str, info_hash: bytes,
                    session: aiohttp.ClientSession | None = None,
                    timeout: float = _WS_TIMEOUT,
                    ssl_ctx=None) -> "ScrapeStats":
    """Scrape swarm stats over a ws/wss tracker."""
    payload = {"action": "scrape", "info_hash": _ws_binary(info_hash)}
    reply = await _ws_roundtrip(tracker_url, payload, "scrape",
                                session=session, timeout=timeout,
                                ssl_ctx=ssl_ctx)
    files = reply.get("files", {})
    stats = files.get(_ws_binary(info_hash))
    if stats is None:
        raise TrackerError("tracker scrape reply missing our info_hash")
    return ScrapeStats(
        seeders=int(stats.get("complete", 0)),
        completed=int(stats.get("downloaded", 0)),
        leechers=int(stats.get("incomplete", 0)),
    )


@dataclasses.dataclass(frozen=True)
class ScrapeStats:
    """Per-infohash swarm statistics from a tracker scrape."""
    seeders: int
    completed: int
    leechers: int


async def scrape(tracker_url: str, info_hash: bytes) -> ScrapeStats:
    """Scrape swarm stats for one infohash.

    HTTP trackers use the /announce -> /scrape URL convention; UDP
    trackers use BEP 15 action 2.  Raises TrackerError when the tracker
    does not support scraping.
    """
    scheme = urllib.parse.urlsplit(tracker_url).scheme.lower()
    if scheme == "udp":
        return await scrape_udp(tracker_url, info_hash)
    if scheme in ("ws", "wss"):
        return await scrape_ws(tracker_url, info_hash)
    return await scrape_http(tracker_url, info_hash)


def _scrape_url(tracker_url: str) -> str:
    """BEP 48 convention: the last path segment 'announce' -> 'scrape'."""
    parts = urllib.parse.urlsplit(tracker_url)
    head, sep, last = parts.path.rpartition("/")
    if not last.startswith("announce"):
        raise TrackerError(f"tracker does not support scrape: {tracker_url}")
    return urllib.parse.urlunsplit(parts._replace(
        path=head + sep + "scrape" + last[len("announce"):]
    ))


async def scrape_http(tracker_url: str, info_hash: bytes) -> ScrapeStats:
    query = urllib.parse.urlencode(
        {"info_hash": info_hash}, quote_via=urllib.parse.quote
    )
    url = _scrape_url(tracker_url)
    sep = "&" if "?" in url else "?"
    async with aiohttp.ClientSession(trust_env=True) as session:
        async with session.get(
            yarl.URL(f"{url}{sep}{query}", encoded=True)
        ) as resp:
            if resp.status != 200:
                raise TrackerError(f"scrape answered {resp.status}")
            body = await resp.read()
    data = bdecode(body)
    if b"failure reason" in data:
        raise TrackerError(data[b"failure reason"].decode("utf-8", "replace"))
    files = data.get(b"files", {})
    entry = files.get(info_hash)
    if not isinstance(entry, dict):
        raise TrackerError("scrape response missing our infohash")
    return ScrapeStats(
        seeders=int(entry.get(b"complete", 0)),
        completed=int(entry.get(b"downloaded", 0)),
        leechers=int(entry.get(b"incomplete", 0)),
    )


# ---------------------------------------------------------------------------
# UDP tracker protocol (BEP 15)
# ---------------------------------------------------------------------------

_UDP_MAGIC = 0x41727101980
_ACTION_CONNECT = 0
_ACTION_ANNOUNCE = 1
_ACTION_SCRAPE = 2
_ACTION_ERROR = 3


async def _udp_roundtrip(loop, transport, proto, payload_fn,
                         timeout: float, retries: int) -> bytes:
    """One retried request/response exchange against a UDP tracker."""
    last: Exception = TrackerError("udp tracker unreachable")
    for _ in range(max(1, retries + 1)):
        tid = random.getrandbits(32)
        fut: asyncio.Future = loop.create_future()
        proto.waiters[tid] = fut
        transport.sendto(payload_fn(tid))
        try:
            async with asyncio.timeout(timeout):
                return await fut
        except TimeoutError:
            proto.waiters.pop(tid, None)
            last = TrackerError(f"udp tracker timed out after {timeout}s")
        except TrackerError as err:
            last = err
    raise last


async def _udp_connect(loop, transport, proto, timeout, retries) -> int:
    """BEP 15 connect round trip -> connection id."""
    resp = await _udp_roundtrip(
        loop, transport, proto,
        lambda tid: struct.pack(">QII", _UDP_MAGIC, _ACTION_CONNECT, tid),
        timeout, retries,
    )
    (action,) = struct.unpack_from(">I", resp, 0)
    if action == _ACTION_ERROR:
        raise TrackerError(resp[8:].decode("utf-8", "replace"))
    if action != _ACTION_CONNECT or len(resp) < 16:
        raise TrackerError("malformed udp connect response")
    (connection_id,) = struct.unpack_from(">Q", resp, 8)
    return connection_id


async def scrape_udp(tracker_url: str, info_hash: bytes,
                     timeout: float = 5.0, retries: int = 2) -> ScrapeStats:
    """BEP 15 action-2 scrape for one infohash."""
    parts = urllib.parse.urlsplit(tracker_url)
    if parts.hostname is None or parts.port is None:
        raise _permanent(f"udp tracker needs host:port: {tracker_url}")
    loop = asyncio.get_running_loop()
    transport, proto = await loop.create_datagram_endpoint(
        _UdpTrackerProtocol, remote_addr=(parts.hostname, parts.port)
    )
    try:
        connection_id = await _udp_connect(
            loop, transport, proto, timeout, retries
        )
        resp = await _udp_roundtrip(
            loop, transport, proto,
            lambda tid: struct.pack(
                ">QII20s", connection_id, _ACTION_SCRAPE, tid, info_hash
            ),
            timeout, retries,
        )
        (action,) = struct.unpack_from(">I", resp, 0)
        if action == _ACTION_ERROR:
            raise TrackerError(resp[8:].decode("utf-8", "replace"))
        if action != _ACTION_SCRAPE or len(resp) < 20:
            raise TrackerError("malformed udp scrape response")
        seeders, completed, leechers = struct.unpack_from(">III", resp, 8)
        return ScrapeStats(seeders=seeders, completed=completed,
                           leechers=leechers)
    finally:
        transport.close()


class _UdpTrackerProtocol(asyncio.DatagramProtocol):
    """Collects datagrams into per-transaction futures."""

    def __init__(self) -> None:
        self.waiters: dict[int, asyncio.Future] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        if len(data) < 8:
            return
        (tid,) = struct.unpack_from(">I", data, 4)
        fut = self.waiters.pop(tid, None)
        if fut is not None and not fut.done():
            fut.set_result(data)

    def error_received(self, exc) -> None:
        for fut in self.waiters.values():
            if not fut.done():
                fut.set_exception(TrackerError(f"udp error: {exc}"))
        self.waiters.clear()


def parse_compact_peers(blob: bytes) -> List[Peer]:
    """BEP 23 compact peers: 4-byte IP + 2-byte port each, concatenated.

    The single parser for every compact-peer surface (HTTP tracker, UDP
    tracker, DHT ``values``).  Port-0 entries are dropped — unconnectable.
    """
    out = []
    for i in range(0, len(blob) - len(blob) % 6, 6):
        host = socket.inet_ntoa(blob[i:i + 4])
        (peer_port,) = struct.unpack(">H", blob[i + 4:i + 6])
        if peer_port:
            out.append(Peer(host, peer_port))
    return out


def parse_compact_peers6(blob: bytes) -> List[Peer]:
    """BEP 7 compact IPv6 peers: 16-byte address + 2-byte port each."""
    out = []
    for i in range(0, len(blob) - len(blob) % 18, 18):
        host = socket.inet_ntop(socket.AF_INET6, blob[i:i + 16])
        (peer_port,) = struct.unpack(">H", blob[i + 16:i + 18])
        if peer_port:
            out.append(Peer(host, peer_port))
    return out


_parse_compact_peers = parse_compact_peers  # backwards-compatible alias


async def announce_udp(
    tracker_url: str,
    info_hash: bytes,
    peer_id: bytes,
    port: int,
    uploaded: int = 0,
    downloaded: int = 0,
    left: int = 0,
    event: str = "started",
    num_want: int = -1,
    timeout: float = 5.0,
    retries: int = 2,
) -> List[Peer]:
    """Announce over the BEP 15 UDP tracker protocol.

    Two round trips: ``connect`` (magic -> connection_id, guards against
    spoofed sources) then ``announce``.  Each request is retried
    ``retries`` times with the given per-attempt timeout; BEP 15's
    15*2^n schedule is collapsed to a flat timeout because the stage
    above already enforces the reference's 240 s stall budget.
    """
    parts = urllib.parse.urlsplit(tracker_url)
    if parts.hostname is None or parts.port is None:
        raise _permanent(f"udp tracker needs host:port: {tracker_url}")
    addr = (parts.hostname, parts.port)

    loop = asyncio.get_running_loop()
    transport, proto = await loop.create_datagram_endpoint(
        _UdpTrackerProtocol, remote_addr=addr
    )
    try:
        def _roundtrip(payload_fn):
            return _udp_roundtrip(
                loop, transport, proto, payload_fn, timeout, retries
            )

        connection_id = await _udp_connect(
            loop, transport, proto, timeout, retries
        )

        # announce round trip
        resp = await _roundtrip(
            lambda tid: struct.pack(
                ">QII20s20sQQQIIIiH",
                connection_id, _ACTION_ANNOUNCE, tid,
                info_hash, peer_id,
                downloaded, left, uploaded,
                _EVENT_CODES.get(event, 0),
                0,                      # IP: let the tracker use the source
                random.getrandbits(32),  # key
                num_want, port,
            )
        )
        (action,) = struct.unpack_from(">I", resp, 0)
        if action == _ACTION_ERROR:
            raise TrackerError(resp[8:].decode("utf-8", "replace"))
        if action != _ACTION_ANNOUNCE or len(resp) < 20:
            raise TrackerError("malformed udp announce response")
        # BEP 15: a tracker reached over IPv6 answers with 18-byte
        # (address, port) entries; slicing those on 6-byte boundaries
        # would fabricate garbage IPv4 peers
        sockname = transport.get_extra_info("sockname")
        if sockname is not None and len(sockname) == 4:  # AF_INET6 tuple
            return parse_compact_peers6(resp[20:])
        return parse_compact_peers(resp[20:])
    finally:
        transport.close()
