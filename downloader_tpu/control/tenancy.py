"""Tenant identity, weights, and per-tenant quotas.

The reference service has no notion of *who* submitted a job: one noisy
library import starves every other submitter behind it in the flat
``v1.download`` queue (PAPER.md §1).  Priority classes (PR 2) reorder
starts but a single tenant can still monopolize every run slot and every
byte of ingress/egress.  This module gives the control plane a tenant
axis:

- ``Download.tenant`` (proto field 4) names the submitter.  Absent or
  empty means the ``"default"`` tenant; a name with no ``tenants.<name>``
  config entry *degrades to* ``"default"`` too — the exact posture of the
  unknown-priority -> NORMAL degrade in :func:`..control.scheduler.
  priority_name` — so tenancy is opt-in per name, label cardinality on
  /metrics stays bounded by config, and a deployment with no ``tenants``
  section behaves byte-for-byte like the pre-tenancy service.
- :class:`TenantTable` resolves wire names and holds each configured
  tenant's scheduling weight (``tenants.<name>.weight``, consumed by the
  weighted-fair pick in :class:`~.scheduler.PriorityScheduler`),
  concurrency cap (``tenants.<name>.max_concurrent``), and ingress/
  egress byte quotas (``tenants.<name>.download_rate_limit`` /
  ``upload_rate_limit``, bytes/s) built on the same
  :class:`~..utils.ratelimit.TokenBucket` machinery as the per-service
  caps.  Tenant buckets stack *under* the service-wide limiter
  (:class:`~..utils.ratelimit.ChainedLimiter`): a transfer pays both.

Config shape::

    tenants:
      vip:   {weight: 4, max_concurrent: 4}
      batch: {weight: 1, max_concurrent: 1,
              download_rate_limit: 8000000, upload_rate_limit: 8000000}

Weights apportion run-slot grants *within* a priority class (priority
still dominates; aging still starvation-proofs both axes).  ``default``
may be configured like any other tenant; unconfigured it has weight 1
and no caps.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..platform.config import cfg_get
from ..utils.ratelimit import TokenBucket, chain_limiters

DEFAULT_TENANT = "default"
DEFAULT_WEIGHT = 1.0

# per-tenant quota/shape knobs a tenants.<name> section may carry
_RATE_KEYS = ("download_rate_limit", "upload_rate_limit")


class TenantTable:
    """Configured tenants: weights, concurrency caps, byte quotas.

    Built once per orchestrator and shared (via ``stage_resources``) with
    the stages, so per-tenant token buckets are per-SERVICE singletons —
    the same memoization discipline as :func:`~..utils.ratelimit.
    shared_bucket` (a per-job bucket would multiply the quota by the
    concurrency).
    """

    def __init__(self, config=None, logger=None):
        self.logger = logger
        self._specs: Dict[str, dict] = {}
        self._buckets: Dict[str, Optional[TokenBucket]] = {}
        raw = cfg_get(config, "tenants", None)
        if raw:
            for name in raw:
                spec = raw.get(name) or {}
                self._specs[str(name)] = self._parse(str(name), spec)
        self._specs.setdefault(DEFAULT_TENANT, self._parse(DEFAULT_TENANT, {}))

    @staticmethod
    def _parse(name: str, spec) -> dict:
        def _get(key, default=None):
            getter = getattr(spec, "get", None)
            return getter(key, default) if getter is not None else default

        weight = _get("weight", DEFAULT_WEIGHT)
        try:
            weight = float(weight)
        except (TypeError, ValueError):
            raise ValueError(
                f"tenants.{name}.weight={weight!r} is not a number"
            ) from None
        if weight <= 0:
            raise ValueError(f"tenants.{name}.weight must be > 0, got {weight}")
        cap = _get("max_concurrent")
        if cap is not None:
            cap = int(cap)
            if cap < 1:
                raise ValueError(
                    f"tenants.{name}.max_concurrent must be >= 1, got {cap}"
                )
        out = {"weight": weight, "max_concurrent": cap}
        for key in _RATE_KEYS:
            rate = _get(key)
            if rate is not None:
                rate = float(rate)
                if rate < 0:
                    raise ValueError(
                        f"tenants.{name}.{key} must be >= 0, got {rate}"
                    )
            out[key] = rate or None  # 0/absent = unlimited
        return out

    # -- identity -------------------------------------------------------
    @property
    def configured(self) -> bool:
        """True when the deployment opted into tenancy (any ``tenants``
        entry beyond the implicit default)."""
        return len(self._specs) > 1 or any(
            v is not None
            for k, v in self._specs[DEFAULT_TENANT].items()
            if k != "weight"
        ) or self._specs[DEFAULT_TENANT]["weight"] != DEFAULT_WEIGHT

    def names(self) -> list:
        """Every tenant the table can attribute work to (bounded by
        config — the /metrics label set)."""
        return sorted(self._specs)

    def resolve(self, wire_name: Optional[str]) -> str:
        """Wire ``Download.tenant`` -> the tenant this worker runs the
        job as.  Absent/empty -> ``default``; a name without a config
        entry degrades to ``default`` (unknown-priority->NORMAL posture)
        so an un-onboarded submitter gets baseline service instead of an
        error, and metric label cardinality stays config-bounded."""
        name = (wire_name or "").strip()
        if not name or name == DEFAULT_TENANT:
            return DEFAULT_TENANT
        if name in self._specs:
            return name
        if self.logger is not None:
            self.logger.debug("unknown tenant, degrading to default",
                              tenant=name)
        return DEFAULT_TENANT

    # -- scheduling inputs ---------------------------------------------
    def weight(self, tenant: str) -> float:
        spec = self._specs.get(tenant)
        return spec["weight"] if spec else DEFAULT_WEIGHT

    def max_concurrent(self, tenant: str) -> Optional[int]:
        spec = self._specs.get(tenant)
        return spec["max_concurrent"] if spec else None

    # -- byte quotas ----------------------------------------------------
    def _bucket(self, tenant: str, key: str) -> Optional[TokenBucket]:
        cache_key = f"{tenant}:{key}"
        if cache_key not in self._buckets:
            spec = self._specs.get(tenant)
            rate = spec.get(key) if spec else None
            self._buckets[cache_key] = TokenBucket(rate) if rate else None
        return self._buckets[cache_key]

    def ingress_limiter(self, tenant: str) -> Optional[TokenBucket]:
        return self._bucket(tenant, "download_rate_limit")

    def egress_limiter(self, tenant: str) -> Optional[TokenBucket]:
        return self._bucket(tenant, "upload_rate_limit")

    # -- introspection --------------------------------------------------
    def describe(self) -> Dict[str, dict]:
        """Static per-tenant config, JSON-shaped for ``GET /v1/tenants``."""
        out = {}
        for name, spec in self._specs.items():
            out[name] = {
                "weight": spec["weight"],
                "maxConcurrent": spec["max_concurrent"],
                "downloadRateLimit": spec["download_rate_limit"],
                "uploadRateLimit": spec["upload_rate_limit"],
            }
        return out


def stage_limiter(ctx, direction: str, base) -> Any:
    """Stack the job's per-tenant byte quota under the service limiter.

    ``ctx`` is the stage's :class:`~..stages.base.StageContext`;
    ``direction`` is ``"ingress"`` or ``"egress"``; ``base`` is the
    service-wide bucket (may be None).  Outside the orchestrator (no
    tenant table in resources, or no registry record) this returns
    ``base`` unchanged — standalone stage use pays nothing.
    """
    table = ctx.resources.get("tenant_table") if ctx.resources else None
    tenant = getattr(ctx.record, "tenant", None)
    if table is None or not tenant:
        return base
    if direction == "ingress":
        quota = table.ingress_limiter(tenant)
    else:
        quota = table.egress_limiter(tenant)
    return chain_limiters(base, quota)
