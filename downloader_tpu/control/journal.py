"""Crash-safe job journal: the durability layer under the registry.

``control/registry.py`` is the control plane's source of truth while the
process lives — and nothing more: a SIGKILL/OOM loses every job record,
retry/poison counter, and flight-recorder timeline, leaves orphan
workdirs on disk, and makes redeliveries start cold with no memory that
a prior attempt already failed twice.  The broker's redelivery (the
reference's whole crash story, PAPER.md §1) restores the *message*, not
the *history*.

This module closes that gap with an append-only JSONL journal under the
work dir (``journal.dir``, default ``<download_path>/.journal/``):

- the registry appends one line per lifecycle event (``open`` at
  receipt, ``state`` per transition) and the orchestrator appends the
  retry/poison counter moves (``retry`` / ``retry_clear``) and the
  delivery settle mode (``settle`` ack/nack — the bit that decides
  whether a terminal job's redelivery is still coming);
- appends are a buffered ``write()`` (microseconds — the bench guards
  ``journal_overhead_ms`` < 1 ms/job); durability comes from a
  **batched fsync** every ``journal.fsync_interval`` seconds off-loop,
  so a kill loses at most one interval of tail entries — bounded,
  documented, and safe: the broker redelivers the message regardless,
  the journal only makes the redelivery *warm*;
- :func:`replay` rebuilds the last-known state per job id, tolerating a
  torn final line (the crash can land mid-``write``);
- :meth:`JobJournal.compact` rewrites the file as one ``snapshot`` line
  plus nothing else — run at every boot after replay and whenever the
  file grows past ``journal.max_bytes``, so the journal is bounded by
  live-job count, not process age.

What replay yields (:class:`RecoveredJob`): enough to re-register the
job as a PARKED ``recovered: awaiting redelivery`` placeholder, restore
its retry schedule, and decide the workdir sweep — a job whose last
settle was ``nack`` (or that never settled) has a redelivery in flight
and keeps its resumable ``.partial``/piece state; an ``ack``-settled
terminal job is gone for good and its workdir is an orphan.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ..platform.config import cfg_get
from ..utils import utcnow_iso as _utcnow_iso

DEFAULT_FSYNC_INTERVAL = 0.05
DEFAULT_MAX_BYTES = 4 << 20
JOURNAL_DIRNAME = ".journal"
JOURNAL_FILENAME = "journal.jsonl"

# journal ops (the "op" key of each line)
OP_OPEN = "open"          # record registered at delivery receipt
OP_STATE = "state"        # lifecycle transition
OP_SETTLE = "settle"      # delivery settled (mode: ack | nack)
OP_RETRY = "retry"        # poison counter advanced (failures: n)
OP_RETRY_CLEAR = "retry_clear"
OP_SNAPSHOT = "snapshot"  # compaction: full live state in one line

_TERMINAL = frozenset({"DONE", "FAILED", "CANCELLED", "DROPPED_POISON",
                       "EXPIRED"})


@dataclass
class RecoveredJob:
    """One job's last-known state, rebuilt from the journal at boot."""

    job_id: str
    file_id: str = ""
    priority: str = "NORMAL"
    tenant: str = "default"
    ttl_seconds: float = 0.0
    state: str = "RECEIVED"
    stage: Optional[str] = None
    reason: Optional[str] = None
    failures: int = 0
    settle: Optional[str] = None  # last settle mode: "ack" | "nack"
    updated_at: str = ""
    # when this job FIRST became an unadopted boot placeholder; "" for a
    # job with real delivery activity.  Survives re-registration across
    # boots (the placeholder's open line carries it forward) and clears
    # on any non-PARKED transition (adoption, running), so
    # "now - recovered_at" measures how long the broker has owed a
    # redelivery that never came — the placeholder-retirement clock
    recovered_at: str = ""

    @property
    def terminal(self) -> bool:
        return self.state in _TERMINAL

    @property
    def redelivery_expected(self) -> bool:
        """True when the broker still owes this job a delivery: the job
        never settled (crash mid-run — the unacked delivery requeues) or
        its last settle was a nack (redelivery explicitly requested)."""
        return self.settle != "ack"

    def to_snapshot(self) -> dict:
        return {
            "id": self.job_id, "fileId": self.file_id,
            "priority": self.priority, "tenant": self.tenant,
            "ttl": self.ttl_seconds, "state": self.state,
            "stage": self.stage, "reason": self.reason,
            "failures": self.failures, "settle": self.settle,
            "at": self.updated_at, "recoveredAt": self.recovered_at,
        }

    @classmethod
    def from_snapshot(cls, raw: dict) -> "RecoveredJob":
        return cls(
            job_id=str(raw.get("id", "")),
            file_id=str(raw.get("fileId", "")),
            priority=str(raw.get("priority", "NORMAL")),
            tenant=str(raw.get("tenant", "default")),
            ttl_seconds=float(raw.get("ttl", 0.0) or 0.0),
            state=str(raw.get("state", "RECEIVED")),
            stage=raw.get("stage"),
            reason=raw.get("reason"),
            failures=int(raw.get("failures", 0) or 0),
            settle=raw.get("settle"),
            updated_at=str(raw.get("at", "")),
            recovered_at=str(raw.get("recoveredAt", "") or ""),
        )


@dataclass
class RecoveredState:
    """Everything :func:`replay` learned from the journal."""

    jobs: Dict[str, RecoveredJob] = field(default_factory=dict)
    torn_lines: int = 0
    entries: int = 0

    def live(self) -> Dict[str, RecoveredJob]:
        """Jobs whose redelivery is still coming: the recovery set."""
        return {job_id: job for job_id, job in self.jobs.items()
                if job.redelivery_expected}


def _apply_line(jobs: Dict[str, RecoveredJob], entry: dict) -> None:
    op = entry.get("op")
    if op == OP_SNAPSHOT:
        jobs.clear()
        for raw in entry.get("jobs", []):
            job = RecoveredJob.from_snapshot(raw)
            if job.job_id:
                jobs[job.job_id] = job
        return
    job_id = entry.get("id")
    if not job_id:
        return
    if op == OP_OPEN:
        # a fresh delivery resets per-attempt state but NOT the poison
        # counter: the counter spans redeliveries by design
        prior = jobs.get(job_id)
        job = RecoveredJob(
            job_id=job_id,
            file_id=str(entry.get("fileId", "")),
            priority=str(entry.get("priority", "NORMAL")),
            tenant=str(entry.get("tenant", "default")),
            ttl_seconds=float(entry.get("ttl", 0.0) or 0.0),
            failures=prior.failures if prior is not None else 0,
            updated_at=str(entry.get("t", "")),
            recovered_at=str(entry.get("recoveredAt", "") or ""),
        )
        jobs[job_id] = job
        return
    job = jobs.get(job_id)
    if job is None:
        # state for a job whose open predates the last compaction window
        # (shouldn't happen — compaction snapshots live jobs — but a
        # half-written history must degrade, not crash the boot)
        job = jobs[job_id] = RecoveredJob(job_id=job_id)
    if op == OP_STATE:
        job.state = str(entry.get("state", job.state))
        job.stage = entry.get("stage", job.stage)
        job.reason = entry.get("reason")
        job.updated_at = str(entry.get("t", job.updated_at))
        if job.state != "PARKED":
            # real progress (adoption, running, settling): the job is no
            # longer an unadopted placeholder — restart the retirement
            # clock from whatever happens next
            job.recovered_at = ""
    elif op == OP_SETTLE:
        job.settle = entry.get("mode")
    elif op == OP_RETRY:
        job.failures = int(entry.get("failures", job.failures + 1))
    elif op == OP_RETRY_CLEAR:
        job.failures = 0


def replay(path: str, limit_bytes: Optional[int] = None) -> RecoveredState:
    """Rebuild per-job state from a journal file (missing file = empty).

    A torn final line — the crash landed mid-``write`` — is counted and
    skipped, never fatal: everything before it already replayed.

    ``limit_bytes`` replays only the first N bytes — the compaction's
    snapshot basis: a compaction racing live appends must snapshot
    exactly the prefix it captured, and nothing that landed after (the
    post-``base`` tail is preserved verbatim instead; replaying those
    lines here too would apply them twice).  ``base`` is always
    line-aligned: appends are whole ``write()`` lines and the offset is
    captured under the append lock after a flush.
    """
    state = RecoveredState()
    try:
        fh = open(path, "rb")
    except FileNotFoundError:
        return state
    consumed = 0
    with fh:
        for raw in fh:
            consumed += len(raw)
            if limit_bytes is not None and consumed > limit_bytes:
                break
            raw = raw.strip()
            if not raw:
                continue
            try:
                entry = json.loads(raw)
            except ValueError:
                state.torn_lines += 1
                continue
            if not isinstance(entry, dict):
                state.torn_lines += 1
                continue
            state.entries += 1
            _apply_line(state.jobs, entry)
    return state


class JobJournal:
    """Append-only journal with batched fsync.

    ``append`` is called from the event loop (registry transitions are
    loop-side) and must stay microseconds: it writes one JSON line to
    the buffered file handle and arms the flush timer.  The actual
    ``flush + fsync`` runs on a daemon thread at most once per
    ``fsync_interval``, so per-job durability cost amortizes across
    every job that settled in the window.  ``close`` flushes
    synchronously — a clean shutdown loses nothing.
    """

    def __init__(self, path: str, *, fsync_interval: float = DEFAULT_FSYNC_INTERVAL,
                 max_bytes: int = DEFAULT_MAX_BYTES, logger=None):
        self.path = path
        self.fsync_interval = max(float(fsync_interval), 0.0)
        self.max_bytes = max(int(max_bytes), 1 << 16)
        self.logger = logger
        self.appended = 0
        # snapshot-rewrites performed over this handle's lifetime (the
        # compaction-thrash regression guard reads it)
        self.compactions = 0
        self._lock = threading.Lock()
        self._flusher: Optional[threading.Timer] = None
        self._compacting = False
        # raised past ``max_bytes`` when a compaction could NOT shrink
        # the file under the bound (the live set alone exceeds it):
        # without this floor every terminal settle would re-trigger a
        # full replay+rewrite that cannot help — O(jobs x file) disk
        # churn at exactly the moment the worker is busiest.  Reset to 0
        # the next time a compaction lands under ``max_bytes``.
        self._compact_floor = 0
        self._closed = False
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._fh = open(path, "a", encoding="utf-8")
        # line census for the ``journal_lines`` growth gauge: counted
        # once at open (the file is compaction-bounded), then maintained
        # incrementally by append/compact
        self.lines = self._count_lines()

    def _count_lines(self) -> int:
        try:
            with open(self.path, "rb") as fh:
                return sum(chunk.count(b"\n")
                           for chunk in iter(lambda: fh.read(1 << 16), b""))
        except OSError:
            return 0

    @classmethod
    def from_config(cls, config, download_root: str,
                    logger=None) -> "Optional[JobJournal]":
        """``journal.enabled`` (default True) under
        ``journal.dir`` (default ``<download_root>/.journal``)."""
        if not cfg_get(config, "journal.enabled", True):
            return None
        configured = cfg_get(config, "journal.dir", None)
        directory = configured or os.path.join(download_root, JOURNAL_DIRNAME)
        return cls(
            os.path.join(directory, JOURNAL_FILENAME),
            fsync_interval=float(cfg_get(
                config, "journal.fsync_interval", DEFAULT_FSYNC_INTERVAL
            )),
            max_bytes=int(cfg_get(
                config, "journal.max_bytes", DEFAULT_MAX_BYTES
            )),
            logger=logger,
        )

    # -- appending ------------------------------------------------------
    def append(self, op: str, job_id: str, **fields: Any) -> None:
        """Write one journal line (buffered; fsync is batched)."""
        if self._closed:
            return
        entry = {"op": op, "id": job_id, "t": _utcnow_iso(), **fields}
        line = json.dumps(entry, separators=(",", ":")) + "\n"
        with self._lock:
            if self._closed:
                return
            self._fh.write(line)
            self.appended += 1
            self.lines += 1
            self._arm_flusher()

    def _arm_flusher(self) -> None:
        # under self._lock.  interval 0 = flush inline (tests/benches
        # that want strict durability per append)
        if self.fsync_interval <= 0:
            self._flush_locked()
            return
        if self._flusher is None:
            timer = threading.Timer(self.fsync_interval, self._flush_timer)
            timer.daemon = True
            self._flusher = timer
            timer.start()

    def _flush_timer(self) -> None:
        with self._lock:
            self._flusher = None
            if not self._closed:
                self._flush_locked()

    def _flush_locked(self) -> None:
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
        except (OSError, ValueError) as err:
            # journal durability is best-effort by contract (the broker
            # redelivers regardless); a full/yanked volume must not take
            # the pipeline down with it
            if self.logger is not None:
                self.logger.warn("journal flush failed", error=str(err))

    def flush(self) -> None:
        """Synchronous flush + fsync (shutdown, tests)."""
        with self._lock:
            self._flush_locked()

    @property
    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    # -- replay + compaction -------------------------------------------
    def replay(self) -> RecoveredState:
        """Replay the on-disk history (flushing our own tail first, so a
        same-process replay — tests, the restart bench — sees every
        append)."""
        self.flush()
        return replay(self.path)

    def compact(self, state: Optional[RecoveredState] = None) -> None:
        """Rewrite the journal as one snapshot line of still-live jobs.

        Ack-settled terminal jobs are dropped — their story is over and
        their workdirs are swept by reconciliation; everything else
        (live, or terminal-but-nacked = redelivery coming) survives with
        its retry counter.  Write-temp + rename keeps a crash mid-compact
        from losing the old file.

        Safe to run off-loop while appends continue: the snapshot basis
        is exactly the first ``base`` bytes captured under the lock, and
        lines written after that offset are preserved VERBATIM after the
        snapshot line (replay applies the snapshot first, then the tail
        ops — the same last-write-wins order they had).  A concurrent
        append is therefore never dropped AND never applied twice: the
        prefix lands only in the snapshot, the tail only after it (the
        soak flushed out the old behavior, which replayed the whole file
        for the snapshot and so duplicated any line that landed between
        the offset capture and the replay).  ``state`` is an optional
        pre-computed replay (tests); None replays the captured prefix.
        """
        with self._lock:
            if self._closed:
                return
            self._fh.flush()
            try:
                base = os.path.getsize(self.path)
            except OSError:
                base = 0
        if state is None:
            state = replay(self.path, limit_bytes=base)
        live = state.live()
        snapshot = {
            "op": OP_SNAPSHOT, "id": "", "t": _utcnow_iso(),
            "jobs": [job.to_snapshot() for job in live.values()],
        }
        line = json.dumps(snapshot, separators=(",", ":")) + "\n"
        tmp = self.path + ".compact"
        with self._lock:
            if self._closed:
                return
            self._fh.flush()
            try:
                with open(self.path, "rb") as src:
                    src.seek(base)
                    tail = src.read()
            except OSError:
                tail = b""
            with open(tmp, "wb") as out:
                out.write(line.encode("utf-8") + tail)
                out.flush()
                os.fsync(out.fileno())
            self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "a", encoding="utf-8")
            self.lines = 1 + tail.count(b"\n")
            self.compactions += 1
            try:
                post = os.path.getsize(self.path)
            except OSError:
                post = 0
            # a compaction that could not get under max_bytes (live-set
            # dominated) must not be re-triggered by the very next
            # settle: require real growth past the post-compact size
            # before trying again
            self._compact_floor = post * 2 if post > self.max_bytes else 0

    @property
    def _compact_threshold(self) -> int:
        return max(self.max_bytes, self._compact_floor)

    def maybe_compact(self) -> bool:
        """Compact when the file outgrew ``max_bytes`` (synchronous —
        boot/tests; the registry's settle path uses the async variant)."""
        if self.size_bytes <= self._compact_threshold:
            return False
        self.compact()
        return True

    def maybe_compact_async(self) -> bool:
        """Size check inline (one stat), rewrite on a daemon thread.

        The loop-side terminal settle that trips the size bound must not
        pay the replay + double-fsync itself — on a contended disk that
        is tens of ms of event-loop stall, the exact lag the overload
        controller is armed on.  Single-flight: a compaction already
        running absorbs the growth that triggered this call.
        """
        if self.size_bytes <= self._compact_threshold:
            return False
        with self._lock:
            if self._closed or self._compacting:
                return False
            self._compacting = True
        thread = threading.Thread(target=self._compact_bg, daemon=True,
                                  name="journal-compact")
        thread.start()
        return True

    def _compact_bg(self) -> None:
        try:
            self.compact()
        except Exception as err:
            # same contract as flush trouble: the journal is best-effort,
            # a failed compaction must never take the pipeline down
            if self.logger is not None:
                self.logger.warn("journal compaction failed",
                                 error=str(err))
        finally:
            self._compacting = False

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._flusher is not None:
                self._flusher.cancel()
                self._flusher = None
            self._flush_locked()
            self._closed = True
            self._fh.close()


def recovery_counters(state: RecoveredState) -> Dict[str, int]:
    """``{job_id: failures}`` for the jobs whose retry schedule must
    survive the restart (failures > 0 and a redelivery still coming)."""
    return {job_id: job.failures
            for job_id, job in state.live().items() if job.failures > 0}
