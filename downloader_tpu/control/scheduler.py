"""Priority-class start-order scheduling for admitted jobs.

The reference starts jobs in raw queue order (/root/reference/lib/main.js:172
consumes FIFO); under mixed traffic a backlog of bulk library imports
delays a user-facing request by the whole backlog.  Here the orchestrator
holds admitted-but-not-started jobs in a small priority queue: when one of
the ``max_concurrent_jobs`` slots frees up, the highest class waiting
starts first (HIGH before NORMAL before BULK).  There is **no mid-job
preemption** — a running bulk job finishes; priority only reorders starts.

Starvation-proofing: a waiter's effective rank improves by one class per
``aging_seconds`` waited, so a BULK job enqueued long ago eventually beats
a just-arrived HIGH job.  Ties break by arrival order (FIFO within class).

For the queue to have anything to reorder, the broker must deliver more
jobs than can run: ``instance.scheduler_backlog`` (env
``SCHEDULER_BACKLOG``) adds that many deliveries to the consumer
prefetch.  The default of 0 keeps exact pre-control-plane behavior
(prefetch == run slots, scheduler passes straight through).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import List

from .. import schemas

# start-order rank per priority class; lower starts first
PRIORITY_RANK = {"HIGH": 0, "NORMAL": 1, "BULK": 2}
DEFAULT_AGING_SECONDS = 60.0


def priority_name(value: int) -> str:
    """Wire enum value -> class name; unknown values (a newer producer)
    degrade to NORMAL instead of failing the delivery."""
    try:
        return schemas.JobPriority.Name(value)
    except ValueError:
        return "NORMAL"


def priority_rank(name: str) -> int:
    return PRIORITY_RANK.get(name, PRIORITY_RANK["NORMAL"])


class _Waiter:
    __slots__ = ("rank", "enqueued", "seq", "fut")

    def __init__(self, rank: int, seq: int):
        self.rank = rank
        self.enqueued = time.monotonic()
        self.seq = seq
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()

    def effective(self, now: float, aging: float):
        """Sort key: class rank improved by one per aging interval."""
        bump = int((now - self.enqueued) / aging) if aging > 0 else 0
        return (self.rank - bump, self.seq)


class PriorityScheduler:
    """Counting gate over ``slots`` with priority-ordered grants."""

    def __init__(self, slots: int,
                 aging_seconds: float = DEFAULT_AGING_SECONDS):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = slots
        self.aging_seconds = float(aging_seconds)
        self._free = slots
        self._waiters: List[_Waiter] = []
        self._seq = itertools.count()

    # -- introspection --------------------------------------------------
    @property
    def waiting(self) -> int:
        return len(self._waiters)

    @property
    def in_use(self) -> int:
        return self.slots - self._free

    # -- gate -----------------------------------------------------------
    async def acquire(self, rank: int = 1) -> None:
        """Take a run slot, queueing by ``rank`` when none is free."""
        if self._free > 0 and not self._waiters:
            self._free -= 1
            return
        waiter = _Waiter(rank, next(self._seq))
        self._waiters.append(waiter)
        try:
            await waiter.fut
        except asyncio.CancelledError:
            try:
                self._waiters.remove(waiter)
            except ValueError:
                if waiter.fut.done() and not waiter.fut.cancelled():
                    # granted in the same tick we were cancelled: return
                    # the slot so it isn't leaked
                    self.release()
            raise

    def release(self) -> None:
        """Give a slot back and grant it to the best waiter, if any."""
        self._free += 1
        self._grant()

    def _grant(self) -> None:
        # aging makes the effective key time-dependent, so order is
        # decided at grant time with a plain min() scan — the waiter set
        # is bounded by scheduler_backlog (tens at most), where O(n)
        # beats maintaining any time-invalidated ordered structure
        now = time.monotonic()
        while self._free > 0 and self._waiters:
            best = min(
                self._waiters,
                key=lambda w: w.effective(now, self.aging_seconds),
            )
            self._waiters.remove(best)
            if best.fut.done():
                # cancelled while queued (guard's task.cancel lands on
                # the future before acquire's except removes the waiter):
                # drop it WITHOUT consuming a slot — set_result on a
                # cancelled future would raise InvalidStateError out of
                # the releasing job's finally and leak the slot
                continue
            self._free -= 1
            best.fut.set_result(None)


class RunSlot:
    """One job's handle on its priority-scheduler run slot.

    Wraps the acquire/release pair the orchestrator used to manage with
    closure flags, and adds :meth:`reacquire` so a stage that parks for
    a long, idle wait — the fleet plane's lease waiters — can give the
    slot back to runnable jobs and queue for it again (same priority
    rank, normal aging) before resuming.  ``release`` is idempotent:
    the park path releases before its sleep and the processor's finally
    must not double-release.
    """

    __slots__ = ("_scheduler", "_rank", "granted", "released")

    def __init__(self, scheduler: PriorityScheduler, rank: int):
        self._scheduler = scheduler
        self._rank = rank
        self.granted = False
        self.released = False

    async def acquire(self) -> None:
        await self._scheduler.acquire(self._rank)
        self.granted = True
        self.released = False

    def release(self) -> None:
        if self.granted and not self.released:
            self.released = True
            self._scheduler.release()

    async def reacquire(self) -> None:
        """Take a slot again after :meth:`release` (no-op when held)."""
        if self.granted and self.released:
            await self._scheduler.acquire(self._rank)
            self.released = False


def backlog_from_config(config) -> int:
    """``instance.scheduler_backlog`` / env SCHEDULER_BACKLOG (extra
    consumer-prefetch deliveries held for start-order reordering)."""
    import os

    from ..platform.config import cfg_get

    raw = os.environ.get("SCHEDULER_BACKLOG")
    if raw is None:
        raw = cfg_get(config, "instance.scheduler_backlog", 0)
    try:
        backlog = int(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"scheduler_backlog must be an integer, got {raw!r}"
        ) from None
    if backlog < 0:
        raise ValueError(f"scheduler_backlog must be >= 0, got {backlog}")
    return backlog


def aging_from_config(config) -> float:
    """``instance.scheduler_aging_seconds`` / env SCHEDULER_AGING_SECONDS
    (seconds per one-class starvation bump; 0 disables aging)."""
    import os

    from ..platform.config import cfg_get

    raw = os.environ.get("SCHEDULER_AGING_SECONDS")
    if raw is None:
        raw = cfg_get(
            config, "instance.scheduler_aging_seconds", DEFAULT_AGING_SECONDS
        )
    try:
        aging = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"scheduler_aging_seconds must be a number, got {raw!r}"
        ) from None
    if aging < 0:
        raise ValueError(
            f"scheduler_aging_seconds must be >= 0, got {aging}"
        )
    return aging
