"""Priority-class start-order scheduling for admitted jobs.

The reference starts jobs in raw queue order (/root/reference/lib/main.js:172
consumes FIFO); under mixed traffic a backlog of bulk library imports
delays a user-facing request by the whole backlog.  Here the orchestrator
holds admitted-but-not-started jobs in a small priority queue: when one of
the ``max_concurrent_jobs`` slots frees up, the highest class waiting
starts first (HIGH before NORMAL before BULK).  There is **no mid-job
preemption** — a running bulk job finishes; priority only reorders starts.

Starvation-proofing: a waiter's effective rank improves by one class per
``aging_seconds`` waited, so a BULK job enqueued long ago eventually beats
a just-arrived HIGH job.  Ties break by arrival order (FIFO within class).

Multi-tenant fairness (control/tenancy.py): when a
:class:`~.tenancy.TenantTable` is attached, grants *within* a priority
class are apportioned across tenants by stride scheduling — each grant
advances the winning tenant's virtual pass by ``1/weight``, and the
tenant with the lowest pass wins the next tie — so a tenant with weight
4 gets ~4x the slots of a weight-1 tenant *under contention* while an
uncontended tenant still uses every free slot.  Per-tenant
``max_concurrent`` caps bound how many slots one tenant may hold at
once; a capped tenant's waiters are simply skipped (the slot goes to
the next eligible waiter, or stays free) until one of its jobs
releases.  Without a table every job is the ``default`` tenant and
behavior is exactly the pre-tenancy scheduler.

For the queue to have anything to reorder, the broker must deliver more
jobs than can run: ``instance.scheduler_backlog`` (env
``SCHEDULER_BACKLOG``) adds that many deliveries to the consumer
prefetch.  The default of 0 keeps exact pre-control-plane behavior
(prefetch == run slots, scheduler passes straight through).
"""

from __future__ import annotations

import asyncio
import itertools
import time
from typing import List

from .. import schemas

# start-order rank per priority class; lower starts first
PRIORITY_RANK = {"HIGH": 0, "NORMAL": 1, "BULK": 2}
DEFAULT_AGING_SECONDS = 60.0


def priority_name(value: int) -> str:
    """Wire enum value -> class name; unknown values (a newer producer)
    degrade to NORMAL instead of failing the delivery."""
    try:
        return schemas.JobPriority.Name(value)
    except ValueError:
        return "NORMAL"


def priority_rank(name: str) -> int:
    return PRIORITY_RANK.get(name, PRIORITY_RANK["NORMAL"])


DEFAULT_TENANT = "default"


class _Waiter:
    __slots__ = ("rank", "enqueued", "seq", "fut", "tenant")

    def __init__(self, rank: int, seq: int, tenant: str = DEFAULT_TENANT):
        self.rank = rank
        self.enqueued = time.monotonic()
        self.seq = seq
        self.tenant = tenant
        self.fut: asyncio.Future = asyncio.get_running_loop().create_future()

    def effective(self, now: float, aging: float):
        """Sort key: class rank improved by one per aging interval."""
        bump = int((now - self.enqueued) / aging) if aging > 0 else 0
        return (self.rank - bump, self.seq)


class PriorityScheduler:
    """Counting gate over ``slots`` with priority-ordered, tenant-fair
    grants (see module docstring)."""

    def __init__(self, slots: int,
                 aging_seconds: float = DEFAULT_AGING_SECONDS,
                 tenants=None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.slots = slots
        self.aging_seconds = float(aging_seconds)
        # control/tenancy.TenantTable (or None): weights + concurrency
        # caps for the weighted-fair pick; None = single-tenant behavior
        self.tenants = tenants
        self._free = slots
        self._waiters: List[_Waiter] = []
        self._seq = itertools.count()
        # stride scheduling state: per-tenant virtual pass (advanced by
        # 1/weight per grant) and per-tenant slots currently held
        self._pass: dict = {}
        self._held: dict = {}

    # -- introspection --------------------------------------------------
    @property
    def waiting(self) -> int:
        return len(self._waiters)

    @property
    def in_use(self) -> int:
        return self.slots - self._free

    def held_by_tenant(self) -> dict:
        """Slots currently held, per tenant (GET /v1/tenants)."""
        return {t: n for t, n in self._held.items() if n}

    def waiting_by_tenant(self) -> dict:
        """Queued waiters, per tenant (GET /v1/tenants)."""
        out: dict = {}
        for w in self._waiters:
            out[w.tenant] = out.get(w.tenant, 0) + 1
        return out

    # -- tenant accounting ----------------------------------------------
    def _capped(self, tenant: str) -> bool:
        if self.tenants is None:
            return False
        cap = self.tenants.max_concurrent(tenant)
        return cap is not None and self._held.get(tenant, 0) >= cap

    def _rejoin(self, tenant: str) -> None:
        """Lift a tenant's virtual pass to the ACTIVE floor when it
        enters from idle.

        Stride fairness only holds among tenants that keep competing; a
        tenant idle for a long stretch would otherwise bank unbounded
        credit (its pass frozen far below everyone else's) and
        monopolize grants on return until it "caught up".  The floor is
        the minimum pass among tenants currently holding or waiting —
        the rejoiner itself excluded, and computed BEFORE it becomes
        active, or its own stale pass would anchor the floor and make
        the clamp a no-op.
        """
        if self._held.get(tenant, 0) or any(
                w.tenant == tenant for w in self._waiters):
            return  # already active: its pass is live, not banked
        active = [self._pass[t] for t, n in self._held.items()
                  if n and t != tenant and t in self._pass]
        active += [self._pass[w.tenant] for w in self._waiters
                   if w.tenant != tenant and w.tenant in self._pass]
        if not active:
            return
        floor = min(active)
        current = self._pass.get(tenant)
        if current is None or current < floor:
            self._pass[tenant] = floor

    def _charge(self, tenant: str) -> None:
        self._held[tenant] = self._held.get(tenant, 0) + 1
        weight = (self.tenants.weight(tenant)
                  if self.tenants is not None else 1.0)
        self._pass[tenant] = self._pass.get(tenant, 0.0) + 1.0 / weight

    # -- gate -----------------------------------------------------------
    async def acquire(self, rank: int = 1,
                      tenant: str = DEFAULT_TENANT) -> None:
        """Take a run slot, queueing by ``rank`` (and tenant fairness)
        when none is free or the tenant is at its concurrency cap."""
        self._rejoin(tenant)
        if self._free > 0 and not self._waiters and not self._capped(tenant):
            self._free -= 1
            self._charge(tenant)
            return
        waiter = _Waiter(rank, next(self._seq), tenant)
        self._waiters.append(waiter)
        # a free slot may be grantable to THIS waiter right away (e.g.
        # earlier waiters all belong to capped tenants)
        self._grant()
        try:
            await waiter.fut
        except asyncio.CancelledError:
            try:
                self._waiters.remove(waiter)
            except ValueError:
                if waiter.fut.done() and not waiter.fut.cancelled():
                    # granted in the same tick we were cancelled: return
                    # the slot so it isn't leaked
                    self.release(tenant)
            raise

    def release(self, tenant: str = DEFAULT_TENANT) -> None:
        """Give a slot back and grant it to the best waiter, if any."""
        self._free += 1
        held = self._held.get(tenant, 0)
        if held > 0:
            self._held[tenant] = held - 1
        self._grant()

    def _grant(self) -> None:
        # aging makes the effective key time-dependent, so order is
        # decided at grant time with a plain min() scan — the waiter set
        # is bounded by scheduler_backlog (tens at most), where O(n)
        # beats maintaining any time-invalidated ordered structure
        now = time.monotonic()
        while self._free > 0 and self._waiters:
            eligible = [w for w in self._waiters
                        if not self._capped(w.tenant)]
            if not eligible:
                # every waiting tenant is at its cap: the slot stays
                # free for the next arrival / the next release re-scans
                return
            best = min(
                eligible,
                key=lambda w: (
                    # priority class (with aging) dominates ...
                    w.effective(now, self.aging_seconds)[0],
                    # ... tenants tie-break by stride pass within it
                    # (every waiting tenant has an entry: _rejoin
                    # materializes it at acquire time) ...
                    self._pass.get(w.tenant, 0.0),
                    # ... FIFO within (class, tenant)
                    w.seq,
                ),
            )
            self._waiters.remove(best)
            if best.fut.done():
                # cancelled while queued (guard's task.cancel lands on
                # the future before acquire's except removes the waiter):
                # drop it WITHOUT consuming a slot — set_result on a
                # cancelled future would raise InvalidStateError out of
                # the releasing job's finally and leak the slot
                continue
            self._free -= 1
            self._charge(best.tenant)
            best.fut.set_result(None)


class RunSlot:
    """One job's handle on its priority-scheduler run slot.

    Wraps the acquire/release pair the orchestrator used to manage with
    closure flags, and adds :meth:`reacquire` so a stage that parks for
    a long, idle wait — the fleet plane's lease waiters — can give the
    slot back to runnable jobs and queue for it again (same priority
    rank, normal aging) before resuming.  ``release`` is idempotent:
    the park path releases before its sleep and the processor's finally
    must not double-release.
    """

    __slots__ = ("_scheduler", "_rank", "_tenant", "granted", "released")

    def __init__(self, scheduler: PriorityScheduler, rank: int,
                 tenant: str = DEFAULT_TENANT):
        self._scheduler = scheduler
        self._rank = rank
        self._tenant = tenant
        self.granted = False
        self.released = False

    async def acquire(self) -> None:
        await self._scheduler.acquire(self._rank, self._tenant)
        self.granted = True
        self.released = False

    def release(self) -> None:
        if self.granted and not self.released:
            self.released = True
            self._scheduler.release(self._tenant)

    async def reacquire(self) -> None:
        """Take a slot again after :meth:`release` (no-op when held)."""
        if self.granted and self.released:
            await self._scheduler.acquire(self._rank, self._tenant)
            self.released = False


def backlog_from_config(config) -> int:
    """``instance.scheduler_backlog`` / env SCHEDULER_BACKLOG (extra
    consumer-prefetch deliveries held for start-order reordering)."""
    import os

    from ..platform.config import cfg_get

    raw = os.environ.get("SCHEDULER_BACKLOG")
    if raw is None:
        raw = cfg_get(config, "instance.scheduler_backlog", 0)
    try:
        backlog = int(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"scheduler_backlog must be an integer, got {raw!r}"
        ) from None
    if backlog < 0:
        raise ValueError(f"scheduler_backlog must be >= 0, got {backlog}")
    return backlog


def aging_from_config(config) -> float:
    """``instance.scheduler_aging_seconds`` / env SCHEDULER_AGING_SECONDS
    (seconds per one-class starvation bump; 0 disables aging)."""
    import os

    from ..platform.config import cfg_get

    raw = os.environ.get("SCHEDULER_AGING_SECONDS")
    if raw is None:
        raw = cfg_get(
            config, "instance.scheduler_aging_seconds", DEFAULT_AGING_SECONDS
        )
    try:
        aging = float(raw)
    except (TypeError, ValueError):
        raise ValueError(
            f"scheduler_aging_seconds must be a number, got {raw!r}"
        ) from None
    if aging < 0:
        raise ValueError(
            f"scheduler_aging_seconds must be >= 0, got {aging}"
        )
    return aging
