"""Cooperative job cancellation.

The reference service has exactly one intervention for a job in flight:
kill the whole worker (/root/reference/lib/main.js:197-204).  The control
plane replaces that with a :class:`CancelToken` carried in every job's
``StageContext`` and checked cooperatively at the natural yield points —
HTTP chunk loops, the torrent client's drive loop between piece batches,
the upload stage's per-file loop — plus :meth:`CancelToken.guard`, which
bounds any long await (admission wait, scheduler queue, a whole stage
dispatch) by the token without requiring the awaited code to poll.

Cancellation is an *operator decision about this delivery*: the
orchestrator settles a cancelled job with ``ack`` (no requeue), removes
its partial staging files, and records the terminal ``CANCELLED`` state
in the registry.  A cancelled singleflight leader rejects its flight, so
coalesced same-content waiters fail over to their own fetch instead of
dying with it (store/cache.py's retry loop).
"""

from __future__ import annotations

import asyncio
from typing import Any, Awaitable, Optional


class JobCancelled(Exception):
    """Raised inside a job's pipeline when its token was cancelled.

    Deliberately NOT an ``asyncio.CancelledError``: it must be
    distinguishable from task teardown (shutdown cancels handlers too)
    and must traverse the orchestrator's generic stage-error handling
    without being retried — the orchestrator catches it and settles the
    delivery with ``ack``.
    """

    code = "ERRCANCELLED"

    def __init__(self, job_id: str = "", reason: str = ""):
        self.job_id = job_id
        self.reason = reason
        detail = f" ({reason})" if reason else ""
        super().__init__(f"job {job_id or '?'} cancelled{detail}")


class CancelToken:
    """One job's cancellation flag; fire-once, observed cooperatively."""

    __slots__ = ("job_id", "reason", "_event")

    def __init__(self, job_id: str = ""):
        self.job_id = job_id
        self.reason: Optional[str] = None
        self._event = asyncio.Event()

    def __repr__(self) -> str:  # registry/API debugging
        state = f"cancelled={self.reason!r}" if self.cancelled else "live"
        return f"CancelToken({self.job_id!r}, {state})"

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self, reason: str = "cancelled") -> bool:
        """Fire the token; False when it was already fired."""
        if self._event.is_set():
            return False
        self.reason = reason
        self._event.set()
        return True

    def raise_if_cancelled(self) -> None:
        """The cooperative check stages call inside their chunk loops."""
        if self._event.is_set():
            raise JobCancelled(self.job_id, self.reason or "")

    async def wait(self) -> None:
        await self._event.wait()

    async def guard(self, awaitable: Awaitable[Any]) -> Any:
        """Await ``awaitable``, aborting with :class:`JobCancelled` the
        moment this token fires first.

        The inner work is cancelled (``asyncio`` task cancellation) and
        *joined* before the error is raised, so its cleanup paths — fd
        teardown, thread-pool drains — finish before the orchestrator
        starts removing the job's files.
        """
        task = asyncio.ensure_future(awaitable)
        if self.cancelled:
            await self._reap(task)
            raise JobCancelled(self.job_id, self.reason or "")
        watcher = asyncio.ensure_future(self._event.wait())
        try:
            done, _pending = await asyncio.wait(
                {task, watcher}, return_when=asyncio.FIRST_COMPLETED
            )
        except asyncio.CancelledError:
            # the caller itself is being torn down (e.g. shutdown):
            # propagate, but never orphan the inner task
            await self._reap(task)
            raise
        finally:
            watcher.cancel()
        if task in done:
            return task.result()  # raises the task's own error, if any
        await self._reap(task)
        raise JobCancelled(self.job_id, self.reason or "")

    @staticmethod
    async def _reap(task: "asyncio.Future") -> None:
        task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            pass
