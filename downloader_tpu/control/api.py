"""Admin API: the control plane's HTTP surface.

Mounted on the same aiohttp app as ``/health`` (health.py), so one port
serves probes, metrics, and operations:

    GET  /v1/jobs                   list live + recently-terminal jobs
    GET  /v1/jobs/{id}              one job's record
    GET  /v1/jobs/{id}/events       the job's flight-recorder timeline
                                    (state transitions, waits, throughput
                                    samples, cache/retry/settle decisions,
                                    correlation ids)
    POST /v1/jobs/{id}/cancel       fire the job's cancel token
    GET  /v1/trace/{trace_id}       cross-worker timeline for one trace:
                                    local segments + peer digests from
                                    the coordination store + live peer
                                    admin APIs (?scope=local = this
                                    worker only; fleet trouble degrades
                                    to the local view, never an error)
    GET  /v1/fleet                  fleet membership: live workers (with
                                    heartbeat payloads), live content
                                    leases, this worker's fleet stats
    GET  /v1/fleet/overview         the aggregated fleet overview doc
                                    (burn rates, breakers, tenant queue
                                    shares, top hops) the elected
                                    aggregator folds each heartbeat;
                                    coord trouble degrades to the local
                                    view (degraded: true), never a 5xx
    GET  /v1/fleet/plan             the placement controller's plan doc
                                    (admission shed, drain set, desired
                                    workers, bounded decision tail) as
                                    this worker's watch-fed cache holds
                                    it; always 200 — absent/stale plan
                                    just reads as plan: null/fresh:false
    GET  /v1/fleet/{id}             one worker's latest heartbeat doc
    GET  /v1/tenants                tenancy + overload posture: per-
                                    tenant weight/caps/quotas, live queue
                                    depth and slot occupancy, saturation
                                    snapshot
    GET  /v1/incidents              exported incident-bundle summaries
                                    (the bounded auto-export ring +
                                    manual exports); disabled plane
                                    reads as enabled:false, never a 5xx
    GET  /v1/incidents/{id}         one full bundle by bundleId, job id,
                                    or trace id
    POST /v1/incidents/{id}/export  snapshot a live/recent job into the
                                    ring now (trigger=manual)
    POST /v1/incidents/verdict      record an incident-replay verdict
                                    (sets incident_replay_signature_match)
    POST /v1/intake/pause           stop pulling deliveries (in-flight
                                    work keeps running; /readyz -> 503)
    POST /v1/intake/resume          start pulling again
    POST /v1/drain?grace=30         pause intake + wait for in-flight
                                    jobs (programmatic shutdown grace)
    GET  /debug/tasks               live asyncio tasks (name, coroutine,
                                    stack top) + loop-lag stats
    GET  /debug/stacks              every thread's and task's current
                                    stack (the SIGUSR1 dump, over HTTP)

Mutating endpoints (POST) are gated by an optional bearer token from
``control.token`` / env ``CONTROL_TOKEN``; reads stay open like
``/metrics``.  Without a token configured every caller is allowed — the
parity posture for a service that previously had no API at all.
"""

from __future__ import annotations

import asyncio
import hmac
import os
import time
from typing import Optional

from aiohttp import web

from ..incident.bundle import TRIGGER_MANUAL, export_incident
from ..platform.config import cfg_get
from ..platform.obs import dump_stacks, dump_tasks
from . import registry as reg


def resolve_token(config) -> Optional[str]:
    return os.environ.get("CONTROL_TOKEN") or cfg_get(
        config, "control.token", None
    )


def bind_control_routes(app: web.Application, orchestrator) -> None:
    token = resolve_token(getattr(orchestrator, "config", None))

    def _registry():
        return getattr(orchestrator, "registry", None)

    def _authorized(request: web.Request) -> bool:
        if not token:
            return True
        header = request.headers.get("Authorization", "")
        # compare BYTES: compare_digest on str raises TypeError for
        # non-ASCII input, which would turn a hostile header into a 500
        # instead of a 401
        return hmac.compare_digest(
            header.encode("utf-8", "surrogateescape"),
            f"Bearer {token}".encode("utf-8", "surrogateescape"),
        )

    def _deny() -> web.Response:
        return web.json_response(
            {"error": "missing or invalid bearer token"}, status=401
        )

    def _unavailable() -> web.Response:
        return web.json_response(
            {"error": "control plane unavailable"}, status=503
        )

    async def jobs_list(request: web.Request) -> web.Response:
        registry = _registry()
        if registry is None:
            return _unavailable()
        state = request.query.get("state")
        if state and state not in reg.LEGAL_TRANSITIONS:
            return web.json_response(
                {"error": f"unknown state {state!r}",
                 "states": sorted(reg.LEGAL_TRANSITIONS)}, status=400
            )
        jobs = registry.jobs(state)
        # ?recovered=true: only jobs that survived a worker crash
        # (journal-replayed placeholders + their adopting redeliveries)
        if request.query.get("recovered") in ("true", "1", "yes"):
            jobs = [r for r in jobs if r.recovered]
        return web.json_response({
            "jobs": [r.to_dict() for r in jobs],
            "counts": registry.counts(),
            "workerId": getattr(orchestrator, "worker_id", None),
            "intakePaused": bool(
                getattr(orchestrator, "intake_paused", False)
            ),
        })

    async def job_show(request: web.Request) -> web.Response:
        registry = _registry()
        if registry is None:
            return _unavailable()
        record = registry.get(request.match_info["id"])
        if record is None:
            return web.json_response({"error": "unknown job"}, status=404)
        return web.json_response(record.to_dict())

    async def job_events(request: web.Request) -> web.Response:
        """The job's flight-recorder timeline — the one endpoint that
        answers "why is job X slow / stuck / dead" without shelling in."""
        registry = _registry()
        if registry is None:
            return _unavailable()
        record = registry.get(request.match_info["id"])
        if record is None:
            return web.json_response({"error": "unknown job"}, status=404)
        return web.json_response({
            "id": record.job_id,
            "state": record.state,
            "stage": record.stage,
            "traceId": record.trace_id,
            "spanId": record.span_id,
            "eventsDropped": record.recorder.dropped,
            "events": record.recorder.events(),
        })

    async def trace_show(request: web.Request) -> web.Response:
        """The cross-worker timeline for one trace id: local registry
        segments + tracer spans, merged with peer digests from the
        coordination store and live peer admin APIs.  Coordination
        trouble degrades to the local view (``degraded: true``) — this
        endpoint never 5xxes on fleet trouble.  ``?scope=local`` (what
        peers send each other) skips every remote hop."""
        assemble = getattr(orchestrator, "assemble_trace", None)
        if assemble is None:
            return _unavailable()
        trace_id = request.match_info["id"]
        remote = request.query.get("scope") != "local"
        document = await assemble(trace_id, remote=remote)
        if not document["segments"] and not document["spans"]:
            return web.json_response(
                {"error": "unknown trace", **document}, status=404
            )
        return web.json_response(document)

    async def fleet_list(_request: web.Request) -> web.Response:
        """Fleet membership: live workers (heartbeat payloads incl. the
        autoscale trio), every live content lease, and this worker's
        own shared-tier stats."""
        plane = getattr(orchestrator, "fleet", None)
        payload = {
            "workerId": getattr(orchestrator, "worker_id", None),
            "enabled": plane is not None,
        }
        if plane is None:
            return web.json_response(payload)
        try:
            payload["workers"] = await plane.workers()
            payload["leases"] = await plane.leases()
        except Exception as err:  # coordination store down: say so
            return web.json_response(
                {**payload, "error": f"coordination store: {err}"},
                status=503,
            )
        payload["heldLeases"] = plane.lease_snapshot()
        payload["stats"] = dict(plane.stats)
        return web.json_response(payload)

    async def fleet_overview(_request: web.Request) -> web.Response:
        """The aggregated fleet overview (ISSUE 15): the one document
        the elected aggregator folds every live member's heartbeat
        digest into — fleet-wide tenant queue shares, worst-of-fleet
        burn rates, open breakers per worker, top hops by
        seconds-per-GB.  The trace-assembly degradation contract: any
        coordination trouble (down, browned out past the 5 s budget)
        serves the LOCAL view with ``degraded: true`` + a bounded
        ``errors`` list — never a 5xx."""
        plane = getattr(orchestrator, "fleet", None)
        # the local view is always serveable — no I/O, no fleet
        local = {"workerId": getattr(orchestrator, "worker_id", None)}
        signals_fn = getattr(orchestrator, "autoscale_signals", None)
        if callable(signals_fn):
            try:
                local["signals"] = dict(signals_fn())
            except Exception:
                pass
        digest_fn = getattr(orchestrator, "slo_digest", None)
        if callable(digest_fn):
            try:
                local["digest"] = dict(digest_fn())
            except Exception:
                pass
        payload: dict = {
            "enabled": plane is not None,
            "workerId": getattr(orchestrator, "worker_id", None),
            "local": local,
            "overview": None,
            "degraded": False,
            "errors": [],
        }
        if plane is None:
            return web.json_response(payload)
        try:
            doc = await plane.fetch_overview()
        except asyncio.CancelledError:
            raise
        except Exception as err:
            payload["degraded"] = True
            payload["errors"].append(
                f"coord overview: {type(err).__name__}: {err}"[:200])
            doc = None
        if doc is not None:
            payload["overview"] = doc
            age = plane.overview_age()
            if age is not None:
                payload["overviewAgeSeconds"] = round(age, 3)
        # the controller's current plan rides along (watch-fed cache,
        # no extra round trip) so `cli fleet top` shows admission/
        # drain/scale posture in the same frame
        plan = plane.current_plan()
        if plan is not None:
            payload["plan"] = plan
        return web.json_response(payload)

    async def fleet_plan(_request: web.Request) -> web.Response:
        """The placement controller's plan (ISSUE 17): served from THIS
        worker's watch-fed cache — the exact document admission acts on
        here, zero coordination round trips, so the endpoint stays up
        (and honest) through coord brownout.  ``fresh`` is the router's
        own staleness gate: false means admission is running
        uncontrolled even though a (stale) plan body is shown."""
        plane = getattr(orchestrator, "fleet", None)
        controller = getattr(orchestrator, "controller", None)
        payload: dict = {
            "enabled": plane is not None,
            "workerId": getattr(orchestrator, "worker_id", None),
            "plan": None,
            "fresh": False,
            "controller": None,
        }
        if controller is not None:
            payload["controller"] = {
                "running": controller._task is not None,
                "ticks": controller.ticks,
                "plansPublished": controller.plans_published,
            }
        if plane is None:
            return web.json_response(payload)
        fresh = plane.current_plan()
        doc = fresh if fresh is not None else plane._plan_doc
        if doc is not None:
            payload["plan"] = doc
            payload["fresh"] = fresh is not None
            payload["planAgeSeconds"] = round(
                max(time.time() - float(doc.get("updatedAt", 0) or 0),
                    0.0), 3)
        return web.json_response(payload)

    async def fleet_show(request: web.Request) -> web.Response:
        plane = getattr(orchestrator, "fleet", None)
        if plane is None:
            return web.json_response(
                {"error": "fleet plane disabled"}, status=503
            )
        try:
            doc = await plane.worker(request.match_info["id"])
        except Exception as err:
            return web.json_response(
                {"error": f"coordination store: {err}"}, status=503
            )
        if doc is None:
            return web.json_response({"error": "unknown worker"},
                                     status=404)
        return web.json_response(doc)

    async def tenants_list(_request: web.Request) -> web.Response:
        """Tenancy + overload posture: per-tenant config (weight, caps,
        quotas), live per-tenant queue depth / held run slots / waiting
        jobs, and the overload controller's saturation snapshot — the
        one endpoint that answers "why is tenant X's work not starting"."""
        table = getattr(orchestrator, "tenants", None)
        if table is None:
            return web.json_response(
                {"error": "tenancy unavailable"}, status=503
            )
        registry = _registry()
        scheduler = getattr(orchestrator, "scheduler", None)
        depths = (registry.tenant_queue_depths()
                  if registry is not None else {})
        held = (scheduler.held_by_tenant()
                if scheduler is not None else {})
        waiting = (scheduler.waiting_by_tenant()
                   if scheduler is not None else {})
        footprint_fn = getattr(orchestrator, "tenant_staging_bytes", None)
        footprints = footprint_fn() if callable(footprint_fn) else {}
        tenants = {}
        for name, spec in table.describe().items():
            tenants[name] = {
                **spec,
                "queued": depths.get(name, 0),
                "runningSlots": held.get(name, 0),
                "waitingForSlot": waiting.get(name, 0),
                # live disk footprint (quotas cover transfer rate only;
                # this is the accounting half, no enforcement)
                "stagingBytes": footprints.get(name, 0),
            }
        overload = getattr(orchestrator, "overload", None)
        return web.json_response({
            "workerId": getattr(orchestrator, "worker_id", None),
            "configured": table.configured,
            "tenants": tenants,
            "overload": (overload.snapshot() if overload is not None
                         else {"enabled": False}),
        })

    async def debug_tasks(_request: web.Request) -> web.Response:
        monitor = getattr(orchestrator, "loop_monitor", None)
        return web.json_response({
            "tasks": dump_tasks(),
            "loopLag": {
                "last": getattr(monitor, "last_lag", None),
                "max": getattr(monitor, "max_lag", None),
            },
        })

    async def debug_stacks(_request: web.Request) -> web.Response:
        return web.json_response(dump_stacks())

    async def job_cancel(request: web.Request) -> web.Response:
        if not _authorized(request):
            return _deny()
        registry = _registry()
        if registry is None:
            return _unavailable()
        job_id = request.match_info["id"]
        reason = request.query.get("reason") or "operator"
        if request.can_read_body:
            try:
                body = await request.json()
                reason = body.get("reason") or reason
            except (ValueError, AttributeError):
                pass
        fired = registry.cancel(job_id, reason=reason)
        record = registry.get(job_id)
        if not fired:
            if record is None:
                return web.json_response({"error": "unknown job"}, status=404)
            # known but already terminal (or token already fired)
            return web.json_response(
                {"error": "job is not cancellable", "job": record.to_dict()},
                status=409,
            )
        return web.json_response(
            {"cancelled": len(fired), "job": record.to_dict()}, status=202
        )

    async def intake_pause(request: web.Request) -> web.Response:
        if not _authorized(request):
            return _deny()
        pause = getattr(orchestrator, "pause_intake", None)
        if pause is None:
            return _unavailable()
        await pause()
        return web.json_response({"intakePaused": True})

    async def intake_resume(request: web.Request) -> web.Response:
        if not _authorized(request):
            return _deny()
        resume = getattr(orchestrator, "resume_intake", None)
        if resume is None:
            return _unavailable()
        await resume()
        return web.json_response({"intakePaused": False})

    async def incidents_list(_request: web.Request) -> web.Response:
        """Exported incident bundles (ISSUE 18), summaries only — the
        same degradation contract as the fleet surfaces: a disabled or
        empty incident plane reads as an empty listing, never a 5xx."""
        store = getattr(orchestrator, "incidents", None)
        if store is None:
            return web.json_response({"enabled": False, "incidents": []})
        payload = {
            "enabled": True,
            "workerId": getattr(orchestrator, "worker_id", None),
            "maxBundles": store.max_bundles,
            "autoExport": store.auto_export,
            "exportedTotal": store.exported_total,
            "lastVerdict": store.last_verdict,
            "incidents": [],
        }
        try:
            payload["incidents"] = store.summaries()
        except Exception:
            pass  # a torn summary degrades to the empty list, not a 5xx
        return web.json_response(payload)

    async def incident_show(request: web.Request) -> web.Response:
        """One full bundle, by bundleId, job id, or trace id."""
        store = getattr(orchestrator, "incidents", None)
        if store is None:
            return web.json_response(
                {"error": "incident plane disabled"}, status=404)
        bundle = store.get(request.match_info["id"])
        if bundle is None:
            return web.json_response(
                {"error": "unknown incident"}, status=404)
        return web.json_response(bundle)

    async def incident_export_route(request: web.Request) -> web.Response:
        """Manual export: snapshot a live/recently-settled job into the
        ring (trigger=manual) and return the full bundle."""
        if not _authorized(request):
            return _deny()
        if getattr(orchestrator, "incidents", None) is None:
            return web.json_response(
                {"error": "incident plane disabled"}, status=409)
        bundle = export_incident(
            orchestrator, request.match_info["id"], trigger=TRIGGER_MANUAL)
        if bundle is None:
            return web.json_response({"error": "unknown job"}, status=404)
        return web.json_response(bundle, status=201)

    async def incident_verdict(request: web.Request) -> web.Response:
        """Record a replay verdict against this worker's incidents:
        `cli incident replay/diff` posts whether the replay reproduced
        the original breach signature, which lands on the
        incident_replay_signature_match gauge (so the worker that
        exported the bundle alarms on a diverging replay)."""
        if not _authorized(request):
            return _deny()
        store = getattr(orchestrator, "incidents", None)
        if store is None:
            return web.json_response(
                {"error": "incident plane disabled"}, status=409)
        try:
            body = await request.json()
        except ValueError:
            return web.json_response(
                {"error": "body must be JSON"}, status=400)
        if not isinstance(body, dict) or "match" not in body:
            return web.json_response(
                {"error": "body must carry match: bool"}, status=400)
        verdict = {
            "match": bool(body.get("match")),
            "bundleId": body.get("bundleId"),
            "fields": body.get("fields"),
        }
        store.last_verdict = verdict
        metrics = getattr(orchestrator, "metrics", None)
        if metrics is not None:
            try:
                metrics.incident_replay_signature_match.set(
                    1.0 if verdict["match"] else 0.0)
            except Exception:
                pass
        return web.json_response({"recorded": True, **verdict})

    async def drain(request: web.Request) -> web.Response:
        if not _authorized(request):
            return _deny()
        drain_fn = getattr(orchestrator, "drain", None)
        if drain_fn is None:
            return _unavailable()
        try:
            grace = float(request.query.get("grace", 30.0))
        except ValueError:
            return web.json_response(
                {"error": "grace must be a number of seconds"}, status=400
            )
        drained = await drain_fn(grace_seconds=grace)
        return web.json_response({
            "drained": drained,
            "intakePaused": True,
            "active": len(getattr(orchestrator, "active_jobs", [])),
        }, status=200 if drained else 504)

    app.router.add_get("/v1/jobs", jobs_list)
    app.router.add_get("/v1/jobs/{id}", job_show)
    app.router.add_get("/v1/jobs/{id}/events", job_events)
    app.router.add_post("/v1/jobs/{id}/cancel", job_cancel)
    # cross-worker trace assembly: local + digests + live peers
    app.router.add_get("/v1/trace/{id}", trace_show)
    # fleet plane: membership, leases, per-worker heartbeat payloads
    app.router.add_get("/v1/fleet", fleet_list)
    # the aggregated overview + the controller's plan must register
    # BEFORE the {id} route or "overview"/"plan" would be captured as
    # worker ids
    app.router.add_get("/v1/fleet/overview", fleet_overview)
    app.router.add_get("/v1/fleet/plan", fleet_plan)
    app.router.add_get("/v1/fleet/{id}", fleet_show)
    # tenancy + overload: per-tenant weights/caps/quotas, live queue
    # depth and slot occupancy, and the saturation snapshot
    app.router.add_get("/v1/tenants", tenants_list)
    # runtime introspection: reads, open like /metrics
    app.router.add_get("/debug/tasks", debug_tasks)
    app.router.add_get("/debug/stacks", debug_stacks)
    # incident plane: the bundle ring (reads open like /metrics;
    # manual exports + replay verdicts token-gated).  The literal
    # /verdict route registers before the {id} capture, like
    # /v1/fleet/overview above
    app.router.add_get("/v1/incidents", incidents_list)
    app.router.add_get("/v1/incidents/{id}", incident_show)
    app.router.add_post("/v1/incidents/verdict", incident_verdict)
    app.router.add_post("/v1/incidents/{id}/export", incident_export_route)
    app.router.add_post("/v1/intake/pause", intake_pause)
    app.router.add_post("/v1/intake/resume", intake_resume)
    app.router.add_post("/v1/drain", drain)
