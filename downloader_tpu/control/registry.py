"""Job registry: the control plane's source of truth for job lifecycle.

The reference's only job visibility is the inverted ``/health`` counter
(/root/reference/lib/main.js:174-194): an operator cannot list, inspect,
or intervene in work.  The registry records every delivery from the
moment it is received — *before* admission, closing the pre-r7 blind
spot where a job parked in the admission gate was invisible to
``/health`` and drain — and walks it through a validated state machine:

    RECEIVED -> ADMITTED -> RUNNING(stage) -> PUBLISHING
                                 -> DONE | FAILED | CANCELLED | DROPPED_POISON

``stage`` is the sequential stage name under the barrier dispatch
(download/process/upload[/upscale]); the streaming dispatch runs all
three logical stages overlapped and carries one combined
``RUNNING("pipeline")`` attribution instead — per-file detail rides the
flight recorder's ``file_complete``/``upload_start``/``upload_done``
events, and ``stage_seconds`` accumulates under ``"pipeline"``.

Illegal transitions raise :class:`IllegalTransition` (a lifecycle bug
must fail loudly, not corrupt operator-facing state).  Each record keeps
per-stage wall timing, byte counters sampled from stage progress, and
the cancel token the admin API fires.  Terminal records move to a
bounded ring for post-hoc inspection (``GET /v1/jobs`` keeps answering
for recently finished work without growing forever).

Metrics: ``jobs_by_state`` gauge (every record the registry knows, by
state) and ``job_state_transitions_total`` counter (from/to labels).

Observability (platform/obs.py): every record carries a
:class:`~..platform.obs.FlightRecorder` — a bounded ring of structured
events (state transitions with per-stage timing, throughput samples,
cache/retry/cancel/settle decisions, span references) served live by
``GET /v1/jobs/{id}/events``.  A record closing as FAILED or
DROPPED_POISON logs a debug bundle (the tail of its timeline + its
trace id), so a dead job's post-mortem is one log line away even after
the terminal ring evicts it.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Any, Deque, Dict, List, Optional

from ..platform.obs import DEFAULT_EVENT_LIMIT, FlightRecorder, HopLedger
from ..utils import utcnow_iso as _utcnow_iso
from .cancel import CancelToken

# -- lifecycle states ---------------------------------------------------
RECEIVED = "RECEIVED"
PARKED = "PARKED"
ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
PUBLISHING = "PUBLISHING"
DONE = "DONE"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
DROPPED_POISON = "DROPPED_POISON"
# deadline-expired BULK work dropped by the overload layer
# (control/overload.py): distinct from FAILED (nothing errored) and from
# DROPPED_POISON (the content is fine) — the job simply outlived its
# submitter-declared TTL while queued, and re-running it would waste the
# very capacity the deadline exists to protect
EXPIRED = "EXPIRED"

TERMINAL_STATES = frozenset({DONE, FAILED, CANCELLED, DROPPED_POISON,
                             EXPIRED})

# RUNNING -> RUNNING models stage hops (download -> process -> upload);
# ADMITTED -> PUBLISHING is the idempotency skip (done marker already
# staged); FAILED is reachable from anywhere non-terminal (a handler can
# die at any point and the record must still close).  PARKED is the
# fault-tolerance layer's holding state (platform/errors.py): a job
# waiting out an open dependency breaker at admission, or sitting in a
# delayed-redelivery backoff before its nack — visible in
# ``jobs_by_state`` instead of masquerading as stuck RECEIVED/RUNNING.
LEGAL_TRANSITIONS: Dict[str, frozenset] = {
    # EXPIRED is reachable only BEFORE a job runs (RECEIVED/PARKED/
    # ADMITTED): a deadline noticed mid-transfer finishes the work — the
    # bytes are mostly paid for, and the deadline's purpose is to shed
    # *queued* backlog, not to waste a nearly-done transfer
    RECEIVED: frozenset({PARKED, ADMITTED, FAILED, CANCELLED, EXPIRED}),
    # PARKED -> RUNNING: a job parked MID-RUN (waiting out a peer
    # worker's content lease, fleet/plane.py) resumes its stage when
    # the leader publishes; admission-parked jobs still go via ADMITTED.
    # PARKED -> RECEIVED: a crash-recovery placeholder (control/
    # journal.py) is adopted by its redelivery and re-enters the normal
    # intake path from the top — one record carries both incarnations.
    # PARKED -> DONE: a recovery placeholder whose content a fleet PEER
    # already staged (durable done marker observed) is retired without
    # a local run — its redelivery went to the peer and will never
    # arrive here (orchestrator._probe_recovered_staged).
    PARKED: frozenset(
        {RECEIVED, ADMITTED, RUNNING, DONE, FAILED, CANCELLED,
         DROPPED_POISON, EXPIRED}
    ),
    ADMITTED: frozenset(
        {RUNNING, PARKED, PUBLISHING, FAILED, CANCELLED, DROPPED_POISON,
         EXPIRED}
    ),
    RUNNING: frozenset(
        {RUNNING, PARKED, PUBLISHING, FAILED, CANCELLED, DROPPED_POISON}
    ),
    # DROPPED_POISON from PUBLISHING: publish failures count toward the
    # poison threshold too (they used to bypass it and redeliver forever)
    PUBLISHING: frozenset(
        {PARKED, DONE, FAILED, CANCELLED, DROPPED_POISON}
    ),
    DONE: frozenset(),
    FAILED: frozenset(),
    CANCELLED: frozenset(),
    DROPPED_POISON: frozenset(),
    EXPIRED: frozenset(),
}

DEFAULT_TERMINAL_RING = 256
# flight-recorder events kept in a terminal debug bundle log line
DEBUG_BUNDLE_EVENTS = 20


class IllegalTransition(RuntimeError):
    """A lifecycle move the state machine forbids."""


class JobRecord:
    """One delivery's lifecycle, as the control plane sees it."""

    __slots__ = (
        "uid", "job_id", "file_id", "priority", "state", "stage", "reason",
        "percent", "bytes", "cancel", "created_at", "updated_at",
        "stage_seconds", "_entered_mono", "_created_mono",
        "recorder", "trace_id", "span_id", "transferred", "retry",
        "worker_id", "tenant", "ttl_seconds", "deadline_mono",
        "recovered", "hops", "fleet_fence", "fleet_fence_key",
        "fleet_waited_s", "workload",
        "route_key", "route_decision", "plan_epoch",
    )

    def __init__(self, uid: int, job_id: str, file_id: str, priority: str,
                 recorder_events: int = DEFAULT_EVENT_LIMIT,
                 worker_id: Optional[str] = None,
                 tenant: str = "default",
                 ttl_seconds: float = 0.0,
                 hop_ledger: bool = True):
        self.uid = uid
        self.job_id = job_id
        self.file_id = file_id
        self.priority = priority
        # resolved tenant identity (control/tenancy.py): the axis the
        # scheduler's weighted-fair pick, the per-tenant quotas, and the
        # shed metrics attribute this delivery to
        self.tenant = tenant
        # optional deadline: Download.ttl_seconds measured from receipt;
        # 0 = none.  deadline_mono is the absolute monotonic cutoff.
        self.ttl_seconds = float(ttl_seconds or 0.0)
        self.deadline_mono: Optional[float] = None
        # which worker processed this delivery: stamped into the record,
        # every flight-recorder event (recorder context below), the
        # job's child logger, and GET /v1/jobs — the cross-worker join
        # key beside trace_id once a fleet of workers shares traffic
        self.worker_id = worker_id
        self.state = RECEIVED
        self.stage: Optional[str] = None
        self.reason: Optional[str] = None
        self.percent: Optional[int] = None
        self.bytes: Dict[str, int] = {}
        self.cancel = CancelToken(job_id)
        self.created_at = _utcnow_iso()
        self.updated_at = self.created_at
        self.stage_seconds: Dict[str, float] = {}
        self._created_mono = time.monotonic()
        self._entered_mono = self._created_mono
        if self.ttl_seconds > 0:
            self.deadline_mono = self._created_mono + self.ttl_seconds
        # per-job flight recorder (platform/obs.py): the job's bounded
        # event timeline, served by GET /v1/jobs/{id}/events.  The
        # tenant joins the context only when non-default, so a
        # single-tenant deployment's event stream is unchanged.
        context: Dict[str, Any] = {}
        if worker_id:
            context["workerId"] = worker_id
        if tenant and tenant != "default":
            context["tenant"] = tenant
        self.recorder = FlightRecorder(
            recorder_events, context=context or None,
        )
        # correlation ids: the job span's W3C trace/span id, also bound
        # into the job's child logger — one id joins log lines, the
        # OTLP span, and this record's timeline
        self.trace_id: Optional[str] = None
        self.span_id: Optional[str] = None
        # crash-recovery provenance (control/journal.py): True on a
        # record replayed from the journal at boot — first as the PARKED
        # "awaiting redelivery" placeholder, then carried through the
        # adopting redelivery, so GET /v1/jobs?recovered= can list the
        # jobs that survived a worker kill
        self.recovered = False
        # live retry/backoff detail (platform/errors.py): the Retrier
        # sets it while a dependency call is between attempts, the
        # orchestrator while the job is parked for delayed redelivery —
        # so GET /v1/jobs/{id} and `cli jobs show` answer "is this job
        # stuck or deliberately waiting" at a glance
        self.retry: Optional[Dict[str, Any]] = None
        # live mid-transfer byte counters (absolute, per kind), fed by
        # the stages' chunk loops and sampled by the TransferProfiler;
        # unlike ``bytes`` (committed at stage completion) these move
        # WHILE a transfer runs, so a stalled job is visibly flat
        self.transferred: Dict[str, int] = {}
        # per-hop byte+time attribution (platform/obs.py HopLedger), fed
        # by the stages' transfer loops; None (``obs.hop_ledger: false``)
        # makes note_hop a no-op — the bench's disabled/enabled A-B leg
        self.hops: Optional[HopLedger] = HopLedger() if hop_ledger else None
        # fencing context (fleet/plane.py): the content-lease fence this
        # job's origin authority derives from — stamped when the job
        # wins a fleet lease, carried into every cross-worker write
        # (shared-tier manifest, done marker, telemetry digest) so a
        # resumed stale leader's writes are rejectable
        self.fleet_fence: Optional[int] = None
        self.fleet_fence_key: Optional[str] = None
        # cumulative seconds this job has parked on fleet lease waits,
        # carried ACROSS redeliveries/coordination errors so the
        # fleet.max_wait livelock bound holds under a flapping coord
        # store (each re-park used to reset the clock)
        self.fleet_waited_s = 0.0
        # workload class (control/slo.py WORKLOAD_CLASSES): stamped by a
        # stage that ran a chip-bound subsystem (the upscale stage sets
        # "UPSCALE"), so the job ALSO burns that subsystem's SLO budget
        self.workload: Optional[str] = None
        # placement context (fleet/router.py + the controller plan):
        # the content route key, the router's admission outcome, and
        # the plan epoch in force when this delivery was admitted —
        # joined onto slo_breach events and incident bundles so a
        # breach explains WHERE the job was when it burned
        self.route_key: Optional[str] = None
        self.route_decision: Optional[str] = None
        self.plan_epoch: Optional[int] = None

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def deadline_expired(self, now: Optional[float] = None) -> bool:
        """True once the job's TTL (if any) has elapsed since receipt."""
        if self.deadline_mono is None:
            return False
        return (now if now is not None else time.monotonic()) \
            >= self.deadline_mono

    def deadline_remaining(self) -> Optional[float]:
        """Seconds until the deadline (negative = overdue); None = no TTL."""
        if self.deadline_mono is None:
            return None
        return self.deadline_mono - time.monotonic()

    def event(self, kind: str, **fields: Any) -> None:
        """Append one flight-recorder event to this job's timeline."""
        self.recorder.record(kind, **fields)

    def add_bytes(self, kind: str, count: int) -> None:
        """Stage-side byte sampling (downloaded/uploaded so far)."""
        if count:
            self.bytes[kind] = self.bytes.get(kind, 0) + int(count)

    def note_transfer(self, kind: str, total: int) -> None:
        """Live absolute transfer counter (cheap: called per chunk)."""
        self.transferred[kind] = int(total)

    def note_hop(self, hop: str, nbytes: int, seconds: float) -> None:
        """Accumulate one hop sample (cheap: called per chunk/slice)."""
        if self.hops is not None:
            self.hops.note(hop, nbytes, seconds)

    def note_progress(self, percent: int) -> None:
        self.percent = int(percent)
        self.updated_at = _utcnow_iso()

    def to_dict(self) -> dict:
        """JSON shape served by ``GET /v1/jobs[/{id}]``."""
        remaining = self.deadline_remaining()
        return {
            "id": self.job_id,
            "fileId": self.file_id,
            "priority": self.priority,
            "tenant": self.tenant,
            "ttlSeconds": self.ttl_seconds or None,
            "deadlineRemainingSeconds": (
                round(remaining, 3) if remaining is not None else None
            ),
            "workerId": self.worker_id,
            "state": self.state,
            "stage": self.stage,
            "reason": self.reason,
            "percent": self.percent,
            "bytes": dict(self.bytes),
            "retry": dict(self.retry) if self.retry else None,
            "recovered": self.recovered,
            "cancelRequested": self.cancel.cancelled,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "createdAt": self.created_at,
            "updatedAt": self.updated_at,
            "ageSeconds": round(time.monotonic() - self._created_mono, 3),
            "stageSeconds": {
                k: round(v, 3) for k, v in self.stage_seconds.items()
            },
            "hopLedger": (self.hops.summary()
                          if self.hops is not None and self.hops else None),
            "fleetFence": self.fleet_fence,
            "placement": ({
                "routeKey": self.route_key,
                "routeDecision": self.route_decision,
                "planEpoch": self.plan_epoch,
            } if (self.route_key or self.route_decision
                  or self.plan_epoch is not None) else None),
        }


class JobRegistry:
    """Registry of live jobs + a bounded ring of terminal ones.

    Single-event-loop discipline (like the orchestrator's other state):
    every mutation happens on the loop, so no lock is needed.
    """

    def __init__(self, metrics=None, terminal_ring: int = DEFAULT_TERMINAL_RING,
                 logger=None, recorder_events: int = DEFAULT_EVENT_LIMIT,
                 worker_id: Optional[str] = None, journal=None,
                 hop_ledger: bool = True):
        self.metrics = metrics
        self.logger = logger
        self.worker_id = worker_id
        # per-hop transfer attribution (``obs.hop_ledger``, default on):
        # False hands records no ledger, so every note_hop is a no-op
        self.hop_ledger = bool(hop_ledger)
        # crash-safe durability (control/journal.py): every register/
        # transition appends one journal line, so a killed worker's
        # replacement can replay the lifecycle it lost.  None = the
        # exact pre-journal in-memory-only registry.
        self.journal = journal
        self.recorder_events = max(int(recorder_events), 1)
        self.terminal_ring = max(int(terminal_ring), 0)
        self._active: "collections.OrderedDict[int, JobRecord]" = (
            collections.OrderedDict()
        )
        self._ring: Deque[JobRecord] = collections.deque()
        self._seq = itertools.count(1)

    # -- metrics helpers -----------------------------------------------
    def _gauge(self, state: str, delta: int) -> None:
        if self.metrics is not None:
            self.metrics.jobs_by_state.labels(state=state).inc(delta)

    # -- lifecycle ------------------------------------------------------
    def register(self, job_id: str, file_id: str,
                 priority: str = "NORMAL", tenant: str = "default",
                 ttl_seconds: float = 0.0,
                 recovered_at: str = "") -> JobRecord:
        """Open a record at delivery receipt (state RECEIVED).

        ``recovered_at`` is set only by startup reconciliation when it
        re-opens a boot placeholder: carried on the journal ``open``
        line so the placeholder-retirement clock (when its redelivery
        never arrives) survives any number of restarts instead of
        resetting with each boot's re-registration.
        """
        record = JobRecord(next(self._seq), job_id, file_id, priority,
                           recorder_events=self.recorder_events,
                           worker_id=self.worker_id,
                           tenant=tenant, ttl_seconds=ttl_seconds,
                           hop_ledger=self.hop_ledger)
        # a redelivery (park-then-nack leaves a FAILED terminal record
        # behind) inherits the job's cumulative fleet lease wait, so
        # fleet.max_wait bounds TOTAL parked time under a flapping
        # coordination store instead of resetting on every re-park.  A
        # DONE/CANCELLED prior is a genuine resubmission: fresh budget.
        prior = self.get(job_id)
        if prior is not None and prior.state in (FAILED, PARKED):
            record.fleet_waited_s = prior.fleet_waited_s
        self._active[record.uid] = record
        self._gauge(RECEIVED, +1)
        record.event("received", priority=priority)
        if self.journal is not None:
            fields = dict(fileId=file_id, priority=priority,
                          tenant=tenant, ttl=ttl_seconds)
            if recovered_at:
                fields["recoveredAt"] = recovered_at
            self.journal.append("open", job_id, **fields)
        return record

    def adopt_recovered(self, job_id: str, file_id: str,
                        priority: str = "NORMAL",
                        tenant: str = "default",
                        ttl_seconds: float = 0.0) -> Optional[JobRecord]:
        """Hand a crash-recovery placeholder to its arriving redelivery.

        A placeholder is a live PARKED record the startup reconciliation
        opened from the journal (``recovered`` flag set, reason
        ``recovered: ...``).  The redelivery re-enters the normal intake
        path with the SAME record — and crucially the same cancel token,
        so an operator cancel fired during the replay window settles the
        redelivery the moment it arrives.  Identity fields are refreshed
        from the delivery (the journal's copy may predate a producer-side
        change).  Returns None when no placeholder is waiting.
        """
        placeholder = None
        for record in self._active.values():
            if (record.job_id == job_id and record.recovered
                    and record.state == PARKED
                    and (record.reason or "").startswith("recovered")):
                placeholder = record
        if placeholder is None:
            return None
        placeholder.file_id = file_id
        placeholder.priority = priority
        placeholder.tenant = tenant
        placeholder.ttl_seconds = float(ttl_seconds or 0.0)
        placeholder.deadline_mono = (
            time.monotonic() + placeholder.ttl_seconds
            if placeholder.ttl_seconds > 0 else None
        )
        if self.journal is not None:
            # journal the refreshed identity too: a crash after adoption
            # must replay the delivery's fields, not the stale pre-crash
            # open line (an open on a live job keeps its poison counter)
            self.journal.append("open", job_id, fileId=file_id,
                                priority=priority, tenant=tenant,
                                ttl=ttl_seconds)
        self.transition(placeholder, RECEIVED,
                        reason="recovered: redelivery arrived")
        placeholder.event("redelivered_after_recovery")
        return placeholder

    def transition(self, record: JobRecord, state: str,
                   stage: Optional[str] = None,
                   reason: Optional[str] = None) -> JobRecord:
        """Move ``record`` to ``state``; illegal moves raise."""
        if state not in LEGAL_TRANSITIONS:
            raise IllegalTransition(f"unknown state {state!r}")
        if state not in LEGAL_TRANSITIONS[record.state]:
            raise IllegalTransition(
                f"job {record.job_id}: {record.state} -> {state} is not a "
                f"legal lifecycle transition"
            )
        now = time.monotonic()
        stage_closed = None
        # close the timing of the stage (or state) being left
        if record.state == RUNNING and record.stage:
            stage_closed = round(now - record._entered_mono, 6)
            record.stage_seconds[record.stage] = (
                record.stage_seconds.get(record.stage, 0.0)
                + (now - record._entered_mono)
            )
        if self.metrics is not None:
            self.metrics.job_state_transitions.labels(
                from_state=record.state, to_state=state
            ).inc()
        event_fields: Dict[str, Any] = {"from": record.state, "to": state}
        if stage_closed is not None:
            # the CLOSED stage rides its own key: on a RUNNING->RUNNING
            # stage hop, "stage" below names the stage being ENTERED, and
            # the closed stage's timing must not be attributed to it
            event_fields["stage_closed"] = record.stage
            event_fields["stage_s"] = stage_closed
        self._gauge(record.state, -1)
        self._gauge(state, +1)
        record.state = state
        if state == RUNNING:
            record.stage = stage
            event_fields["stage"] = stage
        # non-RUNNING states keep the last stage entered: a terminal
        # record should still say which stage the job died/cancelled in
        if reason is not None:
            record.reason = reason
            event_fields["reason"] = reason
        record.updated_at = _utcnow_iso()
        record._entered_mono = now
        record.event("state", **event_fields)
        if self.journal is not None:
            self.journal.append("state", record.job_id, state=state,
                                stage=record.stage, reason=reason)
        if state in TERMINAL_STATES:
            self._retire(record)
        return record

    def _retire(self, record: JobRecord) -> None:
        if record.hops is not None and record.hops:
            # the job's byte/time attribution, sealed into the timeline
            # at settle (one event) and into the fleet-wide
            # hop_seconds_per_gb/hop_bytes metrics — where this
            # gigabyte's wall time actually went, per hop
            record.event("hop_ledger", hops=record.hops.summary())
            if self.metrics is not None:
                record.hops.observe(self.metrics)
        if (record.state in (FAILED, DROPPED_POISON)
                and self.logger is not None):
            # terminal debug bundle: the timeline's tail + correlation
            # ids, in one log line — a dead job stays diagnosable after
            # the terminal ring evicts its record
            self.logger.warn(
                "job debug bundle", jobId=record.job_id, state=record.state,
                reason=record.reason, stage=record.stage,
                traceId=record.trace_id, spanId=record.span_id,
                bytes=dict(record.bytes),
                eventsDropped=record.recorder.dropped,
                events=record.recorder.tail(DEBUG_BUNDLE_EVENTS),
            )
        if record.recorder.dropped and self.metrics is not None:
            # growth-pressure signal: how much per-job timeline the
            # bounded event rings shed (counted once, at settle — the
            # recorder's own drop counter is per-job and dies with it)
            self.metrics.recorder_ring_evictions.inc(
                record.recorder.dropped)
        self._active.pop(record.uid, None)
        self._ring.append(record)
        while len(self._ring) > self.terminal_ring:
            evicted = self._ring.popleft()
            # the gauge counts records the registry still knows about
            self._gauge(evicted.state, -1)
        if self.journal is not None:
            # amortized growth bound: only ever checked when a job ends
            # (one stat), and the rewrite itself runs off-thread so a
            # loop-side settle never pays the replay + fsyncs
            self.journal.maybe_compact_async()

    # -- control --------------------------------------------------------
    def cancel(self, job_id: str, reason: str = "operator") -> List[JobRecord]:
        """Fire the cancel token of every live record for ``job_id``.

        Returns the records whose tokens fired (empty when the job is
        unknown or already terminal).  The *state* moves to CANCELLED
        only when the job actually settles — cancellation is
        cooperative, and the record must reflect reality.
        """
        fired = []
        for record in self._active.values():
            if record.job_id == job_id and record.cancel.cancel(reason):
                record.updated_at = _utcnow_iso()
                record.event("cancel_requested", reason=reason)
                fired.append(record)
        if fired and self.logger is not None:
            self.logger.info("job cancellation requested",
                             jobId=job_id, reason=reason)
        return fired

    # -- introspection --------------------------------------------------
    def get(self, job_id: str) -> Optional[JobRecord]:
        """Most recent record for ``job_id``: live first, then the ring."""
        latest = None
        for record in self._active.values():
            if record.job_id == job_id:
                latest = record
        if latest is not None:
            return latest
        for record in reversed(self._ring):
            if record.job_id == job_id:
                return record
        return None

    def jobs(self, state: Optional[str] = None) -> List[JobRecord]:
        """All known records, live before terminal, newest last."""
        out = list(self._active.values()) + list(self._ring)
        if state:
            out = [r for r in out if r.state == state]
        return out

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for record in self.jobs():
            out[record.state] = out.get(record.state, 0) + 1
        return out

    def queued_snapshot(self) -> "tuple[int, float]":
        """``(depth, oldest_age_seconds)`` over jobs accepted but not
        yet running (RECEIVED / PARKED / ADMITTED) — the autoscale
        signal pair: how much work is waiting and for how long.

        Jobs parked MID-RUN waiting out a peer worker's content lease
        (fleet/plane.py) are excluded: they are coalescing by design,
        not capacity starvation, and counting them would tell an
        autoscaler to add workers that could only join the same wait.
        """
        depth = 0
        oldest = 0.0
        now = time.monotonic()
        for record in self._queued_records():
            depth += 1
            oldest = max(oldest, now - record._created_mono)
        return depth, oldest

    def _queued_records(self):
        """Records accepted but not yet running — the ONE copy of the
        queued predicate both :meth:`queued_snapshot` and
        :meth:`tenant_queue_depths` apply (so the per-tenant gauges can
        never desynchronize from the queue_depth they break down)."""
        for record in self._active.values():
            if record.state not in (RECEIVED, PARKED, ADMITTED):
                continue
            if (record.state == PARKED and record.reason
                    and record.reason.startswith("fleet_lease_wait")):
                continue
            yield record

    def tenant_queue_depths(self) -> Dict[str, int]:
        """Queued-not-yet-running depth per tenant — the per-tenant
        breakdown of :meth:`queued_snapshot`'s depth (same predicate by
        construction), feeding the ``tenant_queue_depth`` gauges and
        ``GET /v1/tenants``."""
        out: Dict[str, int] = {}
        for record in self._queued_records():
            out[record.tenant] = out.get(record.tenant, 0) + 1
        return out
