"""Saturation-aware overload control: shed BULK work before thrashing.

The dependency breakers (PR 5) shed when *someone else* is down; nothing
shed when *this worker* is drowning — a saturated worker kept accepting
BULK backlog until the disk-headroom gate or the poison guard started
killing healthy jobs.  :class:`OverloadController` closes that gap: it
samples the autoscale signal trio (``Orchestrator.autoscale_signals()``:
queue depth, oldest-queued age, cache disk headroom) plus the event-loop
lag the :class:`~..platform.obs.LoopLagMonitor` already measures, and
declares the worker *saturated* once any configured threshold has been
breached for ``overload.sustain`` consecutive samples (a single GC pause
or burst must not flip the switch).

While saturated, the orchestrator sheds **BULK** deliveries at admission
with PR 5's park-then-nack discipline — the delivery is parked briefly
(``overload.shed_backoff``) and nacked back to the broker, *never*
FAILED permanently and never charged against the poison budget, so the
work simply waits out the pressure (or lands on a less-loaded fleet
peer).  HIGH and NORMAL traffic keeps flowing: shedding exists to
protect it.  Sheds are attributed on
``jobs_shed_total{reason,tenant}``.

All thresholds default to *off* except event-loop lag (a worker whose
loop is seconds behind cannot serve anyone), so an unconfigured
deployment only sheds in a state where it previously thrashed:

    overload:
      enabled: true            # false removes the controller entirely
      interval: 1.0            # sampling cadence, seconds
      sustain: 3               # consecutive breached samples => saturated
      max_loop_lag: 1.5        # seconds; 0 disables the lag trigger
      min_headroom_bytes: 0    # shed when cache/download disk headroom
                               # falls below this (0 = disabled)
      max_queue_depth: 0       # shed when more jobs than this are queued
      max_oldest_seconds: 0    # shed when the oldest queued job is older
      shed_backoff: 5.0        # park before the shed nack, seconds
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, List, Optional

from ..platform.config import cfg_get

DEFAULT_INTERVAL = 1.0
DEFAULT_SUSTAIN = 3
DEFAULT_MAX_LOOP_LAG = 1.5
DEFAULT_SHED_BACKOFF = 5.0


class OverloadController:
    """Sampled saturation detector + BULK shed policy (module docstring)."""

    def __init__(
        self,
        signals_fn: Callable[[], dict],
        lag_fn: Callable[[], Optional[float]],
        *,
        interval: float = DEFAULT_INTERVAL,
        sustain: int = DEFAULT_SUSTAIN,
        max_loop_lag: float = DEFAULT_MAX_LOOP_LAG,
        min_headroom_bytes: int = 0,
        max_queue_depth: int = 0,
        max_oldest_seconds: float = 0.0,
        shed_backoff: float = DEFAULT_SHED_BACKOFF,
        metrics=None,
        logger=None,
    ):
        if interval <= 0:
            raise ValueError(f"overload.interval must be > 0, got {interval}")
        if sustain < 1:
            raise ValueError(f"overload.sustain must be >= 1, got {sustain}")
        self.signals_fn = signals_fn
        self.lag_fn = lag_fn
        self.interval = float(interval)
        self.sustain = int(sustain)
        self.max_loop_lag = float(max_loop_lag)
        self.min_headroom_bytes = int(min_headroom_bytes)
        self.max_queue_depth = int(max_queue_depth)
        self.max_oldest_seconds = float(max_oldest_seconds)
        self.shed_backoff = float(shed_backoff)
        self.metrics = metrics
        self.logger = logger
        self.saturated = False
        self.reasons: List[str] = []
        self.saturated_since: Optional[float] = None
        self._streak = 0
        self._task: Optional[asyncio.Task] = None
        self._last_signals: dict = {}

    # -- config ---------------------------------------------------------
    @classmethod
    def from_config(cls, config, signals_fn, lag_fn, *, metrics=None,
                    logger=None) -> Optional["OverloadController"]:
        """Build from ``overload.*``; None when explicitly disabled."""
        if not bool(cfg_get(config, "overload.enabled", True)):
            return None
        return cls(
            signals_fn, lag_fn,
            interval=float(cfg_get(config, "overload.interval",
                                   DEFAULT_INTERVAL)),
            sustain=int(cfg_get(config, "overload.sustain",
                                DEFAULT_SUSTAIN)),
            max_loop_lag=float(cfg_get(config, "overload.max_loop_lag",
                                       DEFAULT_MAX_LOOP_LAG)),
            min_headroom_bytes=int(cfg_get(
                config, "overload.min_headroom_bytes", 0)),
            max_queue_depth=int(cfg_get(
                config, "overload.max_queue_depth", 0)),
            max_oldest_seconds=float(cfg_get(
                config, "overload.max_oldest_seconds", 0.0)),
            shed_backoff=float(cfg_get(config, "overload.shed_backoff",
                                       DEFAULT_SHED_BACKOFF)),
            metrics=metrics, logger=logger,
        )

    # -- sampling -------------------------------------------------------
    def sample(self) -> bool:
        """Take one pressure sample; returns the (possibly new)
        saturated verdict.  Exposed for tests and for callers that want
        an on-demand reading between timer ticks."""
        reasons: List[str] = []
        try:
            signals = dict(self.signals_fn())
        except Exception as err:  # a broken probe must not kill the loop
            if self.logger is not None:
                self.logger.warn("overload signal probe failed",
                                 error=str(err)[:200])
            signals = {}
        lag = None
        try:
            lag = self.lag_fn()
        except Exception:
            pass
        signals["loop_lag_seconds"] = lag
        self._last_signals = signals
        if self.max_loop_lag > 0 and lag is not None \
                and lag >= self.max_loop_lag:
            reasons.append("loop_lag")
        headroom = signals.get("cache_headroom_bytes")
        if self.min_headroom_bytes > 0 and headroom is not None \
                and headroom < self.min_headroom_bytes:
            reasons.append("disk_headroom")
        depth = signals.get("queue_depth")
        if self.max_queue_depth > 0 and depth is not None \
                and depth > self.max_queue_depth:
            reasons.append("queue_depth")
        oldest = signals.get("oldest_queued_seconds")
        if self.max_oldest_seconds > 0 and oldest is not None \
                and oldest > self.max_oldest_seconds:
            reasons.append("queue_age")
        self._streak = self._streak + 1 if reasons else 0
        was = self.saturated
        self.saturated = self._streak >= self.sustain
        if self.saturated:
            self.reasons = reasons
            if not was:
                self.saturated_since = time.monotonic()
                if self.logger is not None:
                    self.logger.warn(
                        "worker saturated: shedding BULK work",
                        reasons=reasons, signals=signals,
                    )
        else:
            self.reasons = []
            if was:
                self.saturated_since = None
                if self.logger is not None:
                    self.logger.info("worker pressure cleared, "
                                     "BULK intake restored")
        if self.metrics is not None:
            self.metrics.overload_saturated.set(1.0 if self.saturated
                                                else 0.0)
        return self.saturated

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            self.sample()

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(
                self._loop(), name="overload-controller"
            )

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- policy ---------------------------------------------------------
    def should_shed(self, priority: str) -> Optional[str]:
        """The shed reason when this delivery should be bounced, else
        None.  Only BULK is sheddable — the controller exists to protect
        HIGH/NORMAL time-to-staged, not to ration it."""
        if not self.saturated or priority != "BULK":
            return None
        return self.reasons[0] if self.reasons else "saturated"

    # -- introspection --------------------------------------------------
    def snapshot(self) -> dict:
        """JSON state for ``GET /v1/tenants`` and ``/readyz``."""
        return {
            "saturated": self.saturated,
            "reasons": list(self.reasons),
            "saturatedForSeconds": (
                round(time.monotonic() - self.saturated_since, 3)
                if self.saturated_since is not None else None
            ),
            "signals": dict(self._last_signals),
            "thresholds": {
                "maxLoopLag": self.max_loop_lag or None,
                "minHeadroomBytes": self.min_headroom_bytes or None,
                "maxQueueDepth": self.max_queue_depth or None,
                "maxOldestSeconds": self.max_oldest_seconds or None,
            },
            "sustain": self.sustain,
            "interval": self.interval,
        }
