"""In-process SLO accounting: burn rates, error budgets, hop budgets.

The service's whole job is meeting a staging deadline for the
downstream converter, and since PR 13 the repo can *measure* that SLO —
but only inside the soak harness, after the fact.  This module is the
standing, in-production half (ISSUE 15 tentpole piece 1):

- **Objectives** come from config (``slo.objectives.<class>.p99_ms`` +
  ``.availability``), keyed by priority class.  A key matching a
  configured tenant name creates a tenant-scoped objective too, so a
  vip tenant can carry a tighter target than its class.
- **Every settled delivery** is classified at the single settle seam
  the orchestrator already funnels through (``_journal_settle``):
  an acked ``done``/``staged_elsewhere`` inside its objective's target
  latency is *good*; an acked failure (permanent, poison, stalled,
  deadline) or a latency breach is *bad*; nacks are redelivery
  attempts, not resolutions, and cancels are operator actions —
  neither burns budget.  A bad resolution stamps an ``slo_breach``
  flight-recorder event on the job before it retires, so the breach
  rides the timeline, the debug bundle, and the fleet trace digest.
- **Multi-window burn rates** (the SRE alerting math): per objective,
  ``burn = bad_fraction(window) / (1 - availability)`` over a fast
  (~5 m) and a slow (~1 h) window — burn 1.0 spends the budget exactly
  at the allowed rate; 14x on both windows is the classic page.
  Tracked on the monotonic clock in one bounded ring per objective
  (the PR 14 slow-call-ring discipline: ``slo.max_events`` caps
  memory no matter the job rate), scanned only at scrape/snapshot
  time behind a short memo.
- **Exports**: ``slo_burn_rate{class,window}`` +
  ``slo_error_budget_remaining{class}`` gauges, the ``slo`` block on
  ``/readyz``, and the compact digest the fleet heartbeat carries so
  the elected sweeper can aggregate a fleet-wide view
  (fleet/plane.py ``build_overview``).

Percentile math is shared WITH the soak harness (soak/slo.py imports
:func:`percentile` from here), so ``make soak`` and the production
``/readyz`` block report the same statistic by construction.

**Per-hop regression budgets** (tentpole piece 3) live here too:
:func:`evaluate_hop_budgets` asserts a measured per-hop
``seconds_per_gb`` summary against the checked-in calibration baseline
(BASELINE_HOPS.json), failing with the guilty hop named — the ratchet
ROADMAP item 2's zero-copy work lands against (bench.py v20 ``--slo``).
"""

from __future__ import annotations

import collections
import math
import time
from typing import Any, Dict, List, Optional, Sequence

from ..platform.config import cfg_get

# objective classes always tracked (JobPriority enum names); unknown
# priorities resolve to NORMAL, the control plane's usual posture
PRIORITY_CLASSES = ("HIGH", "NORMAL", "BULK")

# workload classes: orthogonal to priority — a job that exercised a
# chip-bound subsystem (record.workload, stamped by the stage) ALSO
# counts against that subsystem's objective, so compute is a
# first-class worker class on the same burn-rate plane as downloads
WORKLOAD_CLASSES = ("UPSCALE",)

# default per-class objectives: p99 time-to-staged target (ms) and
# availability target.  Sized like the soak ceilings: interactive HIGH
# work is the tight one, BULK is deliberately loose (it is the class
# the overload layer sheds by design).
DEFAULT_OBJECTIVES: Dict[str, "tuple[float, float]"] = {
    "HIGH": (30_000.0, 0.999),
    "NORMAL": (60_000.0, 0.999),
    "BULK": (300_000.0, 0.99),
}

# upscale jobs decode + infer + encode whole videos: minutes-scale by
# nature, and a faulted compute seam should page well before the
# generic availability floor would
DEFAULT_WORKLOAD_OBJECTIVES: Dict[str, "tuple[float, float]"] = {
    "UPSCALE": (120_000.0, 0.99),
}

DEFAULT_FAST_WINDOW = 300.0      # ~5 m: the page-fast window
DEFAULT_SLOW_WINDOW = 3600.0     # ~1 h: the page-slow window
DEFAULT_BUDGET_WINDOW = 86400.0  # error budget accounted over a day
# bounded per-objective event ring (the PR 14 slow-call-ring posture):
# at 10 jobs/s one objective still holds ~14 min of history
DEFAULT_MAX_EVENTS = 8192
# snapshot memo: /metrics + /readyz + heartbeat digest share one scan
SNAPSHOT_MEMO_S = 0.5

# settle whys that are a SUCCESSFUL resolution (good iff inside target)
_GOOD_WHYS = frozenset({"done", "staged_elsewhere"})
# whys excluded from the SLO entirely: operator actions, not service
# failures (a cancel is the submitter changing their mind)
_EXCLUDED_WHYS = frozenset({"cancelled"})

# per-GB observations below this weight are noise — the same floor the
# HopLedger applies (platform/obs.py MIN_OBSERVE_BYTES)
_MIN_HOP_BYTES = 1 << 20


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in 0..100); 0.0 on empty input.

    THE percentile used repo-wide: the soak harness (soak/slo.py), the
    live ``/readyz`` SLO block, and bench v20's hop-budget calibration
    all call this one function, so their numbers agree by construction.
    """
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(int(math.ceil(q / 100.0 * len(ordered))) - 1, 0)
    return float(ordered[min(rank, len(ordered) - 1)])


class Objective:
    """One SLO: a latency target + an availability target."""

    __slots__ = ("name", "p99_ms", "availability")

    def __init__(self, name: str, p99_ms: float, availability: float):
        if not 0.0 < availability < 1.0:
            raise ValueError(
                f"slo.objectives.{name}.availability must be in (0, 1), "
                f"got {availability!r}")
        if p99_ms <= 0:
            raise ValueError(
                f"slo.objectives.{name}.p99_ms must be > 0, "
                f"got {p99_ms!r}")
        self.name = name
        self.p99_ms = float(p99_ms)
        self.availability = float(availability)

    @property
    def budget_fraction(self) -> float:
        """The fraction of resolutions allowed to be bad (1 - avail)."""
        return 1.0 - self.availability


class _Series:
    """One objective's bounded event ring: ``(mono_t, good, latency_s)``."""

    __slots__ = ("ring", "good_total", "bad_total")

    def __init__(self, max_events: int):
        self.ring: "collections.deque[tuple]" = collections.deque(
            maxlen=max(int(max_events), 16))
        self.good_total = 0
        self.bad_total = 0

    def add(self, now: float, good: bool, latency_s: float) -> None:
        self.ring.append((now, good, latency_s))
        if good:
            self.good_total += 1
        else:
            self.bad_total += 1

    def window_counts(self, now: float,
                      window_s: float) -> "tuple[int, int]":
        """``(good, bad)`` inside the window.  The ring is time-ordered,
        so scan from the newest end and stop at the horizon."""
        horizon = now - window_s
        good = bad = 0
        for t, ok, _lat in reversed(self.ring):
            if t < horizon:
                break
            if ok:
                good += 1
            else:
                bad += 1
        return good, bad

    def window_latencies(self, now: float,
                         window_s: float) -> List[float]:
        horizon = now - window_s
        out = []
        for t, _ok, lat in reversed(self.ring):
            if t < horizon:
                break
            out.append(lat)
        return out


class SloTracker:
    """Live SLO accounting for one worker (see module docstring).

    Cheap by construction: :meth:`note_settle` is a deque append plus a
    handful of dict adds (the ``slo_overhead_ms`` bench guard keeps it
    under 1 ms/job); all window math happens at snapshot time, behind a
    short memo, over bounded rings.
    """

    def __init__(self, objectives: Dict[str, Objective], *,
                 fast_window: float = DEFAULT_FAST_WINDOW,
                 slow_window: float = DEFAULT_SLOW_WINDOW,
                 budget_window: float = DEFAULT_BUDGET_WINDOW,
                 max_events: int = DEFAULT_MAX_EVENTS,
                 tenant_objectives: Optional[Dict[str, Objective]] = None,
                 workload_objectives: Optional[Dict[str, Objective]] = None,
                 clock=time.monotonic):
        self.objectives = dict(objectives)
        # tenant-scoped objectives: fed ALONGSIDE the class objective
        # (a vip job counts against both vip's target and HIGH's)
        self.tenant_objectives = dict(tenant_objectives or {})
        # workload-scoped objectives (UPSCALE): fed alongside too, keyed
        # by record.workload — chips get their own burn rate
        self.workload_objectives = dict(workload_objectives or {})
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.budget_window = float(budget_window)
        self.clock = clock
        self._series: Dict[str, _Series] = {
            name: _Series(max_events)
            for name in (list(self.objectives)
                         + list(self.tenant_objectives)
                         + list(self.workload_objectives))
        }
        # cumulative per-hop totals + stage wall across settled jobs:
        # the live (mixed-traffic) attribution the fleet digest carries
        # — topHops by seconds-per-GB plus the hop/stage reconcile
        # ratio the soak leaves unguarded by design
        # (``hop_reconcile_ratio_mixed``: here it is at least VISIBLE)
        self._hop_totals: Dict[str, list] = {}
        self._stage_seconds_total = 0.0
        self._memo = {"at": -1e9, "snap": None}

    # -- config ---------------------------------------------------------
    @classmethod
    def from_config(cls, config,
                    tenant_names: Sequence[str] = ()
                    ) -> Optional["SloTracker"]:
        """Build from ``slo.*`` (None when ``slo.enabled`` is false).

        Objectives: every priority class gets a default objective,
        overridable via ``slo.objectives.<class>.p99_ms`` /
        ``slo.objectives.<class>.availability``.  An objectives key
        matching a configured tenant name (the ``tenants`` table)
        creates a tenant-scoped objective with the same knobs.
        """
        if not bool(cfg_get(config, "slo.enabled", True)):
            return None

        def objective(name: str, default_p99: float,
                      default_avail: float) -> Objective:
            return Objective(
                name,
                float(cfg_get(config, f"slo.objectives.{name}.p99_ms",
                              default_p99)),
                float(cfg_get(config,
                              f"slo.objectives.{name}.availability",
                              default_avail)),
            )

        objectives = {
            name: objective(name, p99, avail)
            for name, (p99, avail) in DEFAULT_OBJECTIVES.items()
        }
        workload_objectives = {
            name: objective(name, p99, avail)
            for name, (p99, avail) in DEFAULT_WORKLOAD_OBJECTIVES.items()
        }
        tenant_objectives: Dict[str, Objective] = {}
        configured = cfg_get(config, "slo.objectives", None)
        for name in list(configured) if configured is not None else []:
            if name in objectives or name in workload_objectives:
                continue
            if name not in tenant_names:
                # neither a class nor a configured tenant: a typo'd key
                # must not silently track nothing
                raise ValueError(
                    f"slo.objectives.{name!r} is neither a priority "
                    f"class {PRIORITY_CLASSES}, a workload class "
                    f"{WORKLOAD_CLASSES}, nor a configured tenant")
            # tenant objectives default to NORMAL's bounds — the
            # RESOLVED ones, so a configured NORMAL override carries
            # into tenants that don't pin their own numbers
            base = objectives["NORMAL"]
            tenant_objectives[name] = objective(
                name, base.p99_ms, base.availability)
        return cls(
            objectives,
            tenant_objectives=tenant_objectives,
            workload_objectives=workload_objectives,
            fast_window=float(cfg_get(
                config, "slo.fast_window", DEFAULT_FAST_WINDOW)),
            slow_window=float(cfg_get(
                config, "slo.slow_window", DEFAULT_SLOW_WINDOW)),
            budget_window=float(cfg_get(
                config, "slo.budget_window", DEFAULT_BUDGET_WINDOW)),
            max_events=int(cfg_get(
                config, "slo.max_events", DEFAULT_MAX_EVENTS)),
        )

    # -- the settle seam -------------------------------------------------
    def resolve_class(self, priority: Optional[str]) -> str:
        return priority if priority in self.objectives else "NORMAL"

    def note_settle(self, record, mode: str, why: str) -> bool:
        """Classify one settled delivery (the orchestrator calls this
        from its single settle funnel, for every ack AND nack).

        Nacks are redelivery attempts — the job is not over — and
        cancels are operator decisions; neither is a resolution.
        Everything else resolves good (acked done/staged inside the
        latency target) or bad (acked failure, or a latency breach).
        Returns True when the resolution burned error budget (an
        ``slo_breach`` was stamped) — the incident plane's auto-export
        trigger (downloader_tpu/incident).
        """
        if mode != "ack" or why in _EXCLUDED_WHYS:
            return False
        now = self.clock()
        latency_s = max(
            now - getattr(record, "_created_mono", now), 0.0)
        cls = self.resolve_class(getattr(record, "priority", None))
        target = self.objectives[cls]
        succeeded = why in _GOOD_WHYS
        good = succeeded and latency_s * 1000.0 <= target.p99_ms
        self._series[cls].add(now, good, latency_s)
        tenant = getattr(record, "tenant", None)
        tenant_obj = self.tenant_objectives.get(tenant)
        if tenant_obj is not None:
            self._series[tenant].add(
                now,
                succeeded and latency_s * 1000.0 <= tenant_obj.p99_ms,
                latency_s)
        # workload class (UPSCALE): stamped by the stage that ran the
        # chip path, so compute burns its own budget alongside the
        # priority class's
        workload = getattr(record, "workload", None)
        workload_obj = self.workload_objectives.get(workload)
        if workload_obj is not None:
            self._series[workload].add(
                now,
                succeeded and latency_s * 1000.0 <= workload_obj.p99_ms,
                latency_s)
        if not good:
            # the breach rides the job's own timeline (and from there
            # the debug bundle + the fleet trace digest) BEFORE the
            # record retires — with the placement context in force
            # (route key, router decision, plan epoch: ISSUE 18), so a
            # bundle explains WHERE the job was when it burned
            try:
                record.event(
                    "slo_breach", objective=cls, why=why,
                    latency_ms=round(latency_s * 1000.0, 1),
                    target_ms=target.p99_ms,
                    breach=("availability" if not succeeded
                            else "latency"),
                    routeKey=getattr(record, "route_key", None),
                    routeDecision=getattr(record, "route_decision", None),
                    planEpoch=getattr(record, "plan_epoch", None))
            except Exception:
                pass  # accounting must never fail a settle
        # hop/stage accumulation for the fleet digest (mixed-traffic
        # attribution): two bounded dict walks per settled job
        hops = getattr(record, "hops", None)
        if hops is not None and hops:
            for hop, nbytes, seconds in hops.iter_hops():
                entry = self._hop_totals.get(hop)
                if entry is None:
                    self._hop_totals[hop] = [int(nbytes), float(seconds)]
                else:
                    entry[0] += int(nbytes)
                    entry[1] += seconds
        stage_seconds = getattr(record, "stage_seconds", None)
        if stage_seconds:
            self._stage_seconds_total += sum(stage_seconds.values())
        return not good

    # -- window math -----------------------------------------------------
    def burn_rate(self, name: str, window_s: float,
                  now: Optional[float] = None) -> float:
        """``bad_fraction(window) / budget_fraction`` — 1.0 spends the
        error budget exactly at the allowed rate; 0.0 with no events."""
        series = self._series.get(name)
        objective = (self.objectives.get(name)
                     or self.tenant_objectives.get(name)
                     or self.workload_objectives.get(name))
        if series is None or objective is None:
            return 0.0
        good, bad = series.window_counts(
            self.clock() if now is None else now, window_s)
        total = good + bad
        if total == 0:
            return 0.0
        return (bad / total) / objective.budget_fraction

    def budget_remaining(self, name: str,
                         now: Optional[float] = None) -> float:
        """Error budget left over the budget window, 1.0 (untouched) to
        0.0 (exhausted — clamped: spending PAST the budget still reads
        0, the actionable floor)."""
        series = self._series.get(name)
        objective = (self.objectives.get(name)
                     or self.tenant_objectives.get(name)
                     or self.workload_objectives.get(name))
        if series is None or objective is None:
            return 1.0
        good, bad = series.window_counts(
            self.clock() if now is None else now, self.budget_window)
        total = good + bad
        if total == 0:
            return 1.0
        allowed = total * objective.budget_fraction
        if allowed <= 0.0:
            return 0.0 if bad else 1.0
        return max(1.0 - bad / allowed, 0.0)

    # -- surfaces --------------------------------------------------------
    def objective_names(self) -> List[str]:
        return (list(self.objectives) + list(self.tenant_objectives)
                + list(self.workload_objectives))

    def snapshot(self) -> dict:
        """The ``/readyz`` ``slo`` block (memoized: /metrics, /readyz,
        and the heartbeat digest share one ring scan per half second)."""
        now = self.clock()
        memo = self._memo
        if memo["snap"] is not None and now - memo["at"] < SNAPSHOT_MEMO_S:
            return memo["snap"]
        out: Dict[str, Any] = {}
        for name in self.objective_names():
            objective = (self.objectives.get(name)
                         or self.tenant_objectives.get(name)
                         or self.workload_objectives[name])
            series = self._series[name]
            fast = self.burn_rate(name, self.fast_window, now)
            slow = self.burn_rate(name, self.slow_window, now)
            latencies = series.window_latencies(now, self.slow_window)
            entry = {
                "targetP99Ms": objective.p99_ms,
                "availability": objective.availability,
                "burnFast": round(fast, 3),
                "burnSlow": round(slow, 3),
                "budgetRemaining": round(
                    self.budget_remaining(name, now), 4),
                "resolved": series.good_total + series.bad_total,
                "bad": series.bad_total,
                # the same nearest-rank percentile the soak reports
                "p99Ms": round(
                    percentile(latencies, 99.0) * 1000.0, 1),
                "p50Ms": round(
                    percentile(latencies, 50.0) * 1000.0, 1),
                # the classic multiwindow condition: burning on BOTH
                # windows means the breach is real and still happening
                "breached": fast > 1.0 and slow > 1.0,
            }
            out[name] = entry
        snap = {"objectives": out,
                "windows": {"fastS": self.fast_window,
                            "slowS": self.slow_window,
                            "budgetS": self.budget_window}}
        memo["snap"] = snap
        memo["at"] = now
        return snap

    def digest(self) -> dict:
        """The compact SLO block the fleet heartbeat carries (a few
        hundred bytes: burn/budget per objective + hop totals)."""
        snap = self.snapshot()
        hops = {
            hop: {"bytes": nbytes, "seconds": round(seconds, 3)}
            for hop, (nbytes, seconds) in sorted(
                self._hop_totals.items())
        }
        hop_seconds = sum(v[1] for v in self._hop_totals.values())
        stage_seconds = self._stage_seconds_total
        return {
            "burn": {name: {"fast": entry["burnFast"],
                            "slow": entry["burnSlow"]}
                     for name, entry in snap["objectives"].items()},
            "budget": {name: entry["budgetRemaining"]
                       for name, entry in snap["objectives"].items()},
            "breached": sorted(
                name for name, entry in snap["objectives"].items()
                if entry["breached"]),
            "hops": hops,
            "hopSeconds": round(hop_seconds, 3),
            "stageSeconds": round(stage_seconds, 3),
            # mixed-phase attribution ratio (soak stat
            # ``hop_reconcile_ratio_mixed``): unguarded by design —
            # concurrent jobs inflate each other's wall — but visible,
            # so attribution DRIFT at least shows on the overview
            "hopReconcileRatio": round(
                hop_seconds / stage_seconds, 4) if stage_seconds > 0
            else None,
        }


def top_hops(hop_totals: Dict[str, dict], count: int = 3) -> List[dict]:
    """The ``count`` worst hops by seconds-per-GB from ``{hop:
    {bytes, seconds}}`` totals — only hops that moved enough bytes for
    the rate to mean anything (the HopLedger floor)."""
    rows = []
    for hop, entry in hop_totals.items():
        nbytes = int(entry.get("bytes", 0) or 0)
        seconds = float(entry.get("seconds", 0.0) or 0.0)
        if nbytes < _MIN_HOP_BYTES:
            continue
        rows.append({
            "hop": hop,
            "secondsPerGb": round(seconds / (nbytes / 1e9), 3),
            "bytes": nbytes,
        })
    rows.sort(key=lambda r: -r["secondsPerGb"])
    return rows[:count]


# -- per-hop regression budgets (BASELINE_HOPS.json) --------------------

def evaluate_hop_budgets(measured: Dict[str, float],
                         baseline: dict) -> "tuple[bool, List[str]]":
    """Assert measured per-hop ``seconds_per_gb`` against the
    calibration baseline's budgets.

    ``measured``: ``{hop: seconds_per_gb}`` from a calibration-shaped
    run (bench v20 ``--slo`` measures the same workload the baseline
    was calibrated on).  ``baseline``: the parsed BASELINE_HOPS.json —
    ``{"hops": {hop: {"budget_s_per_gb": ...}}}``.

    Returns ``(ok, failures)`` where each failure NAMES the guilty hop
    — the whole point: a cpu_s_per_gb regression arrives with the hop
    that caused it, not as an aggregate vibe.  A baseline hop missing
    from the measurement fails too (a renamed/dropped hop is attribution
    drift, not a win).
    """
    failures: List[str] = []
    budgets = baseline.get("hops", {})
    for hop in sorted(budgets):
        budget = float(budgets[hop].get("budget_s_per_gb", 0.0) or 0.0)
        if budget <= 0:
            continue
        got = measured.get(hop)
        if got is None:
            failures.append(
                f"hop '{hop}' missing from the measured ledger "
                f"(baseline expects <= {budget:g} s/GB) — attribution "
                "drift or a renamed hop")
            continue
        if got > budget:
            failures.append(
                f"hop '{hop}' spent {got:.3f} s/GB, budget "
                f"{budget:g} s/GB (baseline p99 "
                f"{budgets[hop].get('p99_s_per_gb', '?')}) — this hop "
                "is the regression")
    return not failures, failures


def hop_budget_baseline(samples: Dict[str, List[float]],
                        headroom: float = 4.0) -> dict:
    """Build the BASELINE_HOPS.json ``hops`` payload from calibration
    samples: ``{hop: [seconds_per_gb, ...]}`` over repeated runs.

    ``budget_s_per_gb`` = p99 x ``headroom``: wide enough that CI-host
    noise never trips it, tight enough that a hop doubling its cost
    (the regressions ROADMAP item 2 hunts) fails naming the hop.
    """
    hops = {}
    for hop, values in sorted(samples.items()):
        if not values:
            continue
        p50 = percentile(values, 50.0)
        p99 = percentile(values, 99.0)
        hops[hop] = {
            "p50_s_per_gb": round(p50, 4),
            "p99_s_per_gb": round(p99, 4),
            "budget_s_per_gb": round(p99 * headroom, 4),
            "samples": len(values),
        }
    return {"headroom": headroom, "hops": hops}
