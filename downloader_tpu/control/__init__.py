"""Control plane: job registry, lifecycle API, cooperative cancellation,
and priority-class start scheduling.

The reference worker is fire-and-forget (the only intervention is killing
the process, /root/reference/lib/main.js:174-204); this package gives
operators and the downstream converter steering:

- :mod:`.registry` — every delivery tracked through a validated state
  machine from receipt to a terminal state, with a bounded ring of
  finished records for post-hoc inspection.
- :mod:`.cancel` — a cooperative :class:`CancelToken` carried in every
  job's ``StageContext``, checked at the stages' chunk loops and by the
  torrent client between piece batches.
- :mod:`.api` — ``/v1/jobs``, cancel, intake pause/resume, and drain
  endpoints mounted on the health app.
- :mod:`.scheduler` — priority-class (HIGH/NORMAL/BULK) start ordering
  over the concurrency slots, with a starvation-proof aging bump.
"""

from .cancel import CancelToken, JobCancelled
from .registry import (
    ADMITTED,
    CANCELLED,
    DONE,
    DROPPED_POISON,
    EXPIRED,
    FAILED,
    PARKED,
    PUBLISHING,
    RECEIVED,
    RUNNING,
    TERMINAL_STATES,
    IllegalTransition,
    JobRecord,
    JobRegistry,
)
from .scheduler import (
    PRIORITY_RANK,
    PriorityScheduler,
    priority_name,
    priority_rank,
)

__all__ = [
    "ADMITTED", "CANCELLED", "DONE", "DROPPED_POISON", "EXPIRED",
    "FAILED", "PARKED", "PUBLISHING", "RECEIVED", "RUNNING",
    "TERMINAL_STATES", "PRIORITY_RANK",
    "CancelToken", "IllegalTransition", "JobCancelled", "JobRecord",
    "JobRegistry", "PriorityScheduler", "priority_name", "priority_rank",
]
