"""Cross-worker trace assembly: one timeline for one trace id.

PR 3 gave every job a W3C trace id that joins its log lines, OTLP span,
and flight-recorder timeline — *inside one worker*.  PR 6 made the
system a fleet, and the trace stopped dead at the worker boundary: a
lease waiter's timeline showed only ``fleet_lease_wait`` while the fetch
it was actually waiting on ran (invisibly) on the leader.  This module
is the join:

- **Local segments** — every registry record (live + terminal ring)
  carrying the trace id, with its full event timeline and hop ledger.
- **Digest segments** — other workers' per-job digests published to the
  coordination store at ``telemetry/<trace_id>/<worker_id>/<job_id>``
  (fleet/plane.py, written at settle, GC'd after
  ``fleet.telemetry_ttl``).
- **Linked traces** — a waiter's ``fleet`` wait event names the leader
  job's trace id (carried on the lease document); the assembler follows
  those links so the leader's origin fetch appears in the waiter's
  assembled view, attributed to the leader's worker.
- **Live peers** — workers advertising an ``adminUrl`` in their
  heartbeat are queried over ``GET /v1/trace/{id}?scope=local`` for
  still-running (not-yet-digested) segments.

Degradation contract (the PR 5/6 posture): coordination-store or peer
trouble can never fail the assembly — the response downgrades to
whatever was reachable, flags ``degraded: true``, and lists the errors.
A local-only view is always available.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional

# bound on lease-leader trace links followed per assembly (a waiter has
# at most one leader per content key; this caps pathological fan-out)
MAX_LINKED_TRACES = 8
# per-peer admin-API budget: trace assembly is an operator read, but it
# must never hang behind one wedged peer
PEER_TIMEOUT = 5.0


def _segment_from_record(record, worker_id: Optional[str]) -> dict:
    hops = getattr(record, "hops", None)
    return {
        "workerId": record.worker_id or worker_id,
        "jobId": record.job_id,
        "traceId": record.trace_id,
        "spanId": record.span_id,
        "state": record.state,
        "stage": record.stage,
        "stageSeconds": {k: round(v, 3)
                         for k, v in record.stage_seconds.items()},
        "hopLedger": (hops.summary()
                      if hops is not None and hops else None),
        "events": record.recorder.events(),
        "source": "local",
    }


def local_segments(orchestrator, trace_id: str) -> List[dict]:
    """Segments this worker can answer for without any I/O."""
    registry = getattr(orchestrator, "registry", None)
    if registry is None:
        return []
    worker_id = getattr(orchestrator, "worker_id", None)
    return [
        _segment_from_record(record, worker_id)
        for record in registry.jobs()
        if record.trace_id == trace_id
    ]


def local_spans(orchestrator, trace_id: str) -> List[dict]:
    """Finished spans in the local tracer buffer for this trace."""
    tracer = getattr(orchestrator, "tracer", None)
    if tracer is None:
        return []
    try:
        spans = tracer.spans()
    except Exception:
        return []
    worker_id = getattr(orchestrator, "worker_id", None)
    out = []
    for span in spans:
        if span.trace_id != trace_id:
            continue
        doc = span.to_dict()
        doc["workerId"] = worker_id
        out.append(doc)
    return out


def linked_trace_ids(segments: List[dict]) -> Dict[str, str]:
    """Trace ids referenced by fleet wait / shared-origin events — the
    cross-trace links the assembler follows — mapped to the link label
    the merged segments are stamped with (``lease_leader`` /
    ``shared_origin``, naming the event field the link came from)."""
    out: Dict[str, str] = {}
    for segment in segments:
        for event in segment.get("events") or []:
            for field, label in (("leaderTraceId", "lease_leader"),
                                 ("originTraceId", "shared_origin")):
                linked = event.get(field)
                if linked and linked != segment.get("traceId") \
                        and linked not in out:
                    out[linked] = label
    return out


async def assemble(orchestrator, trace_id: str, *,
                   remote: bool = True) -> dict:
    """The ``GET /v1/trace/{id}`` document (see module docstring).

    ``remote=False`` (the ``?scope=local`` form peers use on each other)
    skips the coordination store and peer hops — no recursion, no
    cross-fleet amplification.
    """
    worker_id = getattr(orchestrator, "worker_id", None)
    segments = local_segments(orchestrator, trace_id)
    spans = local_spans(orchestrator, trace_id)
    errors: List[str] = []
    degraded = False
    fleet = getattr(orchestrator, "fleet", None)

    if remote and fleet is not None:
        seen = {(s.get("workerId"), s.get("jobId")) for s in segments}

        async def _merge_digests(tid: str, link: Optional[str]) -> None:
            nonlocal degraded
            try:
                digests = await fleet.fetch_telemetry(tid)
            except asyncio.CancelledError:
                raise
            except Exception as err:
                degraded = True
                errors.append(f"coord telemetry {tid[:8]}: {err}"[:200])
                return
            for doc in digests:
                key = (doc.get("workerId"), doc.get("jobId"))
                if key in seen:
                    continue  # local view wins over its own digest
                seen.add(key)
                segments.append({
                    "workerId": doc.get("workerId"),
                    "jobId": doc.get("jobId"),
                    "traceId": doc.get("traceId"),
                    "spanId": doc.get("spanId"),
                    "state": doc.get("state"),
                    "stage": doc.get("stage"),
                    "stageSeconds": doc.get("stageSeconds") or {},
                    "hopLedger": doc.get("hopLedger"),
                    "events": doc.get("events") or [],
                    "source": "digest",
                    **({"link": link} if link else {}),
                })

        await _merge_digests(trace_id, None)
        # follow lease-leader / shared-origin links discovered in the
        # segments so far: the waiter's view pulls in the leader's fetch
        linked_ids = list(
            linked_trace_ids(segments).items())[:MAX_LINKED_TRACES]
        for linked, label in linked_ids:
            await _merge_digests(linked, label)

        # live peers: segments for jobs still running (no digest yet).
        # Queried for the linked leader traces too — mid-incident the
        # leader's fetch has no digest (published only at settle), and
        # on the peer that fetch runs under ITS OWN trace id, so asking
        # only for ours would 404 and hide exactly the segment a parked
        # waiter's triage needs.
        peers: List[dict] = []
        try:
            peers = [
                w for w in await fleet.workers()
                if w.get("adminUrl") and w.get("workerId") != worker_id
            ]
        except asyncio.CancelledError:
            raise
        except Exception as err:
            degraded = True
            errors.append(f"coord workers: {err}"[:200])
        if peers:
            import aiohttp

            timeout = aiohttp.ClientTimeout(total=PEER_TIMEOUT)
            span_ids = {s.get("spanId") for s in spans}

            async def _ask_peer(session, peer, tid, link):
                url = peer["adminUrl"].rstrip("/") + f"/v1/trace/{tid}"
                try:
                    async with session.get(
                        url, params={"scope": "local"}
                    ) as resp:
                        if resp.status == 404:
                            return None  # peer knows nothing: fine
                        if resp.status != 200:
                            raise RuntimeError(f"HTTP {resp.status}")
                        return peer, link, await resp.json()
                except asyncio.CancelledError:
                    raise
                except Exception as err:
                    return peer, link, err

            async with aiohttp.ClientSession(timeout=timeout) as session:
                # concurrent: a wedged peer costs PEER_TIMEOUT once,
                # not once per peer per trace id
                answers = await asyncio.gather(*[
                    _ask_peer(session, peer, tid, link)
                    for peer in peers
                    for tid, link in [(trace_id, None)] + linked_ids
                ])
            for answer in answers:
                if answer is None:
                    continue
                peer, link, body = answer
                if isinstance(body, Exception):
                    degraded = True
                    errors.append(
                        f"peer {peer.get('workerId')}: {body}"[:200])
                    continue
                for segment in body.get("segments") or []:
                    key = (segment.get("workerId"), segment.get("jobId"))
                    if key in seen:
                        continue
                    seen.add(key)
                    segment = dict(segment)
                    segment["source"] = "peer"
                    if link:
                        segment["link"] = link
                    segments.append(segment)
                for span in body.get("spans") or []:
                    if span.get("spanId") in span_ids:
                        continue
                    span_ids.add(span.get("spanId"))
                    spans.append(span)

    workers: List[Any] = sorted(
        {s.get("workerId") for s in segments if s.get("workerId")}
    )
    return {
        "traceId": trace_id,
        "workerId": worker_id,
        "workers": workers,
        "segments": segments,
        "spans": spans,
        "degraded": degraded,
        "errors": errors,
    }


def merged_timeline(document: dict) -> List[dict]:
    """All segments' events in one wall-clock-ordered list, each stamped
    with its segment's worker/job identity (the ``cli trace show``
    rendering; also handy for tests)."""
    out: List[Dict[str, Any]] = []
    for segment in document.get("segments") or []:
        for event in segment.get("events") or []:
            row = dict(event)
            row.setdefault("workerId", segment.get("workerId"))
            row["jobId"] = segment.get("jobId")
            out.append(row)
    out.sort(key=lambda e: e.get("t") or 0)
    return out
