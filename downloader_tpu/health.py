"""HTTP surface: probes + metrics + the control plane's admin API.

``GET /health`` + ``/livez`` + ``/readyz`` + ``/metrics``, plus the
``/v1/jobs`` (list / show / events / cancel), intake, drain, and
``/debug/tasks`` / ``/debug/stacks`` endpoints from ``control/api.py``
mounted on the same app (one port for probes, metrics, operations, and
runtime introspection).

``/health`` has behavioral parity with /root/reference/lib/main.js:174-194,
including the reference's deliberate inverted semantics: a worker with zero
active jobs answers 500 ``Not Running Jobs`` (it is expected to always be
busy); otherwise 200 with ``{metadata: {success, host}, data: {active}}``.
Because the orchestrator here actually removes finished jobs (the reference's
``slice`` bug made ``activeJobs`` grow forever, lib/main.js:169), the
endpoint is now truthful — which makes it operationally wrong as a k8s
probe: an idle-but-healthy worker would be restarted.  So:

- ``/livez`` — 200 whenever the process can answer (liveness probe).
- ``/readyz`` — 200 while the orchestrator is connected and consuming,
  503 before start / after shutdown begins (readiness probe).
- ``health.sane: true`` in config flips ``/health`` itself to sane
  semantics (200 when idle, with the same payload shape); the default
  stays reference parity.

``/metrics`` exposes the Prometheus registry (reference ``Prom.expose()``,
lib/main.js:44).

Default port 3401, overridable via ``$PORT`` (reference lib/main.js:194).
"""

from __future__ import annotations

import os
import socket
from typing import Optional

from aiohttp import web

from .control.api import bind_control_routes
from .orchestrator import Orchestrator
from .platform.config import cfg_get
from .platform.metrics import Metrics

DEFAULT_PORT = 3401


def build_app(orchestrator: Orchestrator, metrics: Optional[Metrics] = None) -> web.Application:
    app = web.Application()
    sane = bool(
        cfg_get(getattr(orchestrator, "config", None), "health.sane", False)
    )

    def _payload(active: int) -> dict:
        return {
            "metadata": {"success": True, "host": socket.gethostname()},
            "data": {"active": active},
        }

    async def health(_request: web.Request) -> web.Response:
        active = len(orchestrator.active_jobs)
        if active == 0 and not sane:
            return web.json_response({"message": "Not Running Jobs"}, status=500)
        return web.json_response(_payload(active))

    async def livez(_request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})

    async def readyz(_request: web.Request) -> web.Response:
        if not orchestrator.consuming:
            return web.json_response({"status": "not consuming"}, status=503)
        if getattr(orchestrator, "intake_paused", False):
            # paused via POST /v1/intake/pause or /v1/drain: alive, but
            # deliberately not taking work — not ready
            return web.json_response(
                {"status": "paused", "active": len(orchestrator.active_jobs)},
                status=503,
            )
        # dependency circuit breakers (platform/errors.py): an open
        # staging-store/convert-publish breaker means new jobs park at
        # admission — tell load-aware orchestrators to route elsewhere
        # until the half-open probe restores service.  The payload always
        # carries the states, so the open -> half_open -> closed cycle is
        # observable here as well as on /metrics.
        breakers = getattr(orchestrator, "breakers", None)
        states = breakers.states() if breakers is not None else {}
        # open-reason attribution (failure vs slow): a slow-opened
        # breaker means the dependency is up but browned out — wait it
        # out and shed; a failure-opened one means check it is up at all
        reasons = (breakers.open_reasons()
                   if breakers is not None else {})
        # readiness keys on the ADMISSION dependencies only (store +
        # publish): an open per-job breaker someone opted into must not
        # pull the whole replica out of rotation
        blocked = (breakers.blocking_dependencies(
            getattr(orchestrator, "admission_dependencies", None))
            if breakers is not None else [])
        # live SLO posture (control/slo.py): burn rates per objective
        # and window, error budget remaining, current p50/p99 — the
        # same numbers as slo_burn_rate/slo_error_budget_remaining on
        # /metrics (one memoized snapshot feeds both).  Carried on the
        # 503 breaker body too: burn-rate triage (is the SLO actually
        # bleeding?) and breaker triage (which dependency, slow or
        # failed?) read off one probe.
        slo = getattr(orchestrator, "slo", None)
        slo_block = slo.snapshot() if slo is not None else None
        if blocked:
            body = {"status": "breaker_open", "breakers": states,
                    "blocked": blocked,
                    "active": len(orchestrator.active_jobs)}
            if reasons:
                body["breakerReasons"] = reasons
            if slo_block is not None:
                body["slo"] = slo_block
            return web.json_response(body, status=503)
        payload = {"status": "ready",
                   "active": len(orchestrator.active_jobs),
                   "breakers": states}
        if reasons:
            payload["breakerReasons"] = reasons
        if slo_block is not None:
            payload["slo"] = slo_block
        # overload controller (control/overload.py): a saturated worker
        # is still READY — HIGH/NORMAL flow, only BULK is shed — but the
        # posture is surfaced so routing layers can prefer idle peers
        overload = getattr(orchestrator, "overload", None)
        if overload is not None and overload.saturated:
            payload["overload"] = {
                "saturated": True,
                "reasons": list(overload.reasons),
            }
        # fleet plane: identity + liveness posture, without awaiting the
        # coordination store (readiness probes must stay cheap — the
        # full membership view lives on GET /v1/fleet)
        plane = getattr(orchestrator, "fleet", None)
        if plane is not None:
            payload["fleet"] = {
                "workerId": plane.worker_id,
                "heldLeases": len(plane.lease_snapshot()),
                "coordErrors": plane.stats.get("coordErrors", 0),
            }
        # crash recovery (control/journal.py): what the last boot's
        # reconciliation found — recovered placeholders, restored retry
        # counters, swept orphan workdirs.  Present only when a journal
        # is configured; torn lines > 0 is worth an operator's look.
        recovery = getattr(orchestrator, "recovery", None)
        if recovery is not None:
            payload["recovery"] = recovery
        return web.json_response(payload)

    async def prom(_request: web.Request) -> web.Response:
        body = metrics.render() if metrics is not None else b""
        return web.Response(body=body, content_type="text/plain")

    app.router.add_get("/health", health)
    app.router.add_get("/livez", livez)
    app.router.add_get("/readyz", readyz)
    app.router.add_get("/metrics", prom)
    # control plane: /v1/jobs, cancel, intake pause/resume, drain
    # (degrades to 503s against orchestrators without a registry)
    bind_control_routes(app, orchestrator)
    return app


async def start_server(
    orchestrator: Orchestrator,
    metrics: Optional[Metrics] = None,
    port: Optional[int] = None,
) -> web.AppRunner:
    """Bind the HTTP surface; returns the runner (caller cleans up)."""
    app = build_app(orchestrator, metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    resolved = port if port is not None else int(os.environ.get("PORT", DEFAULT_PORT))
    site = web.TCPSite(runner, "0.0.0.0", resolved)
    await site.start()
    return runner
