"""OpenCV-backed y4m <-> container codec tool (ffmpeg-contract subset).

The transcode plumbing (:mod:`.compute.transcode`) talks to external
codecs over the ffmpeg yuv4mpegpipe contract; production deployments use
ffmpeg itself.  This tool implements the same contract on top of
OpenCV's bundled FFMPEG build (``cv2``, present in the TPU-host image),
so hosts without an ffmpeg binary — including CI and the bench host —
can still run the decode front-end and encode back-end against a real
subprocess speaking real compressed containers:

    decode:  downloader-tpu-codec -i movie.mkv -f yuv4mpegpipe \
                 -pix_fmt yuv420p -loglevel error -
             (container frames -> planar 4:2:0 y4m on stdout)

    encode:  downloader-tpu-codec -y -f yuv4mpegpipe -i - \
                 -c:v mpeg4 out.mkv
             (y4m on stdin -> compressed container at the last operand)

Flag subset: ``-i``, ``-f``, ``-pix_fmt``, ``-loglevel``, ``-c:v``,
``-preset``, ``-crf``, ``-r`` (value-taking; unknown value-flags are
rejected, ffmpeg-style, rather than mis-parsed as the output), ``-y``
(bare).  Only 4:2:0 is supported — exactly what the transcode module
requests (``-pix_fmt yuv420p``).

This is a capability fallback, not an ffmpeg replacement: codec choice
is limited to what the local OpenCV build provides (``mpeg4``/``mjpeg``/
``ffv1`` are reliably present; ``libx264`` needs an OpenH264-enabled
build and fails cleanly otherwise).
"""

from __future__ import annotations

import sys
from fractions import Fraction
from typing import List, Optional

# ffmpeg codec name -> OpenCV fourcc
_FOURCC = {
    "libx264": "avc1",
    "h264": "avc1",
    "libx265": "hev1",
    "hevc": "hev1",
    "mpeg4": "mp4v",
    "mjpeg": "MJPG",
    "ffv1": "FFV1",
    "libvpx-vp9": "VP90",
    "vp9": "VP90",
}

_VALUE_FLAGS = {"-i", "-f", "-pix_fmt", "-loglevel", "-c:v", "-preset",
                "-crf", "-r"}
_BARE_FLAGS = {"-y", "-nostdin"}
# accepted for command-line compatibility with the transcode module's
# ffmpeg invocations but not implemented by the OpenCV backend (cv2's
# VideoWriter exposes no rate-control or speed knobs): announced on
# stderr (unless -loglevel error or below) so operators comparing
# against real ffmpeg output know the requested rate/quality behavior
# was not applied (advisor r4)
_IGNORED_VALUE_FLAGS = {"-preset", "-crf", "-r"}


class CodecError(RuntimeError):
    pass


def _parse(argv: List[str]) -> dict:
    opts = {"flags": {}, "output": None}
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg in _VALUE_FLAGS:
            if i + 1 >= len(argv):
                raise CodecError(f"flag {arg} needs a value")
            opts["flags"][arg] = argv[i + 1]
            i += 2
        elif arg in _BARE_FLAGS:
            opts["flags"][arg] = True
            i += 1
        elif arg.startswith("-") and arg != "-":
            raise CodecError(f"unknown flag {arg}")
        else:
            if opts["output"] is not None:
                raise CodecError(
                    f"multiple outputs: {opts['output']!r} and {arg!r}")
            opts["output"] = arg
            i += 1
    if "-i" not in opts["flags"]:
        raise CodecError("no input (-i)")
    if opts["output"] is None:
        raise CodecError("no output operand")
    return opts


def _fps_fraction(fps: float) -> Fraction:
    if not fps or fps != fps or fps <= 0:  # 0/NaN from broken containers
        return Fraction(25, 1)
    return Fraction(fps).limit_denominator(100_000)


def _decode(src: str, out_fh) -> int:
    """Container -> y4m (4:2:0) on ``out_fh``.  Returns frames written."""
    import cv2
    import numpy as np

    from .compute.video import Y4MHeader, Y4MWriter

    cap = cv2.VideoCapture(src)
    if not cap.isOpened():
        raise CodecError(f"cannot open {src!r} (unsupported or missing)")
    try:
        fps = _fps_fraction(cap.get(cv2.CAP_PROP_FPS))
        writer = None
        frames = 0
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            h, w = frame.shape[:2]
            if h % 2 or w % 2:  # 4:2:0 needs even dims; crop one line/col
                frame = frame[: h - h % 2, : w - w % 2]
                h, w = frame.shape[:2]
            if writer is None:
                header = Y4MHeader(
                    width=w, height=h,
                    fps_num=fps.numerator, fps_den=fps.denominator,
                    colorspace="420jpeg",
                )
                writer = Y4MWriter(out_fh, header)
            i420 = cv2.cvtColor(frame, cv2.COLOR_BGR2YUV_I420)
            flat = np.ascontiguousarray(i420).reshape(-1)
            y_n, c_n = h * w, (h // 2) * (w // 2)
            writer.write_frame(
                flat[:y_n].reshape(h, w),
                flat[y_n:y_n + c_n].reshape(h // 2, w // 2),
                flat[y_n + c_n:].reshape(h // 2, w // 2),
            )
            frames += 1
        if frames == 0:
            raise CodecError(f"no decodable video frames in {src!r}")
        return frames
    finally:
        cap.release()


def _encode(in_fh, dst: str, codec: Optional[str]) -> int:
    """y4m on ``in_fh`` -> container at ``dst``.  Returns frames read."""
    import cv2
    import numpy as np

    from .compute.video import Y4MReader

    reader = Y4MReader(in_fh)
    hdr = reader.header
    if hdr.subsampling != (2, 2):
        raise CodecError(
            f"only 4:2:0 input is supported, got C{hdr.colorspace}")
    if codec is not None and codec not in _FOURCC:
        raise CodecError(f"unknown codec {codec!r} "
                         f"(supported: {', '.join(sorted(_FOURCC))})")
    if codec is None:
        codec = "mjpeg" if dst.lower().endswith(".avi") else "mpeg4"
    fourcc = cv2.VideoWriter_fourcc(*_FOURCC[codec])
    fps = hdr.fps_num / hdr.fps_den if hdr.fps_den else 25.0
    writer = cv2.VideoWriter(dst, fourcc, fps, (hdr.width, hdr.height))
    if not writer.isOpened():
        writer.release()
        raise CodecError(
            f"VideoWriter rejected codec {codec!r} ({_FOURCC[codec]}) "
            f"for {dst!r} — not in this OpenCV build?")
    try:
        frames = 0
        for y, cb, cr in reader:
            i420 = np.concatenate(
                [y.reshape(-1), cb.reshape(-1), cr.reshape(-1)]
            ).reshape(hdr.height * 3 // 2, hdr.width)
            writer.write(cv2.cvtColor(i420, cv2.COLOR_YUV2BGR_I420))
            frames += 1
        if frames == 0:
            raise CodecError("empty y4m stream (no FRAMEs)")
        return frames
    finally:
        writer.release()


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    try:
        opts = _parse(argv)
        ignored = sorted(_IGNORED_VALUE_FLAGS & opts["flags"].keys())
        # the notice is informational, so it honors -loglevel the way
        # ffmpeg's own banner/warnings do: anything at or below "error"
        # silences it (the transcode module always passes -loglevel
        # error, keeping its captured-stderr failure tails clean)
        quiet = opts["flags"].get("-loglevel") in (
            "quiet", "panic", "fatal", "error")
        if ignored and not quiet:
            print("downloader-tpu-codec: note: accepted but not "
                  "implemented by the OpenCV backend (no effect): "
                  + " ".join(f"{f} {opts['flags'][f]}" for f in ignored),
                  file=sys.stderr)
        src = opts["flags"]["-i"]
        out = opts["output"]
        if out == "-":
            # decode mode: container in, y4m on stdout
            if opts["flags"].get("-f") != "yuv4mpegpipe":
                raise CodecError("stdout output needs -f yuv4mpegpipe")
            pix = opts["flags"].get("-pix_fmt", "yuv420p")
            if pix != "yuv420p":
                raise CodecError(f"only yuv420p output is supported, "
                                 f"got {pix!r}")
            _decode(src, sys.stdout.buffer)
            sys.stdout.buffer.flush()
        elif src == "-":
            # encode mode: y4m on stdin, container out
            _encode(sys.stdin.buffer, out, opts["flags"].get("-c:v"))
        else:
            raise CodecError(
                "need a pipe on one side: -i - (encode) or '-' out (decode)")
        return 0
    except CodecError as err:
        print(f"downloader-tpu-codec: {err}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        return 1
    except Exception as err:  # parity with ffmpeg: nonzero + stderr line
        print(f"downloader-tpu-codec: {type(err).__name__}: {err}",
              file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
