"""Integrity scrubber: re-verify landed bytes forever, self-heal rot.

Hash-on-land (PR 8/19) proves bytes were the origin's bytes at the
landing moment; nothing re-proves it afterwards — and the zero-copy
staging path now shares inodes aggressively (cache hardlinks into
workdirs, ``consume=True`` spills hardlink into the fs store, the
peer tier hardlinks store objects into peer caches), so one flipped
bit propagates *by inode* to every view of the content.  This module
closes the loop, in two halves:

- **The landing recovery sidecar** (``.landed.json`` in each job
  workdir): basename -> md5 of every promoted output, persisted
  *durably before* the data rename (stages/download.py ``_promote``).
  Boot recovery (:func:`verify_landed`) re-hashes the sidecar-named
  outputs of every resumable workdir and demotes any mismatch — the
  torn-tail crash case, where the file's SIZE still checks out but
  the tail pages never reached the disk — back to re-fetch instead
  of serving the hole.  Only sidecar-named files are judged: a
  workdir's resumable ``.partial``/piece state is verified by its own
  machinery (validators, SHA-1 piece hashes) on resume.

- :class:`Scrubber` — an incremental, rate-limited background walk of
  the local content cache, the shared staging tier (when the store is
  co-located and exposes on-disk paths), and live workdir sidecars,
  re-hashing every object against its landing digest.  A mismatch is
  REPAIRED from a healthy replica when one exists — always into a
  **fresh inode** (copy-on-repair: ``os.replace`` of a verified copy,
  never a re-link), so a peer's corruption can never be "fixed" into
  shared state and every other hardlinked view of the bad inode stays
  detectable — and QUARANTINED otherwise (moved aside for triage;
  quarantined workdir outputs are re-fetched from origin by the job's
  own redelivery).  Hashing is billed to the ``scrub`` hop and paced
  against ``scrub.rate_mb_s`` so a deep cache never steals the
  landing path's disk bandwidth.  Verdicts are counted on
  ``scrub_objects_total{outcome=clean|repaired|quarantined}`` and the
  cumulative state rides the fleet heartbeat digest onto
  ``/v1/fleet/overview`` and ``cli fleet top``.

Knobs (``scrub.*``)::

    scrub:
      enabled: true        # false removes the background scrubber
      interval: 300.0      # seconds between scrub passes
      rate_mb_s: 32.0      # hashing budget; 0 = unpaced
      quarantine_dir: ""   # default <download_root>/.quarantine
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import time
from typing import Dict, Optional

from ..platform import vfs
from ..platform.config import cfg_get
from ..utils.hashing import md5_file_hex

#: per-workdir recovery sidecar: {output basename: md5 hex}, written
#: durably BEFORE each output's promote rename
LANDED_SIDECAR = ".landed.json"

DEFAULT_INTERVAL = 300.0
DEFAULT_RATE_MB_S = 32.0


# -- the landing recovery sidecar --------------------------------------
def read_landed(dirpath: str) -> Dict[str, str]:
    """The workdir's recovery sidecar, ``{}`` when absent or torn (an
    unreadable sidecar means nothing was promised, so nothing is
    judged — the job's own resume machinery takes over)."""
    try:
        with open(os.path.join(dirpath, LANDED_SIDECAR)) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict):
        return {}
    return {str(k): str(v) for k, v in doc.items()
            if isinstance(k, str) and isinstance(v, str)}


def _write_sidecar(dirpath: str, landed: Dict[str, str]) -> None:
    path = os.path.join(dirpath, LANDED_SIDECAR)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(landed, fh)
    # durable BEFORE the caller's data rename — that ordering is the
    # whole recovery contract.  Its own seam so a torn-promote drill
    # aimed at ``disk.promote`` lands on the DATA rename, not here.
    vfs.promote(tmp, path, seam="disk.sidecar", key=path)


def note_landed(dirpath: str, name: str, digest: str) -> None:
    """Record ``name``'s landing digest in the workdir sidecar
    (read-modify-write, idempotent, durable)."""
    landed = read_landed(dirpath)
    if landed.get(name) == digest:
        return
    landed[name] = digest
    _write_sidecar(dirpath, landed)


def drop_landed(dirpath: str, name: str) -> None:
    """Forget ``name``'s sidecar entry (its bytes were demoted or
    quarantined; the note must not outlive them)."""
    landed = read_landed(dirpath)
    if landed.pop(name, None) is None:
        return
    if landed:
        _write_sidecar(dirpath, landed)
    else:
        try:
            os.remove(os.path.join(dirpath, LANDED_SIDECAR))
        except OSError:
            pass


def verify_landed(dirpath: str) -> "tuple[int, int]":
    """Boot-time torn-tail recovery for one resumable workdir
    (thread-side, called from the orchestrator's workdir sweep).

    Re-hashes every output the sidecar names; a mismatch is DEMOTED —
    the file is deleted and its note dropped, so the job's redelivery
    re-fetches instead of serving bytes the disk never durably held.
    A sidecar note without its file (the promote crashed between the
    sidecar write and the data rename) is pruned silently: nothing
    was ever promoted, nothing could have been served.  Returns
    ``(verified, demoted)`` counts.
    """
    landed = read_landed(dirpath)
    if not landed:
        return 0, 0
    verified = demoted = 0
    changed = False
    for name, want in sorted(landed.items()):
        path = os.path.join(dirpath, name)
        try:
            # graftlint: disable=second-pass-read -- boot recovery after a crash: no in-memory digest survived the process, one pass decides serve-vs-refetch
            got = md5_file_hex(path)
        except OSError:
            landed.pop(name)
            changed = True
            continue
        if got == want:
            verified += 1
            continue
        try:
            os.remove(path)
        except OSError:
            pass
        landed.pop(name)
        changed = True
        demoted += 1
    if changed:
        if landed:
            _write_sidecar(dirpath, landed)
        else:
            try:
                os.remove(os.path.join(dirpath, LANDED_SIDECAR))
            except OSError:
                pass
    return verified, demoted


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


# -- the background scrubber -------------------------------------------
class Scrubber:
    """Incremental background integrity walk (module docstring)."""

    def __init__(self, *, cache=None, fleet=None,
                 workdir_root: Optional[str] = None,
                 quarantine_dir: Optional[str] = None,
                 interval: float = DEFAULT_INTERVAL,
                 rate_bytes: float = DEFAULT_RATE_MB_S * 1e6,
                 metrics=None, logger=None):
        if interval <= 0:
            raise ValueError(f"scrub.interval must be > 0, got {interval}")
        self.cache = cache
        self.fleet = fleet
        self.workdir_root = workdir_root
        self.quarantine_dir = quarantine_dir or (
            os.path.join(workdir_root, ".quarantine") if workdir_root
            else None)
        self.interval = float(interval)
        self.rate_bytes = float(rate_bytes)
        self.metrics = metrics
        self.logger = logger
        # cumulative verdicts, carried on the fleet heartbeat digest
        self.state: dict = {
            "passes": 0, "clean": 0, "repaired": 0, "quarantined": 0,
            "lastPassAt": None, "lastPassSeconds": None,
        }
        self._task: Optional[asyncio.Task] = None

    # -- config ---------------------------------------------------------
    @classmethod
    def from_config(cls, config, *, cache=None, fleet=None,
                    workdir_root=None, metrics=None,
                    logger=None) -> Optional["Scrubber"]:
        """Build from ``scrub.*``; None when explicitly disabled."""
        if not bool(cfg_get(config, "scrub.enabled", True)):
            return None
        return cls(
            cache=cache, fleet=fleet, workdir_root=workdir_root,
            quarantine_dir=cfg_get(config, "scrub.quarantine_dir", None),
            interval=float(cfg_get(config, "scrub.interval",
                                   DEFAULT_INTERVAL)),
            rate_bytes=float(cfg_get(config, "scrub.rate_mb_s",
                                     DEFAULT_RATE_MB_S)) * 1e6,
            metrics=metrics, logger=logger,
        )

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop(), name="scrubber")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval)
            try:
                await self.scan()
            except asyncio.CancelledError:
                raise
            except Exception as err:  # one broken pass must not end them
                if self.logger is not None:
                    self.logger.warn("scrub pass failed",
                                     error=str(err)[:200])

    def snapshot(self) -> dict:
        """JSON state for the SLO digest / fleet overview."""
        return dict(self.state)

    # -- one pass -------------------------------------------------------
    async def scan(self) -> dict:
        """One full scrub pass; returns this pass's verdict counts."""
        counts = {"clean": 0, "repaired": 0, "quarantined": 0}
        mark = time.monotonic()
        await self._scan_cache(counts)
        await self._scan_shared(counts)
        await self._scan_workdirs(counts)
        self.state["passes"] += 1
        for outcome, n in counts.items():
            self.state[outcome] += n
        self.state["lastPassAt"] = round(time.time(), 3)
        self.state["lastPassSeconds"] = round(time.monotonic() - mark, 3)
        if self.logger is not None and (counts["repaired"]
                                        or counts["quarantined"]):
            self.logger.warn("scrub pass found corruption", **counts)
        return counts

    def _note(self, outcome: str, counts: dict) -> None:
        counts[outcome] += 1
        if self.metrics is not None:
            self.metrics.scrub_objects.labels(outcome=outcome).inc()

    async def _hash(self, path: str) -> Optional[str]:
        """md5 of ``path`` off the loop, billed to the ``scrub`` hop and
        paced against the configured bandwidth budget; None when the
        file vanished under the walk (eviction/cleanup races are
        normal, not errors)."""
        try:
            size = os.path.getsize(path)
        except OSError:
            return None
        mark = time.monotonic()
        try:
            # graftlint: disable=second-pass-read -- the scrubber IS the justified second pass: re-verifying cold bytes against their landing digest is this subsystem's entire purpose
            digest = await asyncio.to_thread(md5_file_hex, path)
        except OSError:
            return None
        elapsed = time.monotonic() - mark
        if self.metrics is not None:
            self.metrics.hop_bytes.labels(hop="scrub").inc(size)
            self.metrics.hop_seconds.labels(hop="scrub").inc(elapsed)
        if self.rate_bytes > 0:
            budget = size / self.rate_bytes
            if budget > elapsed:
                await asyncio.sleep(min(budget - elapsed, 5.0))
        return digest

    def _quarantine_file(self, path: str, tag: str) -> bool:
        """Move one corrupt file aside for triage (fresh name per
        incident; cross-device safe)."""
        if not self.quarantine_dir:
            return False
        try:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            dest = os.path.join(
                self.quarantine_dir,
                f"{tag}-{int(time.time())}-{os.path.basename(path)}")
            shutil.move(path, dest)
            return True
        except OSError as err:
            if self.logger is not None:
                self.logger.warn("scrub quarantine failed", path=path,
                                 error=str(err))
            return False

    # -- local cache walk -----------------------------------------------
    async def _scan_cache(self, counts: dict) -> None:
        cache = self.cache
        if cache is None:
            return
        for key in await asyncio.to_thread(cache.keys):
            entry = await cache.peek(key)
            if entry is None or not getattr(entry, "digests", None):
                continue
            bad = False
            async with cache.pinned(key):
                for rel, want in sorted(entry.digests.items()):
                    path = os.path.join(cache.entry_path(key),
                                        *rel.split("/"))
                    got = await self._hash(path)
                    if got is None:
                        continue  # evicted under the walk
                    if got == want:
                        self._note("clean", counts)
                        continue
                    if await self._repair_cache_file(key, rel, want, path):
                        self._note("repaired", counts)
                        if self.logger is not None:
                            self.logger.warn(
                                "scrub: repaired cache file from shared "
                                "tier", key=key[:16], rel=rel)
                    else:
                        bad = True
                        self._note("quarantined", counts)
            if bad:
                # no healthy replica: the whole entry leaves the cache
                # (a later job for this key misses and re-fetches from
                # origin — that IS the repair-from-origin path)
                await cache.quarantine(key, self.quarantine_dir)
                if self.logger is not None:
                    self.logger.warn("scrub: quarantined cache entry",
                                     key=key[:16])

    async def _repair_cache_file(self, key: str, rel: str, want: str,
                                 path: str) -> bool:
        """Re-copy one corrupt cache file from the shared tier.

        The verified copy lands under a temp name and ``os.replace``s
        the corrupt file — ALWAYS a fresh inode (copy-on-repair), so a
        workdir or peer still hardlinked to the corrupt inode keeps
        its own detectable view instead of silently changing under a
        reader."""
        fleet = self.fleet
        if fleet is None or getattr(fleet, "store", None) is None:
            return False
        tmp = f"{path}.scrubtmp.{os.getpid()}"
        try:
            await fleet.store.fget_object(
                fleet.shared_bucket, fleet.shared_name(key, rel), tmp)
        except Exception:
            _unlink_quiet(tmp)
            return False
        got = await self._hash(tmp)
        if got != want:
            _unlink_quiet(tmp)
            return False
        try:
            os.replace(tmp, path)
        except OSError:
            _unlink_quiet(tmp)
            return False
        return True

    # -- shared tier walk -----------------------------------------------
    async def _scan_shared(self, counts: dict) -> None:
        """Scrub the shared staging tier's payload objects — only when
        the store is co-located (exposes ``local_object_path``): a
        remote store's disks are its own scrubber's problem, and
        hashing a remote object would mean streaming it anyway."""
        fleet = self.fleet
        if fleet is None or getattr(fleet, "store", None) is None:
            return
        local_path = getattr(fleet.store, "local_object_path", None)
        if local_path is None:
            return
        from ..fleet.plane import MANIFEST_NAME

        suffix = "/" + MANIFEST_NAME
        names = []
        try:
            async for info in fleet.store.list_objects(
                    fleet.shared_bucket, fleet.shared_prefix):
                name = getattr(info, "name", "")
                if name.endswith(suffix):
                    names.append(name)
        except Exception as err:
            if self.logger is not None:
                self.logger.warn("scrub: shared-tier listing failed",
                                 error=str(err)[:200])
            return
        for mname in sorted(names):
            try:
                doc = json.loads(await fleet.store.get_object(
                    fleet.shared_bucket, mname))
            except Exception:
                continue
            key = doc.get("key")
            digests = doc.get("digests")
            if not key or not isinstance(digests, dict):
                continue
            for rel, want in sorted(digests.items()):
                oname = fleet.shared_name(key, rel)
                path = local_path(fleet.shared_bucket, oname)
                if path is None:
                    continue
                got = await self._hash(path)
                if got is None:
                    continue
                if got == want:
                    self._note("clean", counts)
                    continue
                if await self._repair_shared(key, rel, want, path):
                    self._note("repaired", counts)
                    if self.logger is not None:
                        self.logger.warn(
                            "scrub: repaired shared-tier object from "
                            "local cache", key=key[:16], rel=rel)
                else:
                    # the manifest is the publish: removing it first
                    # makes the entry invisible before the payload
                    # moves, so no peer can fetch a half-quarantined
                    # entry
                    try:
                        await fleet.store.remove_object(
                            fleet.shared_bucket, mname)
                    except Exception:
                        pass
                    await asyncio.to_thread(
                        self._quarantine_file, path,
                        f"shared-{key[:16]}")
                    self._note("quarantined", counts)
                    if self.logger is not None:
                        self.logger.warn(
                            "scrub: quarantined shared-tier object",
                            key=key[:16], rel=rel)

    async def _repair_shared(self, key: str, rel: str, want: str,
                             path: str) -> bool:
        """Repair a shared-tier object from the local cache's copy —
        only when the cache copy is a DIFFERENT inode (a hardlinked
        view shares the corruption by definition) and hash-verifies."""
        cache = self.cache
        if cache is None:
            return False
        src = os.path.join(cache.entry_path(key), *rel.split("/"))
        try:
            if os.path.samestat(os.stat(src), os.stat(path)):
                return False  # same inode: the corruption IS this copy
        except OSError:
            return False
        async with cache.pinned(key):
            got = await self._hash(src)
            if got != want:
                return False

            def _replace() -> bool:
                tmp = f"{path}.scrubtmp.{os.getpid()}"
                try:
                    # copy, never link: the repair must mint a fresh
                    # inode even though source and target sit on the
                    # same volume
                    shutil.copyfile(src, tmp)
                    os.replace(tmp, path)
                    return True
                except OSError:
                    _unlink_quiet(tmp)
                    return False

            return await asyncio.to_thread(_replace)

    # -- workdir sidecar walk -------------------------------------------
    async def _scan_workdirs(self, counts: dict) -> None:
        """Re-verify promoted outputs still staged in live workdirs
        (long BULK queues can hold landed bytes for hours before
        upload).  A corrupt staged output has no healthy replica by
        definition — quarantine it and drop its sidecar note; the
        job's own retry/redelivery re-fetches from origin."""
        root = self.workdir_root
        if not root:
            return
        try:
            names = await asyncio.to_thread(os.listdir, root)
        except OSError:
            return
        for dirname in sorted(names):
            if dirname.startswith("."):
                continue  # .journal / .cache / .quarantine service dirs
            dirpath = os.path.join(root, dirname)
            landed = await asyncio.to_thread(read_landed, dirpath)
            for fname, want in sorted(landed.items()):
                path = os.path.join(dirpath, fname)
                got = await self._hash(path)
                if got is None:
                    continue  # job finished and cleaned up mid-walk
                if got == want:
                    self._note("clean", counts)
                    continue
                await asyncio.to_thread(self._quarantine_file, path,
                                        f"workdir-{dirname}")
                await asyncio.to_thread(drop_landed, dirpath, fname)
                self._note("quarantined", counts)
                if self.logger is not None:
                    self.logger.warn(
                        "scrub: quarantined staged workdir output",
                        workdir=dirname, file=fname)
