"""Object-store abstraction.

The reference's bulk-data backend is S3/MinIO via ``triton-core/minio``
(SURVEY.md §5).  This package defines the exact object-store surface the
pipeline uses — ``getObject`` / ``fGetObject`` / ``fPutObject`` /
``putObject`` / ``bucketExists`` / ``makeBucket`` / ``getObjects``
(/root/reference/lib/main.js:120, lib/upload.js:29-55,
lib/download.js:217-225) — with hermetic in-memory and filesystem-backed
implementations.
"""

from .base import ObjectInfo, ObjectNotFound, ObjectStore
from .cache import ContentCache, Singleflight, cache_key
from .fs import FilesystemObjectStore
from .memory import InMemoryObjectStore

__all__ = [
    "ObjectInfo",
    "ObjectNotFound",
    "ObjectStore",
    "ContentCache",
    "Singleflight",
    "cache_key",
    "FilesystemObjectStore",
    "InMemoryObjectStore",
]


def new_client(config) -> ObjectStore:
    """Build the staging object store from config.

    Capability-equivalent to ``minio.newClient(config)``
    (/root/reference/lib/main.js:41, lib/upload.js:20).  The backend is
    selected by ``config.minio.backend``: ``memory`` (default, hermetic) or
    ``fs`` (rooted at ``config.minio.root``).
    """
    minio_cfg = config.get("minio") if config is not None else None
    backend = (minio_cfg.get("backend", "memory") if minio_cfg is not None else "memory")
    if backend == "fs":
        root = minio_cfg.get("root", "object-store")
        return FilesystemObjectStore(root)
    if backend == "memory":
        return InMemoryObjectStore()
    if backend == "s3":
        from ..platform.config import cfg_get
        from .s3 import S3ObjectStore

        # multipart knobs (``store.multipart_part_size`` /
        # ``store.multipart_concurrency``): deployment-tunable instead of
        # the historical hard-coded 64 MiB / 3 — bad values fail here, at
        # boot, with the S3 API's constraints spelled out
        return S3ObjectStore.from_endpoint(
            minio_cfg.get("endpoint", "localhost:9000"),
            minio_cfg.get("access_key", ""),
            minio_cfg.get("secret_key", ""),
            ssl=minio_cfg.get("ssl", False),
            region=minio_cfg.get("region", "us-east-1"),
            multipart_part_size=cfg_get(
                config, "store.multipart_part_size", None
            ),
            multipart_concurrency=cfg_get(
                config, "store.multipart_concurrency", None
            ),
            # zero-copy staging (ISSUE 19): mmap-fed multipart parts and
            # sendfile single PUTs on plain http; off = byte-exact
            # read() path everywhere
            zero_copy=bool(cfg_get(config, "store.zero_copy", True)),
        )
    raise ValueError(f"unknown object-store backend {backend!r}")
