"""S3-compatible object-store driver (AWS Signature V4 over aiohttp).

The reference talks to MinIO through the ``minio`` npm client
(/root/reference/lib/main.js:41, lib/download.js:210-215,
lib/upload.js:20); this is the equivalent driver, implemented directly
against the S3 REST API so the framework has no extra dependencies.
Implements exactly the surface :class:`~downloader_tpu.store.base.ObjectStore`
defines: bucket head/create, object get/put (bytes and files), and
ListObjectsV2 with prefix + continuation pagination.

Works against MinIO, AWS S3, GCS interop mode, or the in-repo test server
(``tests/minis3.py``).
"""

from __future__ import annotations

import asyncio
import base64
import datetime
import errno
import hashlib
import hmac
import mmap
import os
import re
import socket
import urllib.parse
import xml.etree.ElementTree as ET
from typing import AsyncIterator, Dict, Optional

import aiohttp
import yarl

from ..platform.errors import PERMANENT, TRANSIENT, tag_fault
from .base import ObjectInfo, ObjectNotFound, ObjectStore

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()

# get_object / get_object_versioned are the CONTROL-plane fetch (done
# markers, fleet manifests, coordination docs) and buffer the body in
# memory; media-sized objects must go through the streaming
# fget_object.  The cap turns "someone pointed the doc fetch at a
# 40 GB object" into a loud, immediate error instead of an OOM.
GET_OBJECT_MAX_BYTES = 64 << 20


def _status_error(op: str, status: int, body: bytes = b"") -> RuntimeError:
    """S3 error carrying its taxonomy class (platform/errors.py): 5xx /
    408 / 429 are dependency blips worth a retry; other 4xx repeat
    deterministically and must fail fast."""
    err = RuntimeError(f"{op} failed: {status} {body!r}")
    err.fault_class = (TRANSIENT if status >= 500 or status in (408, 429)
                       else PERMANENT)
    return err


def _uri_encode(value: str, encode_slash: bool = True) -> str:
    safe = "-_.~" if encode_slash else "-_.~/"
    return urllib.parse.quote(value, safe=safe)


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode("utf-8"), hashlib.sha256).digest()


class SigV4Signer:
    """AWS Signature Version 4 for S3 (single-chunk, signed payload)."""

    def __init__(self, access_key: str, secret_key: str, region: str = "us-east-1"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.service = "s3"

    def sign(
        self,
        method: str,
        host: str,
        path: str,
        query: Dict[str, str],
        payload_hash: str,
        now: Optional[datetime.datetime] = None,
    ) -> Dict[str, str]:
        """Return the headers (including Authorization) for the request."""
        now = now or datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date_stamp = now.strftime("%Y%m%d")

        canonical_query = "&".join(
            f"{_uri_encode(k)}={_uri_encode(v)}" for k, v in sorted(query.items())
        )
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        signed_headers = ";".join(sorted(headers))
        canonical_headers = "".join(
            f"{k}:{headers[k].strip()}\n" for k in sorted(headers)
        )
        # ``path`` must arrive already URI-encoded (S3 canonical URIs are
        # encoded exactly once; re-encoding here would corrupt '%')
        canonical_request = "\n".join(
            [
                method,
                path,
                canonical_query,
                canonical_headers,
                signed_headers,
                payload_hash,
            ]
        )
        scope = f"{date_stamp}/{self.region}/{self.service}/aws4_request"
        string_to_sign = "\n".join(
            [
                "AWS4-HMAC-SHA256",
                amz_date,
                scope,
                hashlib.sha256(canonical_request.encode("utf-8")).hexdigest(),
            ]
        )
        key = _hmac(
            _hmac(
                _hmac(
                    _hmac(("AWS4" + self.secret_key).encode("utf-8"), date_stamp),
                    self.region,
                ),
                self.service,
            ),
            "aws4_request",
        )
        signature = hmac.new(
            key, string_to_sign.encode("utf-8"), hashlib.sha256
        ).hexdigest()
        authorization = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        )
        return {
            "Authorization": authorization,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }


class S3ObjectStore(ObjectStore):
    """Path-style S3 client: ``<endpoint>/<bucket>/<key>``."""

    # defaults for the multipart knobs (config: store.multipart_part_size /
    # store.multipart_concurrency); 64 MiB parts match the common S3 client
    # defaults, 5 MiB is the API's hard minimum part size
    DEFAULT_PART_SIZE = 64 << 20
    DEFAULT_MULTIPART_CONCURRENCY = 3
    MIN_PART_SIZE = 5 << 20

    @classmethod
    def from_endpoint(
        cls,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        ssl: bool = True,
        region: str = "us-east-1",
        multipart_part_size: Optional[int] = None,
        multipart_concurrency: Optional[int] = None,
        zero_copy: bool = True,
    ) -> "S3ObjectStore":
        """Build from a host[:port] or full URL; an explicit scheme wins,
        otherwise ``ssl`` picks https/http."""
        if "://" not in endpoint:
            scheme = "https" if ssl else "http"
            endpoint = f"{scheme}://{endpoint}"
        return cls(endpoint, access_key, secret_key, region,
                   multipart_part_size=multipart_part_size,
                   multipart_concurrency=multipart_concurrency,
                   zero_copy=zero_copy)

    def __init__(
        self,
        endpoint: str,
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
        session: Optional[aiohttp.ClientSession] = None,
        multipart_part_size: Optional[int] = None,
        multipart_concurrency: Optional[int] = None,
        zero_copy: bool = True,
    ):
        self.endpoint = endpoint.rstrip("/")
        parsed = urllib.parse.urlparse(self.endpoint)
        self._host = parsed.netloc
        self._signer = SigV4Signer(access_key, secret_key, region)
        self._session = session
        # multipart kicks in above the threshold (= the part size, so no
        # object ever uploads as a single part bigger than a part).
        # Misconfiguration fails loudly, like the rate-limit knobs: a
        # part size under the S3 API's 5 MiB floor would be rejected by
        # the server at complete time with a far less obvious error.
        # None = unset; an explicit 0 must hit the validation below, not
        # silently coerce to the default
        part_size = (self.DEFAULT_PART_SIZE if multipart_part_size is None
                     else int(multipart_part_size))
        if part_size < self.MIN_PART_SIZE:
            raise ValueError(
                f"multipart_part_size must be >= {self.MIN_PART_SIZE} "
                f"(S3 minimum part size), got {part_size}"
            )
        concurrency = (self.DEFAULT_MULTIPART_CONCURRENCY
                       if multipart_concurrency is None
                       else int(multipart_concurrency))
        if concurrency < 1:
            raise ValueError(
                f"multipart_concurrency must be >= 1, got {concurrency}"
            )
        self.multipart_threshold = part_size
        self.multipart_part_size = part_size
        self.multipart_concurrency = concurrency
        # zero-copy staging (config: store.zero_copy, default on):
        # multipart parts are fed from an mmap of the source file
        # (UNSIGNED-PAYLOAD signing, so no hashing pass either) instead
        # of being read into fresh userspace buffers, and — on a plain
        # http endpoint, where the transport allows it — single PUTs and
        # parts go out via os.sendfile so body bytes never transit
        # userspace at all.  Off = the byte-exact read() path everywhere.
        self.zero_copy = bool(zero_copy)
        self._scheme = parsed.scheme or "https"

    async def _ensure_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def _request(
        self,
        method: str,
        path: str,
        query: Optional[Dict[str, str]] = None,
        data: bytes = b"",
        extra_headers: Optional[Dict[str, str]] = None,
    ) -> aiohttp.ClientResponse:
        query = query or {}
        payload_hash = (
            _EMPTY_SHA256 if not data else hashlib.sha256(data).hexdigest()
        )
        headers = self._signer.sign(method, self._host, path, query, payload_hash)
        if extra_headers:
            # merged AFTER signing: conditional headers (If-Match /
            # If-None-Match) are not part of the canonical request, so
            # the signature stays valid with or without them
            headers = {**headers, **extra_headers}
        session = await self._ensure_session()
        url = f"{self.endpoint}{path}"
        if query:
            # identical encoding to the canonical query string, and the URL is
            # marked pre-encoded so yarl can't rewrite what was signed
            url += "?" + "&".join(
                f"{_uri_encode(k)}={_uri_encode(v)}" for k, v in sorted(query.items())
            )
        return await session.request(
            method, yarl.URL(url, encoded=True), headers=headers, data=data
        )

    # -- ObjectStore surface -------------------------------------------
    async def bucket_exists(self, bucket: str) -> bool:
        resp = await self._request("HEAD", f"/{bucket}")
        resp.release()
        return resp.status == 200

    async def make_bucket(self, bucket: str) -> None:
        resp = await self._request("PUT", f"/{bucket}")
        body = await resp.read()
        if resp.status not in (200, 204) and b"BucketAlreadyOwnedByYou" not in body:
            raise _status_error(f"make_bucket({bucket})", resp.status, body)

    def _object_path(self, bucket: str, name: str) -> str:
        return f"/{bucket}/" + "/".join(
            urllib.parse.quote(part, safe="") for part in name.split("/")
        )

    async def _read_capped(self, resp, op: str, bucket: str,
                           name: str) -> bytes:
        """Drain a GET body with a hard in-memory cap.

        ``resp.read()`` buffers however much the server sends; pointing
        the control-plane fetch at a media-sized object used to mean an
        unbounded allocation.  Chunked accumulation up to
        ``GET_OBJECT_MAX_BYTES`` keeps the failure mode a deterministic
        PERMANENT error naming the streaming alternative."""
        declared = int(resp.headers.get("Content-Length") or 0)
        if declared > GET_OBJECT_MAX_BYTES:
            resp.close()  # abort: draining the body is the very cost
            err = RuntimeError(
                f"{op}({bucket}/{name}): object is {declared} bytes, over "
                f"the {GET_OBJECT_MAX_BYTES}-byte in-memory cap — stream "
                "it with fget_object instead")
            err.fault_class = PERMANENT
            raise err
        chunks, total = [], 0
        async for chunk in resp.content.iter_chunked(1 << 20):
            total += len(chunk)
            if total > GET_OBJECT_MAX_BYTES:
                resp.close()  # abort: draining the body is the very cost
                err = RuntimeError(
                    f"{op}({bucket}/{name}): body exceeded the "
                    f"{GET_OBJECT_MAX_BYTES}-byte in-memory cap — stream "
                    "it with fget_object instead")
                err.fault_class = PERMANENT
                raise err
            chunks.append(chunk)
        return b"".join(chunks)

    async def get_object(self, bucket: str, name: str) -> bytes:
        resp = await self._request("GET", self._object_path(bucket, name))
        if resp.status == 404:
            resp.release()
            raise ObjectNotFound(bucket, name)
        if resp.status != 200:
            raise _status_error("get_object", resp.status,
                                await resp.read())
        return await self._read_capped(resp, "get_object", bucket, name)

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        resp = await self._request("PUT", self._object_path(bucket, name), data=data)
        body = await resp.read()
        if resp.status not in (200, 204):
            raise _status_error("put_object", resp.status, body)

    async def get_object_versioned(self, bucket: str, name: str):
        resp = await self._request("GET", self._object_path(bucket, name))
        if resp.status == 404:
            resp.release()
            raise ObjectNotFound(bucket, name)
        if resp.status != 200:
            raise _status_error("get_object_versioned", resp.status,
                                await resp.read())
        body = await self._read_capped(resp, "get_object_versioned",
                                       bucket, name)
        return body, resp.headers.get("ETag", "").strip('"')

    async def put_object_cas(self, bucket: str, name: str, data: bytes, *,
                             if_match: Optional[str] = None,
                             if_none_match: bool = False) -> Optional[str]:
        """S3 conditional write (AWS since 2024-08, MinIO, R2): 412 /
        409 = precondition failed = lost the race, reported as ``None``
        rather than raised — losing a CAS is the caller's normal flow."""
        headers: Dict[str, str] = {}
        if if_none_match:
            headers["If-None-Match"] = "*"
        elif if_match is not None:
            headers["If-Match"] = f'"{if_match}"'
        resp = await self._request(
            "PUT", self._object_path(bucket, name), data=data,
            extra_headers=headers,
        )
        body = await resp.read()
        if resp.status in (409, 412):
            return None
        if resp.status not in (200, 204):
            raise _status_error("put_object_cas", resp.status, body)
        etag = resp.headers.get("ETag", "").strip('"')
        if not etag:
            # a backend that accepted the write but returned no ETag:
            # recover the token with a stat so the caller can CAS again
            try:
                etag = (await self.stat_object(bucket, name)).etag
            except ObjectNotFound:
                etag = ""
        return etag

    async def remove_object(self, bucket: str, name: str) -> None:
        resp = await self._request(
            "DELETE", self._object_path(bucket, name)
        )
        body = await resp.read()
        # S3 DELETE is idempotent: 204 whether or not the key existed;
        # tolerate an explicit 404 from stricter fakes
        if resp.status not in (200, 204, 404):
            raise _status_error("remove_object", resp.status, body)

    async def fget_object(self, bucket: str, name: str, file_path: str,
                          *, progress=None) -> None:
        """Streaming GET straight to disk — media files can be tens of GB,
        so the body must never be buffered whole in memory.

        ``progress`` is an optional ``async (bytes_moved)`` callback
        fired after each chunk lands on disk, so callers (the download
        stage's ``bucket`` method, the fleet shared tier) can keep live
        transfer counters moving during a multi-GB object instead of
        jumping once at the end."""
        path = self._object_path(bucket, name)
        resp = await self._request("GET", path)
        try:
            if resp.status == 404:
                raise ObjectNotFound(bucket, name)
            if resp.status != 200:
                body = await resp.read()
                raise _status_error("fget_object", resp.status, body)
            os.makedirs(os.path.dirname(os.path.abspath(file_path)), exist_ok=True)
            # graftlint: disable=blocking-call-in-async -- one open(2); the download loop below awaits per chunk
            with open(file_path, "wb") as fh:
                async for chunk in resp.content.iter_chunked(1 << 20):
                    fh.write(chunk)
                    if progress is not None:
                        await progress(len(chunk))
        finally:
            resp.release()

    # -- zero-copy upload transport ------------------------------------
    def _sendfile_eligible(self) -> bool:
        """True when PUT bodies can ride ``os.sendfile`` straight from
        the page cache into the socket: the zero-copy knob is on, the
        endpoint is plain http (TLS encrypts in userspace, so there is
        nothing to splice), and the platform has sendfile at all."""
        return (self.zero_copy and self._scheme == "http"
                and hasattr(os, "sendfile"))

    def _signed_url(self, path: str, query: Dict[str, str]) -> yarl.URL:
        url = f"{self.endpoint}{path}"
        if query:
            # identical encoding to the canonical query string (and to
            # _request): pre-encoded so yarl can't rewrite what was signed
            url += "?" + "&".join(
                f"{_uri_encode(k)}={_uri_encode(v)}"
                for k, v in sorted(query.items())
            )
        return yarl.URL(url, encoded=True)

    async def _sendfile_put(self, path: str, query: Dict[str, str],
                            file_path: str, offset: int,
                            count: int, extra_headers=None):
        """One plain-HTTP PUT whose body is fed by ``os.sendfile`` —
        file bytes go page cache -> socket without ever entering
        userspace (the kernel half of the zero-copy upload path).

        Speaks just enough HTTP/1.1 for the S3 PUT surface: one
        request, ``Connection: close``, a status line + headers +
        Content-Length (or EOF) delimited body back.  Returns
        ``(status, headers_dict, body)``.  Any transport error
        propagates — the caller falls back to the byte-exact
        buffered path."""
        loop = asyncio.get_running_loop()
        headers = self._signer.sign("PUT", self._host, path, query,
                                    "UNSIGNED-PAYLOAD")
        # aiohttp adds these implicitly; raw HTTP must spell them out
        # (Host is part of the signed canonical headers)
        headers["Host"] = self._host
        headers["Content-Length"] = str(count)
        headers["Connection"] = "close"
        if extra_headers:
            headers = {**headers, **extra_headers}
        request_uri = path
        if query:
            request_uri += "?" + "&".join(
                f"{_uri_encode(k)}={_uri_encode(v)}"
                for k, v in sorted(query.items()))
        head = (f"PUT {request_uri} HTTP/1.1\r\n"
                + "".join(f"{k}: {v}\r\n" for k, v in headers.items())
                + "\r\n").encode("ascii")

        host, _, port = self._host.partition(":")
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setblocking(False)
            await loop.sock_connect(sock, (host, int(port or 80)))
            await loop.sock_sendall(sock, head)
            if count:
                # graftlint: disable=blocking-call-in-async -- one open(2); the body transfer below is awaited sendfile work
                with open(file_path, "rb") as fh:
                    fh.seek(offset)
                    await loop.sock_sendfile(sock, fh, offset, count,
                                             fallback=True)
            raw = b""
            while b"\r\n\r\n" not in raw:
                chunk = await loop.sock_recv(sock, 65536)
                if not chunk:
                    raise ConnectionError(
                        "connection closed before response headers")
                raw += chunk
            head_blob, _, body = raw.partition(b"\r\n\r\n")
            lines = head_blob.decode("latin-1").split("\r\n")
            status = int(lines[0].split(" ", 2)[1])
            resp_headers: Dict[str, str] = {}
            for line in lines[1:]:
                key, _, value = line.partition(":")
                resp_headers[key.strip().lower()] = value.strip()
            want = int(resp_headers.get("content-length", -1))
            while want < 0 or len(body) < want:
                chunk = await loop.sock_recv(sock, 65536)
                if not chunk:
                    break
                body += chunk
            return status, resp_headers, body
        finally:
            sock.close()

    async def fput_object(self, bucket: str, name: str, file_path: str,
                          *, consume: bool = False, progress=None,
                          content_md5: Optional[str] = None) -> None:
        """Upload a file from disk.

        Small files go up as one streaming PUT with an UNSIGNED-PAYLOAD
        SigV4 signature (no slurping, no double hashing).  Files over
        ``multipart_threshold`` use S3 multipart upload: fixed-size parts
        with per-part retry, so one dropped connection at the 60-GB mark of
        a media file costs one part, not the whole transfer; failures abort
        the upload server-side so no orphaned parts accrue storage.

        With ``zero_copy`` on, a plain-http single PUT rides
        ``os.sendfile`` (body bytes never enter userspace); any
        transport hiccup falls back to the byte-exact aiohttp path.

        ``progress`` is an optional ``async (bytes_moved)`` callback fired
        after each part lands (once with the full size on the single-PUT
        path).  The upload stage charges its egress token bucket there, so
        pacing engages at part granularity instead of only after a whole
        multi-GB object — and only for bytes that actually moved (a part
        charged once on success; failed attempts charge nothing).

        ``content_md5`` (hex) is the caller's hash-on-land digest; it
        rides the single PUT as a ``Content-MD5`` header so the server
        verifies the body against the digest computed when the bytes
        landed — end-to-end integrity with zero extra local reads."""
        size = os.path.getsize(file_path)
        if size > self.multipart_threshold:
            await self._multipart_upload(bucket, name, file_path, size,
                                         progress=progress)
            return
        path = self._object_path(bucket, name)
        extra: Dict[str, str] = {}
        if content_md5:
            # merged after signing, like the CAS conditionals: not part
            # of the canonical request, so the signature stays valid
            extra["Content-MD5"] = base64.b64encode(
                bytes.fromhex(content_md5)).decode("ascii")
        if self._sendfile_eligible():
            try:
                status, _resp_headers, body = await self._sendfile_put(
                    path, {}, file_path, 0, size, extra_headers=extra)
                if status not in (200, 204):
                    raise _status_error("fput_object", status, body)
                if progress is not None:
                    await progress(size)
                return
            except (OSError, ConnectionError, ValueError, IndexError):
                # raw transport hiccup (proxy, IPv6-only host, odd
                # server framing): the buffered path below is byte-exact
                pass
        headers = self._signer.sign(
            "PUT", self._host, path, {}, "UNSIGNED-PAYLOAD"
        )
        headers["Content-Length"] = str(size)
        headers.update(extra)
        session = await self._ensure_session()

        # graftlint: disable=blocking-call-in-async -- one open(2); aiohttp streams the fh body without slurping
        with open(file_path, "rb") as fh:
            resp = await session.request(
                "PUT",
                yarl.URL(f"{self.endpoint}{path}", encoded=True),
                headers=headers,
                data=fh,
            )
        body = await resp.read()
        if resp.status not in (200, 204):
            raise _status_error("fput_object", resp.status, body)
        if progress is not None:
            await progress(size)

    # -- multipart upload ----------------------------------------------
    async def _multipart_upload(self, bucket: str, name: str,
                                file_path: str, size: int,
                                progress=None) -> None:
        path = self._object_path(bucket, name)
        resp = await self._request("POST", path, query={"uploads": ""})
        body = await resp.read()
        if resp.status != 200:
            raise _status_error("initiate multipart", resp.status, body)
        match = re.search(rb"<UploadId>([^<]+)</UploadId>", body)
        if match is None:
            raise RuntimeError(f"initiate multipart: no UploadId in {body!r}")
        upload_id = match.group(1).decode()

        try:
            etags = await self._upload_parts(path, upload_id, file_path, size,
                                             progress=progress)
            manifest = "".join(
                f"<Part><PartNumber>{num}</PartNumber>"
                f"<ETag>{etag}</ETag></Part>"
                for num, etag in etags
            )
            payload = (
                f"<CompleteMultipartUpload>{manifest}"
                f"</CompleteMultipartUpload>"
            ).encode()
            resp = await self._request(
                "POST", path, query={"uploadId": upload_id}, data=payload
            )
            body = await resp.read()
            if resp.status != 200 or b"<Error>" in body:
                raise RuntimeError(
                    f"complete multipart failed: {resp.status} {body!r}"
                )
        except BaseException:
            # abort so the server drops the stored parts (otherwise they
            # bill storage forever with no visible object)
            try:
                resp = await self._request(
                    "DELETE", path, query={"uploadId": upload_id}
                )
                resp.release()
            except Exception:
                pass
            raise

    async def _put_part_streamed(self, path: str, query: Dict[str, str],
                                 payload, length: int):
        """One part PUT with UNSIGNED-PAYLOAD signing: the body (an mmap
        memoryview slice) goes to the transport without being hashed or
        copied into a fresh buffer first — the userspace half of the
        zero-copy upload path."""
        headers = self._signer.sign("PUT", self._host, path, query,
                                    "UNSIGNED-PAYLOAD")
        headers["Content-Length"] = str(length)
        session = await self._ensure_session()
        return await session.request(
            "PUT", self._signed_url(path, query), headers=headers,
            data=payload,
        )

    async def _upload_parts(self, path: str, upload_id: str,
                            file_path: str, size: int, progress=None):
        """Upload fixed-size parts with bounded concurrency + per-part
        retry; returns [(part_number, etag)] in order.

        With ``zero_copy`` on, part bodies are fed from ONE shared mmap
        of the source file — page-cache-backed slices, no per-part
        read() into a fresh buffer, and UNSIGNED-PAYLOAD signing so no
        per-part sha256 pass either (upload CPU stops scaling with
        payload size).  On a plain-http endpoint each part instead rides
        ``os.sendfile`` end to end.  Any zero-copy failure falls back to
        the byte-exact buffered read() path for that attempt."""
        part_size = self.multipart_part_size
        part_count = (size + part_size - 1) // part_size
        sem = asyncio.Semaphore(self.multipart_concurrency)
        use_sendfile = self._sendfile_eligible()

        source_map = None
        if self.zero_copy and not use_sendfile and size:
            try:
                # graftlint: disable=blocking-call-in-async -- one open(2) to seed the mmap; the part bodies stream without further reads
                with open(file_path, "rb") as fh:
                    # the map holds its own fd reference; pages are
                    # clean/page-cache-backed, so queued parts pin
                    # nothing the kernel can't reclaim
                    source_map = mmap.mmap(fh.fileno(), 0,
                                           access=mmap.ACCESS_READ)
            except (OSError, ValueError):
                source_map = None  # exotic fs: buffered fallback below

        def _read_region(offset: int, length: int) -> bytes:
            with open(file_path, "rb") as fh:
                fh.seek(offset)
                return fh.read(length)

        async def _attempt_put(part_number: int, offset: int,
                               length: int, buffered: bool):
            query = {"partNumber": str(part_number),
                     "uploadId": upload_id}
            if not buffered and use_sendfile:
                status, resp_headers, body = await self._sendfile_put(
                    path, query, file_path, offset, length)
                return status, resp_headers.get("etag", ""), body
            if not buffered and source_map is not None:
                payload = memoryview(source_map)[offset:offset + length]
                try:
                    resp = await self._put_part_streamed(
                        path, query, payload, length)
                    body = await resp.read()
                finally:
                    payload.release()
                return (resp.status,
                        resp.headers.get("ETag", ""), body)
            # byte-exact fallback: re-read per attempt (in a thread: a
            # 64 MiB read must not stall the event loop) — the file
            # region is the source of truth, a shared buffer would pin
            # memory for queued parts
            data = await asyncio.to_thread(_read_region, offset, length)
            resp = await self._request("PUT", path, query=query,
                                       data=data)
            body = await resp.read()
            return resp.status, resp.headers.get("ETag", ""), body

        async def _one(part_number: int):
            offset = (part_number - 1) * part_size
            length = min(part_size, size - offset)
            async with sem:
                last: Optional[Exception] = None
                buffered = False
                for attempt in range(3):
                    try:
                        status, etag, body = await _attempt_put(
                            part_number, offset, length, buffered)
                        if status == 200:
                            etag = etag.strip('"')
                            if not etag:
                                # fabricating a local md5 here would turn a
                                # proxy quirk into a confusing InvalidPart
                                # at complete time — fail where the cause is
                                raise RuntimeError(
                                    f"part {part_number}: response has no "
                                    "ETag header"
                                )
                            if progress is not None:
                                # inside the semaphore on purpose: a
                                # pacing sleep in the callback holds this
                                # part's slot, throttling the pool to the
                                # configured egress rate
                                await progress(length)
                            return part_number, etag
                        last = RuntimeError(
                            f"part {part_number}: {status} {body!r}"
                        )
                    except (aiohttp.ClientError, OSError,
                            ConnectionError, ValueError,
                            IndexError) as err:
                        if (isinstance(err, OSError)
                                and err.errno == errno.ENOSPC):
                            # local disk full reading/staging the part:
                            # every further attempt re-reads the same
                            # full volume.  Fail fast PERMANENT so the
                            # retry budget isn't burned and the caller's
                            # except-path AbortMultipartUpload drops the
                            # already-stored parts NOW (no orphans
                            # billing storage with no visible object)
                            raise tag_fault(err, PERMANENT)
                        if getattr(err, "fault_class", None) == PERMANENT:
                            # explicitly pre-classified (injected disk
                            # faults, status-coded errors): fail fast.
                            # NOT classify()-based — a bare ValueError/
                            # IndexError here is a zero-copy slice quirk
                            # whose cure IS the buffered retry below.
                            raise err
                        last = err
                        # a zero-copy transport error retries on the
                        # buffered path — correctness never depends on
                        # the fast path working
                        buffered = True
                    await asyncio.sleep(0.2 * (attempt + 1))
                raise RuntimeError(
                    f"part {part_number} failed after retries: {last}"
                )

        tasks = [
            asyncio.create_task(_one(n)) for n in range(1, part_count + 1)
        ]
        try:
            results = await asyncio.gather(*tasks)
        except BaseException:
            # settle the siblings BEFORE the caller aborts the upload: a
            # part PUT landing after AbortMultipartUpload re-creates
            # orphaned (billed) parts on real S3
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        finally:
            if source_map is not None:
                try:
                    source_map.close()
                except BufferError:
                    pass  # a straggler view: dropped with the map by gc
        return sorted(results)

    async def stat_object(self, bucket: str, name: str) -> ObjectInfo:
        resp = await self._request("HEAD", self._object_path(bucket, name))
        resp.release()
        if resp.status == 404:
            raise ObjectNotFound(bucket, name)
        if resp.status != 200:
            raise _status_error("stat_object", resp.status)
        # S3 ETag: MD5 hex for single-part uploads, md5-of-part-md5s with
        # a ``-N`` suffix for multipart — exposed verbatim; callers that
        # verify content handle both forms (see stages/upload.py
        # _already_staged / utils.hashing.multipart_etag_hex)
        etag = resp.headers.get("ETag", "").strip('"')
        return ObjectInfo(
            name=name,
            size=int(resp.headers.get("Content-Length", 0)),
            etag=etag,
        )

    async def list_objects(self, bucket: str, prefix: str = "") -> AsyncIterator[ObjectInfo]:
        token: Optional[str] = None
        while True:
            query = {"list-type": "2", "prefix": prefix}
            if token:
                query["continuation-token"] = token
            resp = await self._request("GET", f"/{bucket}", query=query)
            body = await resp.read()
            if resp.status == 404:
                raise ObjectNotFound(bucket, prefix)
            if resp.status != 200:
                raise _status_error("list_objects", resp.status, body)

            root = ET.fromstring(body)
            ns = ""
            if root.tag.startswith("{"):
                ns = root.tag[: root.tag.index("}") + 1]
            for contents in root.findall(f"{ns}Contents"):
                key = contents.findtext(f"{ns}Key") or ""
                size = int(contents.findtext(f"{ns}Size") or 0)
                yield ObjectInfo(name=key, size=size)

            truncated = (root.findtext(f"{ns}IsTruncated") or "false") == "true"
            token = root.findtext(f"{ns}NextContinuationToken")
            if not truncated or not token:
                break
