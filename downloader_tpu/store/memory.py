"""Hermetic in-memory object store (the test fake for MinIO/S3)."""

from __future__ import annotations

import asyncio
import hashlib
import os
from typing import AsyncIterator, Dict

from .base import ObjectInfo, ObjectNotFound, ObjectStore


class InMemoryObjectStore(ObjectStore):
    def __init__(self) -> None:
        self._buckets: Dict[str, Dict[str, bytes]] = {}
        self._lock = asyncio.Lock()

    async def bucket_exists(self, bucket: str) -> bool:
        return bucket in self._buckets

    async def make_bucket(self, bucket: str) -> None:
        async with self._lock:
            self._buckets.setdefault(bucket, {})

    def _bucket(self, bucket: str, name: str = "") -> Dict[str, bytes]:
        try:
            return self._buckets[bucket]
        except KeyError:
            raise ObjectNotFound(bucket, name) from None

    async def get_object(self, bucket: str, name: str) -> bytes:
        objects = self._bucket(bucket, name)
        try:
            return objects[name]
        except KeyError:
            raise ObjectNotFound(bucket, name) from None

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        async with self._lock:
            self._buckets.setdefault(bucket, {})[name] = bytes(data)

    async def fget_object(self, bucket: str, name: str, file_path: str,
                          *, progress=None) -> None:
        data = await self.get_object(bucket, name)
        os.makedirs(os.path.dirname(os.path.abspath(file_path)), exist_ok=True)
        await asyncio.to_thread(_write_file, file_path, data)
        if progress is not None:
            await progress(len(data))

    async def fput_object(self, bucket: str, name: str, file_path: str,
                          *, consume: bool = False) -> None:
        data = await asyncio.to_thread(_read_file, file_path)
        await self.put_object(bucket, name, data)

    async def list_objects(self, bucket: str, prefix: str = "") -> AsyncIterator[ObjectInfo]:
        objects = self._buckets.get(bucket, {})
        for name in sorted(objects):
            if name.startswith(prefix):
                yield ObjectInfo(name=name, size=len(objects[name]))

    async def stat_object(self, bucket: str, name: str) -> ObjectInfo:
        data = await self.get_object(bucket, name)
        return ObjectInfo(
            name=name, size=len(data), etag=hashlib.md5(data).hexdigest()
        )

    async def remove_object(self, bucket: str, name: str) -> None:
        async with self._lock:
            self._buckets.get(bucket, {}).pop(name, None)

    async def get_object_versioned(self, bucket: str, name: str):
        async with self._lock:
            objects = self._bucket(bucket, name)
            try:
                data = objects[name]
            except KeyError:
                raise ObjectNotFound(bucket, name) from None
            return data, hashlib.md5(data).hexdigest()

    async def put_object_cas(self, bucket: str, name: str, data: bytes, *,
                             if_match=None, if_none_match=False):
        # the whole compare+swap under one lock: this fake is the
        # reference semantics the MiniS3 412 path must agree with
        async with self._lock:
            objects = self._buckets.setdefault(bucket, {})
            current = objects.get(name)
            if if_none_match:
                if current is not None:
                    return None
            elif if_match is not None:
                if current is None:
                    return None
                if hashlib.md5(current).hexdigest() != if_match:
                    return None
            objects[name] = bytes(data)
            return hashlib.md5(objects[name]).hexdigest()


def _write_file(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)


def _read_file(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()
