"""Content-addressed staging cache with singleflight coalescing.

The reference service treats every ``v1.download`` job as independent:
ten jobs for the same popular episode fetch, filter, and upload the same
bytes ten times (its only dedup is the post-hoc idempotency probe on
*completed* jobs, lib/main.js:119-124).  Under fan-in load the hot path
is redundant network and disk I/O.  This module removes both:

- :class:`ContentCache` — completed downloads kept on disk, keyed by
  content identity (torrent infohash, or URL + RFC-7232 validator).
  Entries materialize into job workdirs by hardlink (O(1)) with a byte
  copy as the cross-device fallback, and are evicted LRU against a
  configurable disk budget.
- :class:`Singleflight` — a job arriving while the same key is already
  mid-download awaits the in-flight fetch instead of starting its own;
  the leader's progress is re-broadcast so each waiter can re-emit it
  through its own telemetry channel.

Crash safety: an entry is only ever visible once its directory — with
the ``.meta.json`` manifest inside — has been atomically renamed into
place.  Fills stage under ``staging/`` with pid-tagged names; a crashed
fill leaves a staging dir that the next construction sweeps via the
shared pid-probe policy (``utils/stale.py``).  Eviction deletes the
manifest first, so a crash mid-evict leaves a manifest-less dir that the
sweep also reclaims — a partial entry is never served.

Eviction while an entry is being read is safe by construction: entries
materialize via hardlink, so unlinking the cache's copy never invalidates
bytes already linked into a workdir; a mid-materialize eviction is
additionally excluded by pinning.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import itertools
import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

from ..utils.disk import free_bytes
from ..utils.stale import probe_stale

META_NAME = ".meta.json"

# default disk budget for cached content (overridable via config/env)
DEFAULT_MAX_BYTES = 10 << 30
# default free-disk floor the orchestrator's admission gate maintains on
# the cache volume before starting a new job
DEFAULT_MIN_FREE_BYTES = 256 << 20


def resolve_cache_path(config) -> str:
    """Where the content cache lives on disk, resolved exactly as
    :meth:`ContentCache.from_config` does: ``CACHE_DIR`` /
    ``instance.cache.path``, defaulting to ``<download_path>/.cache``,
    relative paths anchored at the repo root.

    Shared with the orchestrator's boot workdir sweep, which must
    PROTECT this directory — two divergent copies of the resolution
    would eventually let the sweep rmtree the whole LRU cache.
    """
    from ..platform.config import cfg_get

    path = os.environ.get("CACHE_DIR") or cfg_get(
        config, "instance.cache.path", None
    )
    if not path:
        # default beside the per-job download dirs; dot-prefixed so it
        # can never collide with a media-id workdir
        configured = cfg_get(
            config, "instance.download_path", "downloading"
        )
        path = os.path.join(configured, ".cache")
    if not os.path.isabs(path):
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        path = os.path.join(repo_root, path)
    return path


def cache_key(*parts: str) -> str:
    """Stable content key from identity parts (protocol, locator,
    validator).  SHA-256 so hostile URLs cannot craft path segments."""
    joined = "\x00".join(parts)
    return hashlib.sha256(joined.encode("utf-8", "surrogatepass")).hexdigest()


class _Flight:
    """One in-flight fetch: waiters block on ``wait``; the leader feeds
    ``report`` and finally ``resolve``/``reject``."""

    __slots__ = ("key", "progress", "waiters", "_done", "_error", "_resolved")

    def __init__(self, key: str):
        self.key = key
        self.progress: Optional[int] = None
        self.waiters: int = 0
        self._done = asyncio.Event()
        self._error: Optional[BaseException] = None
        self._resolved = False

    def report(self, percent: int) -> None:
        """Leader-side progress (0-100 of the download band).  Waiters
        observing the change re-emit through their own telemetry."""
        if percent != self.progress:
            self.progress = percent
            # wake waiters without ending the flight: set-and-clear makes
            # Event double as a broadcast condition (every current waiter
            # of .wait() is released on set())
            self._done.set()
            if self._error is None and not self._finished():
                self._done.clear()

    def _finished(self) -> bool:
        return self._error is not None or self._resolved

    def resolve(self) -> None:
        self._resolved = True
        self._done.set()

    def reject(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    async def wait(
        self, on_progress: Optional[Callable[[int], Any]] = None
    ) -> None:
        """Block until the leader settles; re-emit each progress change
        via ``on_progress`` (may be a coroutine function).  Raises
        :class:`LeaderFailed` when the leader errored — the waiter should
        retry (and may become the new leader)."""
        last = None
        while True:
            await self._done.wait()
            if self.progress is not None and self.progress != last:
                last = self.progress
                if on_progress is not None:
                    result = on_progress(self.progress)
                    if asyncio.iscoroutine(result):
                        await result
            if self._error is not None:
                raise LeaderFailed(self.key) from self._error
            if self._resolved:
                return
            # progress-only wakeup: re-arm and keep waiting
            self._done.clear()


class LeaderFailed(Exception):
    """The in-flight fetch this waiter coalesced onto failed; retry."""


class Singleflight:
    """Per-process fan-in coalescing keyed by content key.

    ``run(key, fetch, on_wait_progress)`` returns True when this caller
    became the leader and ran ``fetch`` (which receives a
    ``report(percent)`` callable), False when it awaited a concurrent
    caller's in-flight fetch.  A leader failure releases the waiters to
    retry — the next one through becomes the new leader, so one
    transient error never fails the whole fan-in.
    """

    def __init__(self):
        self._inflight: Dict[str, _Flight] = {}

    def flight(self, key: str) -> Optional[_Flight]:
        return self._inflight.get(key)

    async def run(
        self,
        key: str,
        fetch: Callable[[Callable[[int], None]], Any],
        on_wait_progress: Optional[Callable[[int], Any]] = None,
    ) -> bool:
        """Coalesce ``fetch`` under ``key``.  Returns True when this
        caller led the fetch, False when it waited on another's."""
        while True:
            flight = self._inflight.get(key)
            if flight is None:
                flight = _Flight(key)
                self._inflight[key] = flight
                try:
                    await fetch(flight.report)
                except BaseException as err:
                    flight.reject(err)
                    raise
                else:
                    flight.resolve()
                    return True
                finally:
                    self._inflight.pop(key, None)
            else:
                flight.waiters += 1
                try:
                    await flight.wait(on_progress=on_wait_progress)
                    return False
                except LeaderFailed:
                    continue  # retry: may become the new leader


class CacheEntry:
    __slots__ = ("key", "size", "files", "digests")

    def __init__(self, key: str, size: int, files: List[str],
                 digests: Optional[Dict[str, str]] = None):
        self.key = key
        self.size = size
        self.files = files  # entry-relative paths
        # per-file landing digests (rel -> md5 hex) when the fill
        # carried them: the integrity scrubber's ground truth, and the
        # shared-tier manifest's provenance for fetch-time verification
        self.digests = digests or {}


class ContentCache:
    """Disk-backed content-addressed cache of completed downloads.

    Layout::

        <root>/entries/<key>/            completed content + .meta.json
        <root>/staging/<key>.<pid>.<n>/  in-flight fill (swept if orphaned)

    All filesystem work runs under ``asyncio.to_thread``; metadata
    decisions (lookup/insert/evict bookkeeping) happen on the event loop
    guarded by one lock, so sizes and pins never race.
    """

    def __init__(self, root: str, max_bytes: int = DEFAULT_MAX_BYTES,
                 min_free_bytes: int = DEFAULT_MIN_FREE_BYTES,
                 logger=None):
        self.root = os.path.abspath(root)
        self.max_bytes = int(max_bytes)
        self.min_free_bytes = int(min_free_bytes)
        self.logger = logger
        # optional Metrics handle (attached by the orchestrator): letting
        # the cache count its own evictions covers EVERY trigger —
        # fill-time budget enforcement as well as admission reclaim
        self.metrics = None
        self.entries_dir = os.path.join(self.root, "entries")
        self.staging_dir = os.path.join(self.root, "staging")
        os.makedirs(self.entries_dir, exist_ok=True)
        os.makedirs(self.staging_dir, exist_ok=True)
        self._seq = itertools.count()
        self._lock = asyncio.Lock()
        self._pins: Dict[str, int] = {}
        self._sweep_orphans()

    # -- config ---------------------------------------------------------
    @classmethod
    def from_config(cls, config, logger=None) -> Optional["ContentCache"]:
        """Build from ``instance.cache.*`` / env; None when disabled.

        Knobs: ``CACHE_DIR``/``instance.cache.path`` (enabling the cache
        by giving it a home), ``instance.cache.enabled`` (explicit
        toggle), ``CACHE_MAX_BYTES``/``instance.cache.max_bytes`` (LRU
        disk budget), ``CACHE_MIN_FREE_BYTES``/
        ``instance.cache.min_free_bytes`` (admission headroom floor).
        """
        from ..platform.config import cfg_get

        enabled = os.environ.get("CACHE_ENABLED")
        if enabled is None:
            enabled = cfg_get(config, "instance.cache.enabled", None)
        else:
            enabled = enabled.lower() in ("1", "true", "yes")
        explicit = os.environ.get("CACHE_DIR") or cfg_get(
            config, "instance.cache.path", None
        )
        # a configured path implies enabled unless explicitly disabled
        if enabled is False or (enabled is None and not explicit):
            return None
        path = resolve_cache_path(config)
        max_bytes = int(
            os.environ.get("CACHE_MAX_BYTES")
            or cfg_get(config, "instance.cache.max_bytes", DEFAULT_MAX_BYTES)
        )
        min_free = int(
            os.environ.get("CACHE_MIN_FREE_BYTES")
            or cfg_get(config, "instance.cache.min_free_bytes",
                       DEFAULT_MIN_FREE_BYTES)
        )
        return cls(path, max_bytes=max_bytes, min_free_bytes=min_free,
                   logger=logger)

    # -- internals ------------------------------------------------------
    def _entry_dir(self, key: str) -> str:
        if not key or any(c not in "0123456789abcdef" for c in key):
            raise ValueError(f"cache key must be lowercase hex, got {key!r}")
        return os.path.join(self.entries_dir, key)

    def _read_meta(self, key: str) -> Optional[dict]:
        try:
            with open(os.path.join(self._entry_dir(key), META_NAME)) as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(meta, dict) or meta.get("state") != "complete":
            return None
        return meta

    def _sweep_orphans(self) -> None:
        """Reclaim crashed fills and half-evicted entries (startup only).

        A staging dir's name carries the pid that owned the fill; the
        shared stale policy (live-pid immunity, NFS grace) judges it.  An
        entries/<key> dir without a valid manifest is a crashed evict or
        a torn rename — never servable, always reclaimable.
        """
        for name in _listdir(self.staging_dir):
            full = os.path.join(self.staging_dir, name)
            parts = name.rsplit(".", 2)
            pid = int(parts[1]) if len(parts) == 3 and parts[1].isdigit() else 0
            stale, _age = probe_stale(full, pid, grace=0.0) if pid else (True, None)
            if stale or not pid:
                shutil.rmtree(full, ignore_errors=True)
        for name in _listdir(self.entries_dir):
            if self._read_meta(name) is None:
                shutil.rmtree(os.path.join(self.entries_dir, name),
                              ignore_errors=True)

    def _entry_from_meta(self, key: str, meta: dict) -> CacheEntry:
        digests = meta.get("digests")
        return CacheEntry(key=key, size=int(meta.get("size", 0)),
                          files=list(meta.get("files", [])),
                          digests=dict(digests)
                          if isinstance(digests, dict) else None)

    # -- introspection --------------------------------------------------
    def total_bytes(self) -> int:
        """Sum of completed entry sizes (manifest figures)."""
        total = 0
        for name in _listdir(self.entries_dir):
            meta = self._read_meta(name)
            if meta:
                total += int(meta.get("size", 0))
        return total

    def free_disk_bytes(self) -> int:
        return free_bytes(self.root)

    def has_headroom(self) -> bool:
        """True when the cache volume holds the admission floor."""
        return self.free_disk_bytes() >= self.min_free_bytes

    def keys(self) -> List[str]:
        """Completed entry keys on disk — the scrubber's walk
        inventory (thread-side; call via ``asyncio.to_thread``)."""
        return [name for name in _listdir(self.entries_dir)
                if self._read_meta(name) is not None]

    async def peek(self, key: str) -> Optional[CacheEntry]:
        """Like :meth:`lookup` but WITHOUT the LRU touch: a scrubber
        walk must not promote every entry it verifies to
        most-recently-used (that would turn eviction order into scan
        order)."""
        meta = await asyncio.to_thread(self._read_meta, key)
        if meta is None:
            return None
        return self._entry_from_meta(key, meta)

    def entry_path(self, key: str) -> str:
        """Absolute directory of entry ``key`` (the fleet shared tier
        reads entry files from here when spilling; existence is the
        caller's problem — pair with :meth:`lookup`/:meth:`pinned`)."""
        return self._entry_dir(key)

    @contextlib.asynccontextmanager
    async def pinned(self, key: str):
        """Hold an eviction pin on ``key`` for the duration of the
        block — the same protection :meth:`materialize` takes while
        hardlinking, exposed for external readers (the fleet tier's
        spill streams entry files to the staging bucket)."""
        async with self._lock:
            self._pins[key] = self._pins.get(key, 0) + 1
        try:
            yield
        finally:
            async with self._lock:
                count = self._pins.get(key, 1) - 1
                if count <= 0:
                    self._pins.pop(key, None)
                else:
                    self._pins[key] = count

    # -- operations -----------------------------------------------------
    async def lookup(self, key: str) -> Optional[CacheEntry]:
        """Completed entry for ``key``, LRU-touched; None on miss."""
        async with self._lock:
            meta = await asyncio.to_thread(self._read_meta, key)
            if meta is None:
                return None
            # LRU clock = manifest mtime; touching it is one utime
            try:
                os.utime(os.path.join(self._entry_dir(key), META_NAME))
            except OSError:
                pass
            return self._entry_from_meta(key, meta)

    async def materialize(self, key: str, dest_dir: str) -> Optional[int]:
        """Hardlink-or-copy entry ``key``'s files into ``dest_dir``;
        returns bytes materialized, None when the entry vanished
        (see :meth:`materialize_entry`)."""
        got = await self.materialize_entry(key, dest_dir)
        return got[0] if got is not None else None

    async def materialize_entry(
        self, key: str, dest_dir: str
    ) -> "Optional[tuple[int, list]]":
        """Hardlink-or-copy entry ``key``'s files into ``dest_dir``.

        Returns ``(bytes, dest_paths)`` — the absolute paths just
        materialized, so a cache-hit job can be served from the known
        list without re-walking the workdir — or None when the entry
        vanished (evicted between lookup and use); the caller treats
        that as a miss.  Never exposes a partial workdir: files land
        under a temp name in ``dest_dir`` and rename into place only
        after every file linked; a lost race leaves only temp droppings
        in the job's own workdir, which the job overwrites or the
        upload-stage cleanup removes with the directory.
        """
        async with self.pinned(key):
            # pin BEFORE the manifest read: once pinned the entry
            # cannot be evicted between the read and the links
            async with self._lock:
                meta = await asyncio.to_thread(self._read_meta, key)
                if meta is None:
                    return None
                entry = self._entry_from_meta(key, meta)
            src_dir = self._entry_dir(key)

            def _link_all() -> bool:
                staged = []
                for rel in entry.files:
                    src = os.path.join(src_dir, *rel.split("/"))
                    dst = os.path.join(dest_dir, *rel.split("/"))
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    tmp = f"{dst}.cachetmp.{os.getpid()}.{next(self._seq)}"
                    try:
                        try:
                            os.link(src, tmp)
                        except OSError as err:
                            import errno
                            if err.errno in (errno.EXDEV, errno.EPERM,
                                             errno.EMLINK):
                                shutil.copyfile(src, tmp)
                            else:
                                raise
                    except FileNotFoundError:
                        for t in staged:
                            _unlink_quiet(t[0])
                        return False  # entry evicted under us: miss
                    staged.append((tmp, dst))
                for tmp, dst in staged:
                    os.replace(tmp, dst)
                return True

            ok = await asyncio.to_thread(_link_all)
            if not ok:
                return None
            dests = [os.path.join(dest_dir, *rel.split("/"))
                     for rel in entry.files]
            return entry.size, dests

    async def insert(self, key: str, src_dir: str,
                     digests: Optional[Dict[str, str]] = None
                     ) -> Optional[CacheEntry]:
        """Fill ``key`` from a completed job workdir.

        Hardlinks (or copies) every regular file under ``src_dir`` into a
        staging dir, writes the manifest inside it, then atomically
        renames the whole dir into ``entries/``.  Dotfiles and in-flight
        temp suffixes (``.partial``/``.partial.meta``/segment state) are
        skipped — only verified payload is cacheable.  ``digests``
        (entry-relative path -> md5 hex, from the landing-site hash)
        rides the manifest so the integrity scrubber — and shared-tier
        fetchers — can re-verify these bytes forever without a trusted
        re-read.  Returns the new entry, or None when there was nothing
        to cache or the key lost an insert race (another leader's fill
        is equally valid).
        """
        async with self._lock:
            if await asyncio.to_thread(self._read_meta, key) is not None:
                return None  # already filled
        staging = os.path.join(
            self.staging_dir, f"{key}.{os.getpid()}.{next(self._seq)}"
        )

        def _stage() -> Optional[dict]:
            files: List[str] = []
            size = 0
            for dirpath, _dirnames, filenames in os.walk(src_dir):
                for name in sorted(filenames):
                    if name.startswith(".") or _is_transient(name):
                        continue
                    full = os.path.join(dirpath, name)
                    rel = os.path.relpath(full, src_dir).replace(os.sep, "/")
                    dst = os.path.join(staging, *rel.split("/"))
                    os.makedirs(os.path.dirname(dst), exist_ok=True)
                    try:
                        os.link(full, dst)
                    except OSError:
                        shutil.copyfile(full, dst)
                    files.append(rel)
                    size += os.path.getsize(dst)
            if not files:
                shutil.rmtree(staging, ignore_errors=True)
                return None
            meta = {
                "state": "complete",
                "key": key,
                "size": size,
                "files": files,
                "created": time.time(),
            }
            if digests:
                meta["digests"] = {rel: digests[rel] for rel in files
                                   if rel in digests}
            # manifest rides INSIDE the dir: one rename publishes entry
            # and manifest together, so a torn publish is impossible
            tmp = os.path.join(staging, META_NAME + ".tmp")
            with open(tmp, "w") as fh:
                json.dump(meta, fh)
            os.replace(tmp, os.path.join(staging, META_NAME))
            return meta

        try:
            meta = await asyncio.to_thread(_stage)
        except OSError:
            await asyncio.to_thread(shutil.rmtree, staging, True)
            raise
        if meta is None:
            return None
        async with self._lock:
            entry_dir = self._entry_dir(key)

            def _publish() -> bool:
                try:
                    os.rename(staging, entry_dir)
                    return True
                except OSError:
                    # lost the insert race (or dir exists from a crashed
                    # evict): keep the existing entry, drop ours
                    shutil.rmtree(staging, ignore_errors=True)
                    return False

            if not await asyncio.to_thread(_publish):
                return None
        # budget enforcement AFTER publish: the new entry participates in
        # LRU like any other (and is the most recently used)
        await self.evict_to_budget()
        return self._entry_from_meta(key, meta)

    async def quarantine(self, key: str, dest_dir: Optional[str]) -> bool:
        """Move entry ``key`` out of the cache for triage (integrity
        scrub verdict: corrupt with no healthy replica).  One rename
        retires the whole directory — manifest included, so the
        quarantined copy stays inspectable — and the entry is
        invisible the instant the rename lands (the same one-rename
        discipline as publish/evict).  ``dest_dir`` None just evicts.
        Pinned (mid-materialize) entries are left alone: False."""
        async with self._lock:
            if self._pins.get(key):
                return False
            entry_dir = self._entry_dir(key)

            def _move() -> bool:
                if not os.path.isdir(entry_dir):
                    return False
                if not dest_dir:
                    _unlink_quiet(os.path.join(entry_dir, META_NAME))
                    shutil.rmtree(entry_dir, ignore_errors=True)
                    return True
                dest = os.path.join(dest_dir,
                                    f"{key}.{int(time.time())}")
                try:
                    os.makedirs(dest_dir, exist_ok=True)
                    os.rename(entry_dir, dest)
                    return True
                except OSError:
                    # cross-device quarantine volume: fall back to the
                    # evict discipline (manifest first) rather than
                    # leave corrupt bytes servable
                    _unlink_quiet(os.path.join(entry_dir, META_NAME))
                    shutil.move(entry_dir, dest)
                    return True

            try:
                return await asyncio.to_thread(_move)
            except OSError:
                return False

    async def evict_to_budget(self, extra_needed: int = 0) -> int:
        """LRU-evict until total size fits ``max_bytes - extra_needed``
        AND the volume's free space covers ``min_free_bytes``.  Returns
        bytes evicted.  Pinned (mid-materialize) entries are skipped."""
        async with self._lock:
            def _scan() -> List[tuple]:
                found = []
                for name in _listdir(self.entries_dir):
                    meta = self._read_meta(name)
                    if meta is None:
                        continue
                    try:
                        mtime = os.path.getmtime(
                            os.path.join(self._entry_dir(name), META_NAME))
                    except OSError:
                        mtime = 0.0
                    found.append((mtime, name, int(meta.get("size", 0))))
                found.sort()
                return found

            entries = await asyncio.to_thread(_scan)
            total = sum(size for _m, _n, size in entries)
            budget = max(self.max_bytes - extra_needed, 0)
            evicted = 0
            for _mtime, name, size in entries:
                over_budget = total > budget
                no_headroom = self.free_disk_bytes() < self.min_free_bytes
                if not over_budget and not no_headroom:
                    break
                if self._pins.get(name):
                    continue

                def _remove(name=name) -> None:
                    entry_dir = self._entry_dir(name)
                    # manifest FIRST: the entry turns invisible before
                    # any content byte disappears, so a crash mid-rmtree
                    # can never leave a servable half-entry
                    _unlink_quiet(os.path.join(entry_dir, META_NAME))
                    shutil.rmtree(entry_dir, ignore_errors=True)

                await asyncio.to_thread(_remove)
                total -= size
                evicted += size
                if self.logger is not None:
                    self.logger.info("cache: evicted entry", key=name,
                                     bytes=size)
            if evicted and self.metrics is not None:
                self.metrics.cache_evicted_bytes.inc(evicted)
            return evicted


def _is_transient(name: str) -> bool:
    """In-flight download artifacts that must never be cached."""
    return name.endswith((
        ".partial", ".partial.meta", ".partial-seg", ".partial-seg.state",
        ".resume", ".tmp",
    )) or ".cachetmp." in name or ".scrubtmp." in name


def _listdir(path: str) -> List[str]:
    try:
        return os.listdir(path)
    except OSError:
        return []


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass
