"""Filesystem-backed object store.

A durable local backend with the same interface as the in-memory fake:
objects live at ``<root>/<bucket>/<name>`` with ``/`` in object names mapped
to directories.  Useful for running the full service on one machine without
a MinIO server, and for tests that want to inspect staged bytes on disk.
"""

from __future__ import annotations

import asyncio
import collections
import hashlib
import itertools
import os
import re
import shutil
import time
from typing import AsyncIterator, Optional

from .base import ObjectInfo, ObjectNotFound, ObjectStore
from ..platform import vfs
from ..utils.stale import STALE_GRACE_S as _STALE_GRACE_S
from ..utils.stale import STALE_MAX_AGE_S as _STALE_MAX_AGE_S
from ..utils.stale import probe_stale

# in-flight ingest temp name: <dst>.tmp.<pid>.<counter> (fput_object)
_TMP_RE = re.compile(r"\.tmp\.(\d+)\.\d+$")


def _is_stale_tmp(filename: str, path: str) -> bool:
    """True for an ingest temp whose writer is provably gone.

    A put interrupted by SIGKILL/power loss leaves its per-call-unique
    temp behind with nothing to reclaim it.  Policy (grace for cross-
    host NFS writers, live-pid immunity, day-scale bound on
    inconclusive probes) is shared with the transcoder's part-files —
    see :func:`downloader_tpu.utils.stale.probe_stale`."""
    match = _TMP_RE.search(filename)
    if match is None:
        return False
    stale, _age = probe_stale(path, int(match.group(1)))
    return stale


_warned_foreign: set = set()


def _warn_foreign_key(path: str, age: float) -> None:
    """A temp-patterned file the sweep will never reclaim (its pid field
    probes live, so it never goes stale) yet far older than any real
    ingest could run is almost certainly a foreign object key from a
    store predating the reserved-suffix scheme.  It is hidden from
    listings and unreachable by get/put — surface it once per process so
    operators know to migrate it (advisor r4)."""
    if path in _warned_foreign:
        return
    _warned_foreign.add(path)
    from ..platform.logging import get_logger

    get_logger("store.fs").warn(
        "ignoring temp-suffixed file that looks like a foreign object "
        "key (hidden from listings; rename to migrate)",
        path=path, age_s=round(age),
    )


def _safe_parts(name: str) -> list:
    parts = [p for p in name.split("/") if p not in ("", ".")]
    if any(p == ".." for p in parts):
        raise ValueError(f"object name {name!r} escapes the bucket")
    if parts and _TMP_RE.search(parts[-1]):
        # the ingest-temp suffix is a reserved namespace: without this, a
        # user key matching it would be hidden from list_objects and
        # silently reclaimed by the constructor sweep (review r4)
        raise ValueError(
            f"object name {name!r} uses the reserved ingest-temp suffix"
        )
    return parts


class FilesystemObjectStore(ObjectStore):
    """:meth:`fput_object` can ingest a same-filesystem source by
    hardlink instead of a byte copy — O(1) instead of O(size), which
    roughly halves end-to-end staging time (the upload stage was the
    pipeline's most expensive hop).  Linking requires BOTH the per-call
    ``consume=True`` (the caller's promise it stops mutating the source,
    e.g. the upload stage, which deletes its download directory right
    afterwards — reference lib/upload.js:60-64) AND the store-level
    ``link_puts`` switch (default True); a plain ``fput_object`` always
    byte-copies, so callers that keep using the source cannot silently
    alias the stored object.  Objects themselves are always replaced
    atomically, never edited in place, so linking never aliases
    store-side writes.  Cross-device sources (or filesystems without
    hardlinks) transparently fall back to a copy.

    Object keys whose final segment matches the ingest-temp pattern
    (``*.tmp.<digits>.<digits>``) are a reserved namespace: rejected on
    write, filtered from listings, and reclaimable by the orphan sweep.
    The pipeline itself never produces such names (staged objects are
    ``<id>/original/<base64>`` plus ``done``); a FOREIGN store carrying
    such keys from before this scheme should rename them before
    pointing this driver at it."""

    # etag memo capacity: ~a day of staging churn; FIFO eviction (a miss
    # just re-hashes, so the only cost of an eviction is one read pass)
    _MEMO_CAP = 4096

    def __init__(self, root: str, link_puts: bool = True):
        self.root = os.path.abspath(root)
        self.link_puts = link_puts
        self._tmp_seq = itertools.count()
        # per-directory sweep clocks: the per-put orphan reclaim is
        # rate-limited so a bulk ingest into one big directory pays
        # O(listdir) once per grace period, not per put (review r4)
        self._swept: dict = {}
        # etag memo (hash-on-land): ``path -> ((size, mtime_ns, ino),
        # md5_hex)``.  Objects are only ever replaced atomically, never
        # edited in place, so a matching stat signature proves the bytes
        # are the ones the memoized digest was computed over — stat_object
        # answers without re-reading the whole object (the r3-r5 second
        # pass).  Writers seed it: fput_object from the caller's landed
        # digest (``content_md5``), put_object from the in-memory body.
        self._md5_memo: "collections.OrderedDict" = collections.OrderedDict()
        os.makedirs(self.root, exist_ok=True)

    def _memo_signature(self, path: str) -> Optional[tuple]:
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_size, st.st_mtime_ns, st.st_ino)

    def _memo_store(self, path: str, md5_hex: str) -> None:
        signature = self._memo_signature(path)
        if signature is None:
            return
        self._md5_memo[path] = (signature, md5_hex)
        self._md5_memo.move_to_end(path)
        while len(self._md5_memo) > self._MEMO_CAP:
            self._md5_memo.popitem(last=False)

    def _memo_lookup(self, path: str) -> Optional[str]:
        entry = self._md5_memo.get(path)
        if entry is None:
            return None
        signature, md5_hex = entry
        if signature != self._memo_signature(path):
            # replaced since memoization (or gone): drop the stale digest
            self._md5_memo.pop(path, None)
            return None
        return md5_hex

    def _should_sweep(self, path: str) -> bool:
        dirpath = os.path.dirname(path)
        now = time.monotonic()
        if now - self._swept.get(dirpath, -_STALE_GRACE_S) < _STALE_GRACE_S:
            return False
        if len(self._swept) >= 1024:
            # the ingest layout mints a directory per object id, so the
            # clock dict would grow forever in a long-lived process —
            # evict expired entries (their absence just means one extra
            # sweep later)
            cutoff = now - _STALE_GRACE_S
            self._swept = {d: t for d, t in self._swept.items()
                           if t > cutoff}
        self._swept[dirpath] = now
        return True

    def _bucket_path(self, bucket: str) -> str:
        (part,) = _safe_parts(bucket) or [""]
        return os.path.join(self.root, part)

    def _object_path(self, bucket: str, name: str) -> str:
        return os.path.join(self._bucket_path(bucket), *_safe_parts(name))

    async def bucket_exists(self, bucket: str) -> bool:
        return await asyncio.to_thread(os.path.isdir, self._bucket_path(bucket))

    async def make_bucket(self, bucket: str) -> None:
        await asyncio.to_thread(os.makedirs, self._bucket_path(bucket), exist_ok=True)

    async def get_object(self, bucket: str, name: str) -> bytes:
        path = self._object_path(bucket, name)
        try:
            return await asyncio.to_thread(_read_file, path)
        except (FileNotFoundError, IsADirectoryError):
            raise ObjectNotFound(bucket, name) from None

    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        path = self._object_path(bucket, name)
        # same unique reclaimable temp naming as fput_object: a bare
        # '<path>.tmp' orphaned by SIGKILL would be enumerated as an
        # object forever (review r4)
        await asyncio.to_thread(
            _write_file_atomic, path, data,
            f"{os.getpid()}.{next(self._tmp_seq)}",
            self._should_sweep(path),
        )
        # the body is already in memory — hashing it here makes the
        # later stat_object free instead of a full read pass
        self._memo_store(path, hashlib.md5(data).hexdigest())

    async def fget_object(self, bucket: str, name: str, file_path: str,
                          *, progress=None) -> None:
        src = self._object_path(bucket, name)
        if not await asyncio.to_thread(os.path.isfile, src):
            raise ObjectNotFound(bucket, name)
        os.makedirs(os.path.dirname(os.path.abspath(file_path)), exist_ok=True)
        await asyncio.to_thread(shutil.copyfile, src, file_path)
        if progress is not None:
            await progress(
                await asyncio.to_thread(os.path.getsize, file_path))

    async def fput_object(self, bucket: str, name: str, file_path: str,
                          *, consume: bool = False,
                          content_md5: Optional[str] = None) -> None:
        dst = self._object_path(bucket, name)
        await asyncio.to_thread(
            _ingest_file_atomic, file_path, dst,
            self.link_puts and consume,
            # pid+counter: two concurrent puts of the same key in one
            # process must not share a tmp name (unlink/link/replace
            # would race and one put would die with FileNotFoundError)
            f"{os.getpid()}.{next(self._tmp_seq)}",
            self._should_sweep(dst),
        )
        if content_md5:
            # hash-on-land hint: the caller digested these exact bytes
            # at their landing moment (and a hardlinked ingest IS the
            # same inode), so stat_object can answer without ever
            # re-reading the object
            self._memo_store(dst, content_md5)

    async def list_objects(self, bucket: str, prefix: str = "") -> AsyncIterator[ObjectInfo]:
        bucket_path = self._bucket_path(bucket)

        def _walk() -> list:
            found = []
            for dirpath, _dirnames, filenames in os.walk(bucket_path):
                for filename in filenames:
                    full = os.path.join(dirpath, filename)
                    match = _TMP_RE.search(filename)
                    if match:
                        # in-flight/orphaned ingest temp, never an
                        # object; reclaim orphans opportunistically —
                        # piggybacking on this walk keeps the sweep
                        # free (no constructor-time full-tree scan)
                        stale, age = probe_stale(full, int(match.group(1)))
                        if stale:
                            try:
                                os.unlink(full)
                            except OSError:
                                pass
                        elif age is not None and age > _STALE_MAX_AGE_S:
                            # live-probing pid + ancient: foreign key
                            _warn_foreign_key(full, age)
                        continue
                    key = os.path.relpath(full, bucket_path).replace(os.sep, "/")
                    if key.startswith(prefix):
                        found.append(ObjectInfo(name=key, size=os.path.getsize(full)))
            found.sort(key=lambda info: info.name)
            return found

        for info in await asyncio.to_thread(_walk):
            yield info

    async def stat_object(self, bucket: str, name: str) -> ObjectInfo:
        path = self._object_path(bucket, name)
        etag = self._memo_lookup(path)
        if etag is not None:
            try:
                size = await asyncio.to_thread(os.path.getsize, path)
            except OSError:
                raise ObjectNotFound(bucket, name) from None
            return ObjectInfo(name=name, size=size, etag=etag)
        try:
            size, etag = await asyncio.to_thread(_stat_with_md5, path)
        except OSError:
            raise ObjectNotFound(bucket, name) from None
        # memoize the computed digest so the NEXT stat (manifest verify,
        # fleet probe) is free — without this, every verify pass is a
        # full read of every staged object
        self._memo_store(path, etag)
        return ObjectInfo(name=name, size=size, etag=etag)

    def local_object_path(self, bucket: str, name: str) -> Optional[str]:
        """Peer hardlink tier: the object's on-disk path when it exists
        locally, else None.  Co-located readers (fleet shared tier) may
        hardlink/reflink it instead of streaming a copy — safe because
        objects are only ever replaced atomically, never edited in
        place, so an aliased inode can't see store-side writes."""
        path = self._object_path(bucket, name)
        return path if os.path.isfile(path) else None

    async def remove_object(self, bucket: str, name: str) -> None:
        path = self._object_path(bucket, name)
        self._md5_memo.pop(path, None)

        def _remove() -> None:
            try:
                os.unlink(path)
            except FileNotFoundError:
                return
            except OSError:
                raise
            # prune now-empty parent dirs up to (not including) the
            # bucket root, so evicted prefix trees don't leave husks
            parent = os.path.dirname(path)
            stop = self._bucket_path(bucket)
            while parent != stop and os.path.isdir(parent):
                try:
                    os.rmdir(parent)
                except OSError:
                    break  # not empty (or racing): done pruning
                parent = os.path.dirname(parent)

        await asyncio.to_thread(_remove)


def _stat_with_md5(path: str) -> tuple:
    from ..utils.hashing import md5_file_hex

    # graftlint: disable=second-pass-read -- the memo-miss fallback: no landed digest survived for this object (foreign writer, process restart), so one read pass re-derives it and re-seeds the memo
    return os.path.getsize(path), md5_file_hex(path)


def _read_file(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


def _reclaim_dir(dirpath: str) -> None:
    """Unlink provably-orphaned ingest temps in ONE directory.

    Called on every put (cheap: one listdir of a typically-small dir)
    so write-only workloads reclaim their orphans too — the list walk
    is the other reclaim point, and a deployment that never lists
    would otherwise accumulate SIGKILLed partials forever (review r4)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return
    for name in names:
        if _TMP_RE.search(name):
            full = os.path.join(dirpath, name)
            if _is_stale_tmp(name, full):
                try:
                    os.unlink(full)
                except OSError:
                    pass


def _copy_file(src: str, dst: str) -> None:
    """Byte-copy through the write shim so ENOSPC/EIO/short-write
    drills on ``disk.spill`` exercise the spill byte path, not just
    the rename."""
    with open(src, "rb") as rfh, open(dst, "wb") as wfh:
        while True:
            chunk = rfh.read(1 << 20)
            if not chunk:
                break
            vfs.fh_write_all(wfh, chunk, seam="disk.spill", key=dst,
                             thread_ok=True)


def _write_file_atomic(path: str, data: bytes, suffix: str,
                       sweep: bool = True) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if sweep:
        _reclaim_dir(os.path.dirname(path))
    tmp = f"{path}.tmp.{suffix}"
    try:
        with open(tmp, "wb") as fh:
            vfs.fh_write_all(fh, data, seam="disk.spill", key=path,
                             thread_ok=True)
        # fsync-before-rename: the store's objects are the durable tier
        # the scrubber repairs FROM, so a spilled name must never point
        # at bytes the disk does not hold
        vfs.promote(tmp, path, seam="disk.spill", key=path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _ingest_file_atomic(src: str, dst: str, link_ok: bool, suffix: str,
                        sweep: bool = True) -> None:
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    if sweep:
        _reclaim_dir(os.path.dirname(dst))
    tmp = f"{dst}.tmp.{suffix}"
    try:
        if link_ok:
            try:
                os.link(src, tmp)
            except OSError:
                # cross-device (EXDEV), no-hardlink fs (EPERM), link cap
                # (EMLINK): fall through to the byte copy
                _copy_file(src, tmp)
        else:
            _copy_file(src, tmp)
        # a hardlinked ingest shares the source inode, whose bytes the
        # landing path already fsynced; the copy path's durability comes
        # from promote's fsync-before-rename either way
        vfs.promote(tmp, dst, seam="disk.spill", key=dst)
    except BaseException:
        # tmp names are unique per call, so a failed put (ENOSPC, kill
        # signal unwinding) must remove its own leftover — nothing will
        # ever reuse the name, and list_objects would enumerate it
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
