"""Object-store interface: the MinIO surface the pipeline actually uses."""

from __future__ import annotations

import abc
import dataclasses
from typing import AsyncIterator


class ObjectNotFound(KeyError):
    """Raised when a bucket/object does not exist.

    The orchestrator's idempotency probe relies on catching this
    (reference catches the MinIO getObject error at
    /root/reference/lib/main.js:119-124)."""

    def __init__(self, bucket: str, name: str):
        super().__init__(f"{bucket}/{name}")
        self.bucket = bucket
        self.name = name


@dataclasses.dataclass(frozen=True)
class ObjectInfo:
    """Listing entry (reference iterates ``item.name``/``item.size`` from
    ``getObjects``, /root/reference/lib/download.js:217-222).

    ``etag`` is the content hash when the backend knows it (S3-style MD5
    hex for single-part objects), else ``""``.  Consumers must treat an
    empty etag as "unknown", never as "matches".
    """

    name: str
    size: int
    etag: str = ""


class ObjectStore(abc.ABC):
    """Async object-store client."""

    @abc.abstractmethod
    async def bucket_exists(self, bucket: str) -> bool:
        """(reference lib/upload.js:29)"""

    @abc.abstractmethod
    async def make_bucket(self, bucket: str) -> None:
        """(reference lib/upload.js:30)"""

    @abc.abstractmethod
    async def get_object(self, bucket: str, name: str) -> bytes:
        """Fetch an object's bytes; raises :class:`ObjectNotFound`
        (reference lib/main.js:120)."""

    @abc.abstractmethod
    async def put_object(self, bucket: str, name: str, data: bytes) -> None:
        """Store bytes as an object (reference lib/upload.js:55)."""

    @abc.abstractmethod
    async def fget_object(self, bucket: str, name: str, file_path: str,
                          *, progress=None) -> None:
        """Download an object to a local file, creating parent dirs
        (reference lib/download.js:225).

        ``progress`` is an optional ``async (bytes_moved)`` callback for
        live transfer counters; backends that land the file in one step
        may fire it once with the full size."""

    @abc.abstractmethod
    async def fput_object(self, bucket: str, name: str, file_path: str,
                          *, consume: bool = False) -> None:
        """Upload a local file as an object (reference lib/upload.js:45).

        ``consume=True`` is the caller's promise that it will not MUTATE
        ``file_path``'s bytes after the call — backends may then ingest
        by aliasing (e.g. hardlink) instead of copying.  The path itself
        must remain on disk, unchanged, until the caller removes it: the
        streaming pipeline uploads files mid-download and still needs
        them afterwards (the authoritative post-download walk, torrent
        piece serving, cache fills), so a backend must never DELETE or
        move the source.  The default is the safe byte copy."""

    @abc.abstractmethod
    def list_objects(self, bucket: str, prefix: str = "") -> AsyncIterator[ObjectInfo]:
        """Iterate objects under ``prefix`` (reference ``getObjects``,
        lib/download.js:217)."""

    async def remove_object(self, bucket: str, name: str) -> None:
        """Delete one object; idempotent (a missing object is success).

        Added for the fleet GC sweep (fleet/plane.py): evicting aged
        ``.fleet-cache/`` entries and compacting ``.fleet/`` tombstones
        needs real deletion.  Kept OUT of the pipeline's staging path —
        staged media is never deleted by this service.  Backends that
        cannot delete raise NotImplementedError and the GC degrades to a
        no-op (bounded by that backend's own lifecycle policies).
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support remove_object"
        )

    async def stat_object(self, bucket: str, name: str) -> ObjectInfo:
        """Metadata for one object; raises :class:`ObjectNotFound`.

        Used by the upload stage to skip files that are already staged
        (file-level resume — the reference re-uploads everything on a
        redelivered job, lib/upload.js:34-52).  Default implementation
        scans a prefix listing; backends override with a cheaper probe.
        """
        async for info in self.list_objects(bucket, prefix=name):
            if info.name == name:
                return info
        raise ObjectNotFound(bucket, name)

    async def get_object_versioned(self, bucket: str, name: str):
        """Fetch ``(bytes, etag)`` atomically; raises ObjectNotFound.

        The etag is the token ``put_object_cas`` accepts as ``if_match``
        — together they are the read half of an S3 conditional-write
        (compare-and-swap) loop.  Backends without a native combined
        read fall back to get + stat, which is only best-effort.
        """
        data = await self.get_object(bucket, name)
        try:
            info = await self.stat_object(bucket, name)
            etag = info.etag
        except ObjectNotFound:
            etag = ""
        return data, etag

    async def put_object_cas(self, bucket: str, name: str, data: bytes, *,
                             if_match: "str | None" = None,
                             if_none_match: bool = False) -> "str | None":
        """Conditional put (S3 ``If-Match`` / ``If-None-Match: *``).

        Exactly one of the preconditions must be armed: ``if_none_match=
        True`` succeeds only when the object does NOT exist (create),
        ``if_match=<etag>`` only when the live object's etag still equals
        the one read earlier (replace).  Returns the NEW object's etag on
        success or ``None`` when the precondition failed (someone else
        won the race) — precondition failure is an expected outcome, not
        an error.  Backends that cannot do server-side conditions raise
        NotImplementedError and callers degrade to the best-effort
        nonce-verify discipline (fleet/coord.py BucketCoordStore).
        """
        raise NotImplementedError(
            f"{type(self).__name__} does not support conditional writes"
        )
