"""Origin-plane tests (downloader_tpu/origins/): racing fetch across
mirrors, per-origin breaker/retry seams, failover without job failure,
and HLS-style segment-manifest ingest.

Acceptance (ISSUE 10):

- origin failover: killing one origin mid-transfer completes the job
  with ZERO re-fetch of already-landed ranges and zero poison charges
- live-ingest overlap: the first staged upload for a segment precedes
  the last segment's download completing (PR 4 FileStream invariants:
  the done marker still only lands after the authoritative walk)
"""

import asyncio
import hashlib
import os
import time

import pytest
from aiohttp import web

from downloader_tpu import schemas
from downloader_tpu.control.registry import JobRegistry
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.orchestrator import Orchestrator
from downloader_tpu.origins.manifest import (ManifestStalled,
                                             parse_playlist)
from downloader_tpu.origins.plan import (OriginHealth, origin_label,
                                         resolve_mirrors)
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.errors import RetryPolicy
from downloader_tpu.platform.logging import NullLogger, get_logger
from downloader_tpu.platform import metrics as prom
from downloader_tpu.platform.telemetry import Telemetry
from downloader_tpu.stages.base import FileStream, Job, StageContext
from downloader_tpu.stages.download import stage_factory
from downloader_tpu.stages.process import stage_exts
from downloader_tpu.stages.upload import STAGING_BUCKET, object_name
from downloader_tpu.store.s3 import S3ObjectStore
from downloader_tpu.utils import EventEmitter

from helpers import RangeOrigin
from minis3 import MiniS3

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# plan: labels, health, mirror resolution
# ---------------------------------------------------------------------------

def test_origin_label_host_port():
    assert origin_label("http://mirror-a:8080/x/y.mkv") == "mirror-a:8080"
    assert origin_label("https://mirror-b/y.mkv") == "mirror-b"
    # dots flatten: the label must survive dotted seam/config paths
    # without splitting (seam_dependency splits on the first ".")
    assert origin_label("http://cdn.example.com/y.mkv") \
        == "cdn-example-com"
    assert origin_label("http://10.0.0.9:81/y") == "10-0-0-9:81"
    assert origin_label("not a url at all ://") == "other"


def test_origin_health_label_cardinality_bounded():
    health = OriginHealth(max_labels=2)
    a = health.label("http://a/x")
    b = health.label("http://b/x")
    c = health.label("http://c/x")
    assert (a, b) == ("a", "b")
    assert c == "other"  # overflow collapses: payloads can't mint series
    assert health.label("http://a/other-path") == "a"  # stable


def test_origin_health_ewma_tracks_rate():
    health = OriginHealth()
    for _ in range(10):
        health.feed("fast", 1 << 20, 0.01)   # ~100 MB/s
        health.feed("slow", 1 << 20, 1.0)    # ~1 MB/s
    assert health.bps("fast") > health.bps("slow") * 10
    assert health.bps("never-seen") == 0.0
    assert health.total_bytes("fast") == 10 << 20


def test_resolve_mirrors_filters_and_dedupes():
    primary = "http://origin/a.mkv"
    assert resolve_mirrors(primary, [
        "http://m1/a.mkv",
        "http://origin/a.mkv",      # the primary itself: dropped
        "http://m1/a.mkv",          # duplicate: dropped
        "ftp://m2/a.mkv",           # non-http scheme: dropped
        "https://m3/a.mkv",
        None,                       # junk survives decoding: dropped
    ]) == ["http://m1/a.mkv", "https://m3/a.mkv"]


def test_labeled_dependency_inherits_family_config():
    config = ConfigNode({
        "retry": {"origin": {"attempts": 7, "base": 0.01, "cap": 0.5}},
    })
    policy = RetryPolicy.from_config(config, "origin:mirror-a:8080")
    assert policy.attempts == 7
    assert policy.base == 0.01
    # plain dependencies keep the default chain
    assert RetryPolicy.from_config(config, "store").attempts == 3


def test_manifest_exts_gate_on_source_kind():
    config = ConfigNode({})
    assert ".ts" not in stage_exts(config)
    assert ".ts" in stage_exts(config, "MANIFEST")
    assert ".m4s" in stage_exts(config, "MANIFEST")
    assert ".mkv" in stage_exts(config, "MANIFEST")


# ---------------------------------------------------------------------------
# playlist parser
# ---------------------------------------------------------------------------

def test_parse_playlist_live_and_vod():
    live = parse_playlist(
        "#EXTM3U\n#EXT-X-TARGETDURATION:4\n#EXT-X-MEDIA-SEQUENCE:17\n"
        "#EXTINF:3.9,\nseg17.ts\n#EXTINF:4.0,title\nseg18.ts\n"
    )
    assert not live.ended
    assert live.target_duration == 4.0
    assert [(s.seq, s.uri) for s in live.segments] == [
        (17, "seg17.ts"), (18, "seg18.ts"),
    ]
    vod = parse_playlist(
        "#EXTM3U\n#EXTINF:2,\na.ts\n#EXTINF:2,\nb.ts\n#EXT-X-ENDLIST\n"
    )
    assert vod.ended
    assert [s.seq for s in vod.segments] == [0, 1]
    # unknown tags are ignored like real players
    tagged = parse_playlist(
        "#EXTM3U\n#EXT-X-VERSION:3\n#EXTINF:2,\nx.ts\n"
    )
    assert [s.uri for s in tagged.segments] == ["x.ts"]


def test_parse_playlist_rejects_non_playlists():
    with pytest.raises(ValueError):
        parse_playlist("<html>definitely not a playlist</html>")


# ---------------------------------------------------------------------------
# stage-level racing harness
# ---------------------------------------------------------------------------

def make_ctx(tmp_path, instance=None, extra=None, job_id="race"):
    registry = JobRegistry(logger=NullLogger())
    record = registry.register(job_id, "card")
    metrics = prom.Metrics(f"orig{os.urandom(4).hex()}")
    config = ConfigNode({
        "instance": {"download_path": str(tmp_path / "dl"),
                     **(instance or {})},
        **(extra or {}),
    })
    ctx = StageContext(config=config, emitter=EventEmitter(),
                       logger=get_logger("test-origins"),
                       metrics=metrics, record=record)
    return ctx, record, metrics


def http_media(url, job_id):
    return schemas.Media(
        id=job_id, creator_id="card", name="A Movie",
        type=schemas.MediaType.Value("MOVIE"),
        source=schemas.SourceType.Value("HTTP"), source_uri=url,
    )


def counter_value(metrics, counter, **labels):
    try:
        return counter.labels(**labels)._value.get()
    except Exception:
        return 0.0


async def test_racing_fast_mirror_serves_most_bytes(tmp_path):
    """Slow primary + fast mirror: the raced download is byte-identical
    and the fast origin ends up serving the bulk of the entity (work
    stealing), with race-win attribution on /metrics."""
    payload = os.urandom(12 << 20)
    slow = RangeOrigin(payload, etag='"e1"', rate=2 << 20)
    fast = RangeOrigin(payload, etag='"e1"')
    await slow.start()
    await fast.start()
    ctx, record, metrics = make_ctx(tmp_path, job_id="race-fast")
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(slow.url, "race-fast"),
                  mirrors=(fast.url,))
        result = await download(job)
        got = open(os.path.join(result["path"], "media.bin"), "rb").read()
        assert hashlib.sha256(got).digest() \
            == hashlib.sha256(payload).digest()
        assert fast.served > slow.served
        fast_label = origin_label(fast.url)
        wins = sum(
            counter_value(metrics, metrics.origin_race_wins,
                          origin=fast_label, reason=reason)
            for reason in ("fastest", "failover", "straggler_dup")
        )
        assert wins >= 1
        assert counter_value(metrics, metrics.origin_bytes,
                             origin=fast_label) > len(payload) / 2
        probes = [e for e in record.recorder.events()
                  if e["kind"] == "origin_probe"]
        assert len(probes) == 2
        assert all(p["ok"] for p in probes)
    finally:
        await slow.stop()
        await fast.stop()


async def test_racing_failover_zero_refetch(tmp_path):
    """ACCEPTANCE: an origin dying mid-transfer fails over without
    failing the job, re-fetches ZERO already-landed bytes (the landed
    counter equals the entity exactly), and never burns poison (the
    stage returns success — nothing for the orchestrator to charge)."""
    payload = os.urandom(16 << 20)
    dying = RangeOrigin(payload, etag='"e1"', fail_after=5 << 20)
    healthy = RangeOrigin(payload, etag='"e1"')
    await dying.start()
    await healthy.start()
    ctx, record, _metrics = make_ctx(
        tmp_path, job_id="race-fo",
        extra={
            # deterministic: no straggler duplication (it would land
            # some bytes twice by design and cloud the exact count)
            "origins": {"dup_factor": 1e9},
            "retry": {"origin": {"attempts": 2, "base": 0.01,
                                 "cap": 0.05}},
        },
    )
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(dying.url, "race-fo"),
                  mirrors=(healthy.url,))
        result = await download(job)
        got = open(os.path.join(result["path"], "media.bin"), "rb").read()
        assert hashlib.sha256(got).digest() \
            == hashlib.sha256(payload).digest()
        # zero re-fetch of landed ranges: every landed byte was landed
        # exactly once
        assert record.bytes.get("downloaded") == len(payload)
        events = record.recorder.events()
        assert any(e["kind"] == "origin_failover" for e in events)
        # the failed-over range's re-assignment is attributed
        assert any(e["kind"] == "range_assign"
                   and e.get("reason") == "failover" for e in events)
    finally:
        await dying.stop()
        await healthy.stop()


async def test_racing_mirror_serving_different_entity_excluded(tmp_path):
    """A mirror whose validator disagrees with the primary serves a
    DIFFERENT entity: it is excluded at probe time and the download is
    correct from the primary alone."""
    payload = os.urandom(9 << 20)
    primary = RangeOrigin(payload, etag='"genuine"')
    imposter = RangeOrigin(os.urandom(9 << 20), etag='"imposter"')
    await primary.start()
    await imposter.start()
    ctx, record, _metrics = make_ctx(tmp_path, job_id="race-mm")
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(primary.url, "race-mm"),
                  mirrors=(imposter.url,))
        result = await download(job)
        got = open(os.path.join(result["path"], "media.bin"), "rb").read()
        assert hashlib.sha256(got).digest() \
            == hashlib.sha256(payload).digest()
        assert imposter.served <= 1  # its 0-0 probe byte, nothing more
        probes = {e["origin"]: e for e in record.recorder.events()
                  if e["kind"] == "origin_probe"}
        assert probes[origin_label(imposter.url)]["ok"] is False
        assert probes[origin_label(imposter.url)]["reason"] \
            == "validator_mismatch"
    finally:
        await primary.stop()
        await imposter.stop()


async def test_dead_origin_breaker_opens_sibling_keeps_serving(tmp_path):
    """The dead origin's ``origin:<label>`` breaker opens while the
    sibling origin keeps admitting: a SECOND job against the same
    origin set completes without touching the dead origin again."""
    payload = os.urandom(12 << 20)
    dying = RangeOrigin(payload, etag='"e1"', fail_after=512 << 10)
    healthy = RangeOrigin(payload, etag='"e1"')
    await dying.start()
    await healthy.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="race-brk",
        extra={
            "origins": {"dup_factor": 1e9},
            "retry": {"origin": {"attempts": 2, "base": 0.01,
                                 "cap": 0.05}},
            "breakers": {"origin": {"threshold": 2, "reset": 60.0}},
        },
    )
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(dying.url, "race-brk"),
                  mirrors=(healthy.url,))
        await download(job)
        breakers = ctx.resources["retrier"].breakers
        breaker = breakers.get(f"origin:{origin_label(dying.url)}")
        assert breaker.state == "open"
        # cache-less second job (fresh id), same origins: the open
        # breaker keeps the dead origin out, the sibling serves alone
        dying_requests_before = dying.requests
        registry = JobRegistry(logger=NullLogger())
        ctx.record = registry.register("race-brk2", "card")
        job2 = Job(media=http_media(dying.url + "?job=2", "race-brk2"),
                   mirrors=(healthy.url + "?job=2",))
        result = await download(job2)
        got = open(os.path.join(result["path"], "media.bin"), "rb").read()
        assert hashlib.sha256(got).digest() \
            == hashlib.sha256(payload).digest()
        # probe traffic aside, the open breaker blocked range fetches
        assert dying.requests <= dying_requests_before + 1
    finally:
        await dying.stop()
        await healthy.stop()


async def test_small_entity_still_races_with_mirrors(tmp_path):
    """Entities under SEG_MIN_SIZE race too when mirrors exist (the
    failover guarantee must cover small files), while staying on the
    sequential path with no mirrors."""
    payload = os.urandom(2 << 20)
    primary = RangeOrigin(payload, etag='"e1"')
    mirror = RangeOrigin(payload, etag='"e1"')
    await primary.start()
    await mirror.start()
    ctx, record, _metrics = make_ctx(tmp_path, job_id="race-small")
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(primary.url, "race-small"),
                  mirrors=(mirror.url,))
        result = await download(job)
        got = open(os.path.join(result["path"], "media.bin"), "rb").read()
        assert got == payload
        assert any(e["kind"] == "origin_probe"
                   for e in record.recorder.events())
    finally:
        await primary.stop()
        await mirror.stop()


# ---------------------------------------------------------------------------
# scheduler-level hang/takeover regressions (review round)
# ---------------------------------------------------------------------------

def scheduler_fixture(segments, origins_spec, config=None):
    """A RangeScheduler over fake origins with an in-memory retrier."""
    from downloader_tpu.origins.plan import Origin
    from downloader_tpu.origins.racing import RangeScheduler
    from downloader_tpu.platform.errors import BreakerBoard, Retrier

    cfg = ConfigNode(config or {})
    origins = [Origin(url=f"http://{name}/x", label=name,
                      primary=(i == 0))
               for i, name in enumerate(origins_spec)]
    retrier = Retrier(cfg, breakers=BreakerBoard(cfg))
    health = OriginHealth()
    return origins, retrier, health, cfg, RangeScheduler


async def test_scheduler_takes_over_black_holed_small_tail():
    """REGRESSION (review): a hung owner holding a sub-min_dup_bytes
    tail must not park the job until the 240 s watchdog — past
    origins.stall_takeover an idle origin duplicates it regardless of
    the EWMA/min-tail gates, and completion is judged on BYTES even
    when credit bookkeeping raced."""
    segments = [[0, 0, 64 << 10], [64 << 10, 64 << 10, 128 << 10]]
    origins, retrier, health, cfg, RangeScheduler = scheduler_fixture(
        segments, ["hangs", "works"],
        config={"origins": {"stall_takeover": 0.2}},
    )

    async def fetch(origin, triple, guard):
        if origin.label == "hangs":
            # land a little, then black-hole (no error to fail over)
            triple[1] += 1 << 10
            guard(1 << 10)
            await asyncio.Event().wait()
        while triple[1] < triple[2]:
            n = min(16 << 10, triple[2] - triple[1])
            triple[1] += n
            if not guard(n):
                return
            await asyncio.sleep(0)

    scheduler = RangeScheduler(origins, segments, fetch,
                               retrier=retrier, health=health,
                               config=cfg)
    async with asyncio.timeout(10):
        await scheduler.run()
    assert all(seg[1] >= seg[2] for seg in segments)


async def test_scheduler_reassigns_range_held_by_hung_duplicate():
    """REGRESSION (review): owner failed over AND the straggler dup is
    black-holed — the range's slots must not deadlock; a healthy third
    origin takes it over after stall_takeover."""
    segments = [[0, 0, 4 << 20], [4 << 20, 4 << 20, 8 << 20]]
    origins, retrier, health, cfg, RangeScheduler = scheduler_fixture(
        segments, ["dies", "hangs", "works"],
        config={"origins": {"stall_takeover": 0.2, "dup_factor": 0.0},
                "retry": {"origin": {"attempts": 1, "base": 0.01,
                                     "cap": 0.02}}},
    )
    # the healthy origin must look fast so it dups eagerly; the hung
    # one must look slow (it will own nothing after its dup stalls)
    for _ in range(5):
        health.feed("works", 1 << 20, 0.01)

    async def fetch(origin, triple, guard):
        if origin.label == "dies":
            triple[1] += 1 << 10
            guard(1 << 10)
            raise RuntimeError("origin died mid-range")
        if origin.label == "hangs":
            await asyncio.Event().wait()
        while triple[1] < triple[2]:
            n = min(256 << 10, triple[2] - triple[1])
            triple[1] += n
            if not guard(n):
                return
            await asyncio.sleep(0)

    scheduler = RangeScheduler(origins, segments, fetch,
                               retrier=retrier, health=health,
                               config=cfg)
    async with asyncio.timeout(10):
        await scheduler.run()
    assert all(seg[1] >= seg[2] for seg in segments)


async def test_scheduler_evicts_range_with_both_writers_stalled():
    """REGRESSION (review round 2): a range whose owner AND straggler
    dup are both black-holed must still be claimable by a healthy third
    origin — the stalled owner slot is evicted (identity-guarded
    releases make the replaced writer a harmless zombie)."""
    segments = [[0, 0, 4 << 20]]
    origins, retrier, health, cfg, RangeScheduler = scheduler_fixture(
        segments, ["hung-owner", "hung-dup", "healthy"],
        config={"origins": {"stall_takeover": 0.2}},
    )
    scheduler = RangeScheduler(origins, segments, None,
                               retrier=retrier, health=health,
                               config=cfg)
    rng = scheduler.ranges[0]
    rng.owner, rng.dup = origins[0], origins[1]
    rng.winner = "dup"
    rng.last_progress = time.monotonic() - 1.0  # both writers stalled
    picked = scheduler._pick(origins[2])
    assert picked is not None
    assert picked[1] == "owner"
    assert rng.owner is origins[2]   # evicted the stalled owner slot
    assert rng.winner is None        # writers re-race from here
    # a LIVE pair keeps its slots: fresh progress blocks the eviction
    rng.owner, rng.dup = origins[0], origins[1]
    rng.last_progress = time.monotonic()
    assert scheduler._pick(origins[2]) is None


async def test_segment_fetcher_raises_breaker_open_when_all_blocked():
    """REGRESSION (review): every origin breaker open must surface
    BreakerOpen (park-without-poison) from the segment fetcher, not a
    bare transient error that burns the poison budget."""
    from downloader_tpu.origins.plan import Origin
    from downloader_tpu.origins.racing import SegmentFetcher
    from downloader_tpu.platform.errors import (BreakerBoard, BreakerOpen,
                                                Retrier)

    cfg = ConfigNode({"breakers": {"origin": {"threshold": 1,
                                              "reset": 60.0}}})
    board = BreakerBoard(cfg)
    retrier = Retrier(cfg, breakers=board)
    origins = [Origin(url="http://only/x", label="only", primary=True)]
    board.get("origin:only").record_failure()  # threshold 1: open
    fetcher = SegmentFetcher(origins, retrier=retrier,
                             health=OriginHealth(), config=cfg)

    async def fetch_one(_origin, _hedge):
        raise AssertionError("must not be called: breaker is open")

    with pytest.raises(BreakerOpen):
        await fetcher.fetch(fetch_one, what="segment")


# ---------------------------------------------------------------------------
# manifest ingest (stage level)
# ---------------------------------------------------------------------------

class LiveOrigin:
    """Serves an HLS-style playlist that reveals one more segment every
    ``period`` seconds until ``total``, then appends ENDLIST.  ``vod``
    serves the complete, ended playlist from the first request."""

    def __init__(self, total=6, period=0.12, seg_bytes=48 << 10,
                 vod=False, initial=2, hang_segments=False,
                 gzip_segments=False, stall_mid_body=False):
        self.total = total
        self.period = period
        self.segments = [os.urandom(seg_bytes) for _ in range(total)]
        self.vod = vod
        self.initial = initial
        self.hang_segments = hang_segments
        self.gzip_segments = gzip_segments
        self.stall_mid_body = stall_mid_body
        self.playlist_requests = 0
        self.segment_requests = 0
        self._started = None
        self._runner = None
        self.url = None

    def _visible(self):
        if self.vod:
            return self.total
        if self._started is None:
            self._started = time.monotonic()
        grown = self.initial + int(
            (time.monotonic() - self._started) / self.period
        )
        return min(max(grown, self.initial), self.total)

    async def _playlist(self, _request):
        self.playlist_requests += 1
        visible = self._visible()
        lines = ["#EXTM3U", "#EXT-X-TARGETDURATION:1",
                 "#EXT-X-MEDIA-SEQUENCE:0"]
        for i in range(visible):
            lines.append("#EXTINF:0.5,")
            lines.append(f"seg{i:04d}.ts")
        if visible >= self.total:
            lines.append("#EXT-X-ENDLIST")
        return web.Response(text="\n".join(lines))

    async def _segment(self, request):
        self.segment_requests += 1
        if self.hang_segments:
            await asyncio.Event().wait()
        index = int(request.match_info["i"])
        payload = self.segments[index]
        if self.gzip_segments:
            import gzip as gzip_mod

            body = gzip_mod.compress(payload)
            resp = web.Response(
                body=body, headers={"Content-Encoding": "gzip"})
            # aiohttp would otherwise re-encode; body is pre-compressed
            resp._compressed_body = body
            return resp
        if self.stall_mid_body:
            resp = web.StreamResponse()
            resp.content_length = len(payload)
            await resp.prepare(request)
            await resp.write(payload[: len(payload) // 2])
            await asyncio.Event().wait()  # black-hole mid-body
        return web.Response(body=payload)

    async def start(self) -> str:
        app = web.Application()
        app.router.add_get("/live.m3u8", self._playlist)
        app.router.add_get(r"/seg{i:\d+}.ts", self._segment)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://127.0.0.1:{port}/live.m3u8"
        return self.url

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


def manifest_instance():
    return {"origins": {"manifest": {"min_poll": 0.05,
                                     "stall_timeout": 10.0}}}


async def run_manifest_job(ctx, url, job_id, mirrors=()):
    download = await stage_factory(ctx)
    stream = FileStream()
    announced = []

    async def reader():
        while (event := await stream.next()) is not None:
            announced.append(event)

    job = Job(media=http_media(url, job_id), source_kind="MANIFEST",
              file_stream=stream, mirrors=tuple(mirrors))
    reader_task = asyncio.create_task(reader())
    result = await download(job)
    await stream.close()
    await reader_task
    return result, announced


async def test_manifest_vod_fast_path(tmp_path):
    """An already-ended playlist drains in one pass: no polling loop,
    every segment staged byte-identical, playlist kept for provenance
    but NOT announced as media."""
    live = LiveOrigin(total=4, vod=True)
    await live.start()
    ctx, record, _metrics = make_ctx(
        tmp_path, job_id="vod-1", extra=manifest_instance())
    try:
        result, announced = await run_manifest_job(ctx, live.url, "vod-1")
        assert len(announced) == 4
        for i in range(4):
            got = open(os.path.join(result["path"], f"seg{i:04d}.ts"),
                       "rb").read()
            assert got == live.segments[i]
        assert live.playlist_requests == 1  # the VOD fast path
        assert os.path.exists(os.path.join(result["path"], "live.m3u8"))
        events = [e["kind"] for e in record.recorder.events()]
        assert "manifest_open" in events
        assert "manifest_end" in events
    finally:
        await live.stop()


async def test_manifest_live_polls_until_endlist(tmp_path):
    """A growing live playlist: segments land as they appear, the job
    finishes only at ENDLIST, and every announced segment is durable
    when announced."""
    live = LiveOrigin(total=6, period=0.1)
    await live.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="live-1", extra=manifest_instance())
    try:
        result, announced = await run_manifest_job(ctx, live.url,
                                                   "live-1")
        assert len(announced) == 6
        assert live.playlist_requests > 1  # it genuinely polled
        for i in range(6):
            got = open(os.path.join(result["path"], f"seg{i:04d}.ts"),
                       "rb").read()
            assert got == live.segments[i]
    finally:
        await live.stop()


async def test_manifest_live_window_joins_at_edge(tmp_path):
    """origins.manifest.live_window bounds how far behind the live edge
    a joining worker starts: earlier segments are skipped."""
    live = LiveOrigin(total=6, period=0.08, initial=5)
    await live.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="edge-1",
        extra={"origins": {"manifest": {
            "min_poll": 0.05, "stall_timeout": 10.0, "live_window": 2,
        }}})
    try:
        result, announced = await run_manifest_job(ctx, live.url,
                                                   "edge-1")
        names = sorted(os.path.basename(e.path) for e in announced)
        # joined at edge: seg0000..seg0002 skipped (5 visible - window 2)
        assert names[0] == "seg0003.ts"
        assert names[-1] == "seg0005.ts"
        assert not os.path.exists(
            os.path.join(result["path"], "seg0000.ts"))
    finally:
        await live.stop()


async def test_manifest_stall_raises_dlstall(tmp_path):
    """A live playlist that stops producing without ENDLIST raises the
    stall code the orchestrator's drop policy owns (ERRDLSTALL)."""
    live = LiveOrigin(total=10, period=3600.0, initial=2)
    await live.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="stall-1",
        extra={"origins": {"manifest": {"min_poll": 0.05,
                                        "stall_timeout": 0.4}}})
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(live.url, "stall-1"),
                  source_kind="MANIFEST")
        with pytest.raises(ManifestStalled) as excinfo:
            await download(job)
        assert type(excinfo.value).code == "ERRDLSTALL"
    finally:
        await live.stop()


async def test_manifest_segment_failover_to_mirror(tmp_path):
    """A black-holed primary's segments hedge over to the mirror within
    ONE origins.hedge_delay window (even with a multi-attempt retry
    budget — the hedge is the fetcher's impatience, not the origin's
    verdict), and the slow origin's breaker is NOT fed by it."""
    primary = LiveOrigin(total=3, vod=True, hang_segments=True)
    mirror = LiveOrigin(total=3, vod=True)
    mirror.segments = primary.segments  # same content, healthy serving
    await primary.start()
    await mirror.start()
    ctx, record, _metrics = make_ctx(
        tmp_path, job_id="hedge-1",
        extra={
            "origins": {"hedge_delay": 0.2,
                        "manifest": {"min_poll": 0.05,
                                     "stall_timeout": 10.0}},
        })
    try:
        started = time.monotonic()
        result, announced = await run_manifest_job(
            ctx, primary.url, "hedge-1", mirrors=(mirror.url,))
        elapsed = time.monotonic() - started
        assert len(announced) == 3
        for i in range(3):
            got = open(os.path.join(result["path"], f"seg{i:04d}.ts"),
                       "rb").read()
            assert got == primary.segments[i]
        assert any(e["kind"] == "origin_failover"
                   for e in record.recorder.events())
        # one hedge window per hang, no attempts x backoff pile-up
        # (3 segments + playlist; generous bound, still far below the
        # attempts-retried worst case)
        assert elapsed < 4.0, f"hedge failover too slow: {elapsed:.1f}s"
        # REGRESSION (review round 3): hedge timeouts are the
        # fetcher's impatience, never the origin's failures — its
        # cross-job breaker must stay closed and unfed
        breakers = ctx.resources["retrier"].breakers
        hung_breaker = breakers.get(f"origin:{origin_label(primary.url)}")
        assert hung_breaker.state == "closed"
        assert hung_breaker.failures == 0
    finally:
        await primary.stop()
        await mirror.stop()


async def test_manifest_gzip_segment_decoded_before_staging(tmp_path):
    """REGRESSION (review round 3): a misbehaving CDN sending
    Content-Encoding: gzip segments must have them DECODED before the
    announce — the whole-file HTTP path already refuses to stage
    compressed bytes as media; the manifest path must match."""
    live = LiveOrigin(total=2, vod=True, gzip_segments=True)
    await live.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="gz-1", extra=manifest_instance())
    try:
        result, announced = await run_manifest_job(ctx, live.url, "gz-1")
        assert len(announced) == 2
        for i in range(2):
            got = open(os.path.join(result["path"], f"seg{i:04d}.ts"),
                       "rb").read()
            assert got == live.segments[i]  # the DECODED bytes
    finally:
        await live.stop()


async def test_manifest_sole_origin_mid_body_hang_is_bounded(tmp_path):
    """REGRESSION (review round 3): a sole origin that black-holes
    MID-BODY (no hedge candidate left, stall check blocked inside the
    fetch) must fail within ~stall_timeout per attempt, not ride
    aiohttp's 5-minute session default times the retry budget."""
    live = LiveOrigin(total=2, vod=True, stall_mid_body=True)
    await live.start()
    ctx, _record, _metrics = make_ctx(
        tmp_path, job_id="hang-1",
        extra={
            "origins": {"manifest": {"min_poll": 0.05,
                                     "stall_timeout": 1.0}},
            "retry": {"origin": {"attempts": 1, "base": 0.01,
                                 "cap": 0.05}},
        })
    try:
        download = await stage_factory(ctx)
        job = Job(media=http_media(live.url, "hang-1"),
                  source_kind="MANIFEST", file_stream=None)
        started = time.monotonic()
        with pytest.raises(Exception):
            await download(job)
        assert time.monotonic() - started < 8.0
    finally:
        await live.stop()


# ---------------------------------------------------------------------------
# ACCEPTANCE: live-ingest overlap through the full orchestrator
# ---------------------------------------------------------------------------

async def test_live_ingest_overlap_acceptance(tmp_path):
    """Full service vs memory broker + MiniS3: a live playlist's early
    segments are staged (upload_done) BEFORE the last segment's
    download completes (file_complete), the staged set is
    byte-identical, and the done marker seals only the authoritative
    walk — the PR 4 invariants, now driven by a live source."""
    live = LiveOrigin(total=6, period=0.25, seg_bytes=96 << 10)
    await live.start()
    s3 = MiniS3()
    await s3.start()
    store = S3ObjectStore(f"http://127.0.0.1:{s3.port}", "AKIA", "SECRET")
    broker = InMemoryBroker()
    telem_mq = MemoryQueue(broker)
    await telem_mq.connect()
    orchestrator = Orchestrator(
        config=ConfigNode({
            "instance": {"download_path": str(tmp_path / "downloads")},
            "origins": {"manifest": {"min_poll": 0.05,
                                     "stall_timeout": 15.0}},
        }),
        mq=MemoryQueue(broker),
        store=store,
        telemetry=Telemetry(telem_mq),
        metrics=prom.new(f"liveingest{os.urandom(4).hex()}"),
        logger=NullLogger(),
    )
    await orchestrator.start()
    try:
        msg = schemas.Download(media=schemas.Media(
            id="live-acc", creator_id="card-1", name="Live Event",
            type=schemas.MediaType.Value("MOVIE"),
            source=schemas.SourceType.Value("HTTP"),
            source_uri=live.url,
        ), source_kind=schemas.SourceKind.Value("MANIFEST"))
        broker.publish(schemas.DOWNLOAD_QUEUE, schemas.encode(msg))
        async with asyncio.timeout(60):
            await broker.join(schemas.DOWNLOAD_QUEUE)

        # staged set byte-identical + done marker + one convert publish
        for i in range(live.total):
            staged = await store.get_object(
                STAGING_BUCKET,
                object_name("live-acc", f"seg{i:04d}.ts"),
            )
            assert staged == live.segments[i]
        assert await store.get_object(
            STAGING_BUCKET, "live-acc/original/done") == b"true"
        assert len(broker.published(schemas.CONVERT_QUEUE)) == 1

        record = orchestrator.registry.get("live-acc")
        assert record.state == "DONE"
        events = record.recorder.events()
        completes = [e for e in events if e["kind"] == "file_complete"]
        dones = [e for e in events if e["kind"] == "upload_done"]
        assert len(completes) == live.total
        assert len(dones) >= live.total
        # THE overlap claim: a segment was fully staged while later
        # segments were still being produced/downloaded
        assert min(e["t"] for e in dones) < max(e["t"] for e in completes)
        # the playlist itself never staged (not media)
        with pytest.raises(Exception):
            await store.get_object(
                STAGING_BUCKET, object_name("live-acc", "live.m3u8"))
    finally:
        await orchestrator.shutdown(grace_seconds=2)
        await store.close()
        await s3.stop()
        await live.stop()
