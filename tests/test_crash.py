"""Kill-based crash-chaos harness (ISSUE 8 tentpole d; ``make crash``).

Each scenario runs a REAL ``python -m downloader_tpu`` worker subprocess
against a real-wire MiniAmqp broker + MiniS3 staging store + a local
HTTP origin, SIGKILLs it at a chosen seam — mid-download (bytes already
on disk), between the staged file and the done marker, pre-ack with
everything published, and while holding a fleet content lease — then
restarts it and asserts the crash-safety invariants end to end:

- the job eventually reaches DONE exactly once, and the staged bytes
  are hash-identical to the origin payload;
- no orphan workdirs under the download root, no leaked fleet leases;
- the retry/poison counter survives the restart (monotone, never
  reset by the redelivery);
- the restart surfaces a ``recovery`` block on ``/readyz``.

The kill is a true SIGKILL: either a ``kind: crash`` fault-plan rule
(platform/faults.py) fires ``os.kill(pid, SIGKILL)`` at the seam, or —
for the mid-transfer case, where no call seam sits inside the splice
loop — the parent watches the shared filesystem for the ``.partial``
file and kills the worker while bytes are landing.
"""

import asyncio
import base64
import os
import signal
import socket
import sys

import pytest
import yaml

from downloader_tpu import schemas
from downloader_tpu.control.journal import (JOURNAL_DIRNAME,
                                            JOURNAL_FILENAME, replay)
from downloader_tpu.store.s3 import S3ObjectStore

from minis3 import MiniS3
from miniamqp import MiniAmqpServer

pytestmark = pytest.mark.anyio

STAGING = "triton-staging"
PAYLOAD = bytes(range(256)) * 2048  # 512 KiB, content-checkable


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _object_name(job_id: str, basename: str) -> str:
    encoded = base64.b64encode(basename.encode()).decode()
    return f"{job_id}/original/{encoded}"


def download_msg(job_id: str, uri: str) -> bytes:
    return schemas.encode(schemas.Download(media=schemas.Media(
        id=job_id, creator_id="crash-card",
        type=schemas.MediaType.Value("MOVIE"),
        source=schemas.SourceType.Value("HTTP"),
        source_uri=uri,
    )))


async def start_origin(chunk_delay: float = 0.0):
    """Streamed origin for ``/show.mkv`` with an ETag (cacheable).

    ``chunk_delay`` > 0 streams the payload in 32 KiB chunks with a
    pause after each, holding the transfer open long enough for the
    parent to kill the worker mid-splice.  Returns (runner, url, gets).
    """
    from aiohttp import web

    from helpers import start_http_server

    gets = [0]

    async def serve(request):
        headers = {"ETag": '"crash-etag-1"'}
        if request.method == "HEAD":
            return web.Response(headers={
                **headers, "Content-Length": str(len(PAYLOAD)),
                "Accept-Ranges": "bytes",
            })
        gets[0] += 1
        if not chunk_delay:
            return web.Response(body=PAYLOAD, headers=headers)
        resp = web.StreamResponse(headers={
            **headers, "Content-Length": str(len(PAYLOAD)),
        })
        await resp.prepare(request)
        for off in range(0, len(PAYLOAD), 32 << 10):
            await resp.write(PAYLOAD[off:off + (32 << 10)])
            await asyncio.sleep(chunk_delay)
        await resp.write_eof()
        return resp

    runner, base = await start_http_server(serve, path="/show.mkv")
    return runner, f"{base}/show.mkv", gets


class CrashRig:
    """One scenario's infrastructure: broker + store + config + worker
    generations.  The broker and store OUTLIVE worker kills — they are
    the durable world the restarted worker reconciles against."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.downloads = str(tmp_path / "downloads")
        self.config_dir = str(tmp_path / "config")
        self.health_port = _free_port()
        self.amqp = MiniAmqpServer()
        self.s3 = MiniS3()
        self.store = None
        self.proc = None
        self.generation = 0

    async def start_backends(self) -> None:
        await self.amqp.start()
        s3_url = await self.s3.start()
        self.store = S3ObjectStore(s3_url, "AKIA", "SECRET")
        # the staging bucket pre-exists (production provisions it; the
        # fleet coordination store also writes under it at boot)
        await self.store.make_bucket(STAGING)

    def write_config(self, extra: dict = None) -> None:
        cfg = {
            "instance": {"download_path": self.downloads,
                         "max_concurrent_jobs": 2},
            "rabbitmq": {"backend": "amqp"},
            "minio": {"backend": "s3",
                      "endpoint": f"http://127.0.0.1:{self.s3.port}",
                      "access_key": "AKIA", "secret_key": "SECRET"},
            "services": {"rabbitmq": self.amqp.url},
            # strict per-append durability: the parent reads the journal
            # file while the worker runs
            "journal": {"fsync_interval": 0},
            "retry": {"default": {"attempts": 1, "base": 0.05,
                                  "cap": 0.1},
                      "redelivery": {"base": 0.05, "cap": 0.2}},
        }
        if extra:
            for key, value in extra.items():
                node = cfg.setdefault(key, {})
                if isinstance(value, dict):
                    node.update(value)
                else:
                    cfg[key] = value
        os.makedirs(self.config_dir, exist_ok=True)
        with open(os.path.join(self.config_dir, "converter.yaml"),
                  "w", encoding="utf-8") as fh:
            yaml.safe_dump(cfg, fh)

    async def spawn_worker(self, fault_plan: str = "") -> None:
        """Start a worker generation; blocks until /readyz answers."""
        self.generation += 1
        env = {k: v for k, v in os.environ.items()
               if k not in ("FAULT_PLAN", "PIPELINE_MODE", "CACHE_DIR",
                            "CACHE_ENABLED", "UPLOAD_CONCURRENCY",
                            "CONFIG_PATH", "PORT", "WORKER_ID")}
        env["CONFIG_PATH"] = self.config_dir
        env["PORT"] = str(self.health_port)
        env["WORKER_ID"] = "crash-w1"  # stable across restarts
        if fault_plan:
            env["FAULT_PLAN"] = fault_plan
        log = open(os.path.join(str(self.tmp_path),
                                f"worker-gen{self.generation}.log"), "wb")
        try:
            self.proc = await asyncio.create_subprocess_exec(
                sys.executable, "-m", "downloader_tpu",
                env=env, stdout=log, stderr=log,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
            )
        finally:
            log.close()
        await self._wait_ready()

    async def _wait_ready(self, timeout: float = 30.0) -> None:
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with asyncio.timeout(timeout):
                while True:
                    if self.proc.returncode is not None:
                        raise AssertionError(
                            f"worker gen{self.generation} exited "
                            f"{self.proc.returncode} before ready "
                            f"(see worker-gen{self.generation}.log)"
                        )
                    try:
                        async with session.get(self._url("/readyz")) as r:
                            if r.status == 200:
                                return
                    except aiohttp.ClientError:
                        pass
                    await asyncio.sleep(0.1)

    def _url(self, path: str) -> str:
        return f"http://127.0.0.1:{self.health_port}{path}"

    async def admin(self, path: str):
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.get(self._url(path)) as resp:
                return resp.status, await resp.json()

    async def wait_killed(self, timeout: float = 30.0) -> None:
        """Block until the fault plan's crash point fires."""
        async with asyncio.timeout(timeout):
            await self.proc.wait()
        assert self.proc.returncode == -signal.SIGKILL

    async def kill_now(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        await self.proc.wait()

    async def stall(self, duration: float) -> None:
        """SIGSTOP the worker for ``duration`` seconds, then SIGCONT.

        A stalled worker is alive-but-frozen — the GC-pause shape: its
        leases/heartbeats expire while its process state (in-flight
        transfers, unacked deliveries) survives and resumes.  The
        single-worker mirror of the soak rig's stall chaos."""
        self.proc.send_signal(signal.SIGSTOP)
        try:
            await asyncio.sleep(duration)
        finally:
            self.proc.send_signal(signal.SIGCONT)

    async def wait_job_state(self, job_id: str, state: str,
                             timeout: float = 30.0) -> dict:
        async with asyncio.timeout(timeout):
            while True:
                status, body = await self.admin(f"/v1/jobs/{job_id}")
                if status == 200 and body.get("state") == state:
                    return body
                await asyncio.sleep(0.1)

    def publish(self, job_id: str, uri: str):
        """Publish a Download over the real AMQP wire (own connection)."""
        return self._publish_body(download_msg(job_id, uri))

    async def _publish_body(self, body: bytes) -> None:
        from downloader_tpu.mq.amqp import AmqpQueue

        queue = AmqpQueue(self.amqp.url, heartbeat=5)
        await queue.connect()
        try:
            await queue.publish(schemas.DOWNLOAD_QUEUE, body)
        finally:
            await queue.close()

    # -- invariant helpers ---------------------------------------------
    @property
    def journal_path(self) -> str:
        return os.path.join(self.downloads, JOURNAL_DIRNAME,
                            JOURNAL_FILENAME)

    def journal_state(self):
        return replay(self.journal_path)

    def orphan_workdirs(self) -> list:
        try:
            entries = os.listdir(self.downloads)
        except OSError:
            return []
        return [e for e in entries if not e.startswith(".")
                and os.path.isdir(os.path.join(self.downloads, e))]

    async def staged_bytes(self, job_id: str) -> bytes:
        return await self.store.get_object(
            STAGING, _object_name(job_id, "show.mkv"))

    async def assert_staged_ok(self, job_id: str) -> None:
        from downloader_tpu.stages.upload import parse_done_marker

        assert await self.staged_bytes(job_id) == PAYLOAD
        # uncoordinated jobs seal with the reference-parity b"true";
        # fleet-coordinated ones seal a fenced JSON document — both
        # parse as done (existence is the probe contract)
        marker = await self.store.get_object(
            STAGING, f"{job_id}/original/done")
        assert parse_done_marker(marker)["done"] is True

    async def live_leases(self) -> list:
        """Lease keys whose coordination doc is LIVE (a delete leaves a
        tombstone object behind until the fleet GC sweeps it — liveness
        resolves through the coord store's get, like real readers)."""
        from downloader_tpu.fleet.coord import BucketCoordStore

        coord = BucketCoordStore(self.store, STAGING)
        out = []
        async for info in self.store.list_objects(STAGING,
                                                  ".fleet/leases/"):
            key = info.name[len(".fleet/"):]
            if await coord.get(key) is not None:
                out.append(info.name)
        return out

    async def stop(self) -> None:
        if self.proc is not None and self.proc.returncode is None:
            self.proc.send_signal(signal.SIGKILL)
            await self.proc.wait()
        if self.store is not None:
            await self.store.close()
        await self.s3.stop()
        await self.amqp.stop()


async def test_sigkill_mid_download_then_restart_completes(tmp_path):
    """Kill the worker while origin bytes are landing in ``.partial``:
    the restart keeps the resumable workdir, the redelivery adopts the
    journal placeholder, and the job finishes with staged bytes
    hash-identical to the origin."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, _gets = await start_origin(chunk_delay=0.15)
    try:
        rig.write_config()
        await rig.spawn_worker()
        await rig.publish("crash-dl", uri)

        partial = os.path.join(rig.downloads, "crash-dl",
                               "show.mkv.partial")
        async with asyncio.timeout(20):
            while not (os.path.exists(partial)
                       and os.path.getsize(partial) > 0):
                await asyncio.sleep(0.02)
        await rig.kill_now()  # SIGKILL with the transfer mid-flight

        # the torn world: journal knows the job, workdir holds .partial
        state = rig.journal_state()
        assert "crash-dl" in state.live()
        assert rig.orphan_workdirs() == ["crash-dl"]

        await rig.spawn_worker()  # no fault plan: clean second life
        _status, ready = await rig.admin("/readyz")
        recovery = ready.get("recovery") or {}
        assert recovery.get("recoveredJobs", 0) >= 1
        assert recovery.get("resumableWorkdirs", 0) >= 1

        body = await rig.wait_job_state("crash-dl", "DONE")
        assert body.get("recovered") is True
        await rig.assert_staged_ok("crash-dl")
        assert rig.orphan_workdirs() == []
        final = rig.journal_state().jobs.get("crash-dl")
        assert final is not None and final.state == "DONE"
        assert final.settle == "ack"
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_sigstop_resume_mid_download_completes(tmp_path):
    """Stall-resume chaos (SIGSTOP/SIGCONT, no kill): the worker is
    frozen mid-transfer long enough for any lease/heartbeat to expire,
    then resumed.  Unlike a SIGKILL there is no restart and no journal
    replay — the process itself must ride out its own absence: the job
    completes exactly once, staged bytes byte-identical, no orphan
    workdirs, and the journal shows a single clean settle."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, gets = await start_origin(chunk_delay=0.15)
    try:
        rig.write_config()
        await rig.spawn_worker()
        await rig.publish("stall-dl", uri)

        partial = os.path.join(rig.downloads, "stall-dl",
                               "show.mkv.partial")
        async with asyncio.timeout(20):
            while not (os.path.exists(partial)
                       and os.path.getsize(partial) > 0):
                await asyncio.sleep(0.02)
        # freeze mid-splice: longer than a short lease TTL would be,
        # far shorter than the origin/watchdog stall budgets
        await rig.stall(1.5)

        body = await rig.wait_job_state("stall-dl", "DONE")
        assert body.get("recovered") is not True  # same life, no replay
        await rig.assert_staged_ok("stall-dl")
        assert rig.orphan_workdirs() == []
        final = rig.journal_state().jobs.get("stall-dl")
        assert final is not None and final.state == "DONE"
        assert final.settle == "ack"
        assert gets[0] == 1  # one origin fetch: the stall refetched nothing
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_sigkill_between_file_and_done_marker(tmp_path):
    """Crash point ``store.put`` after=1: the media file is staged, the
    done marker is not — the exact torn-publish window the manifest
    guards.  The restarted attempt resumes (no second byte upload),
    verifies the set, seals it, and settles DONE."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, gets = await start_origin()
    try:
        rig.write_config()
        await rig.spawn_worker(fault_plan=(
            '[{"seam": "store.put", "kind": "crash", "after": 1,'
            ' "count": 1}]'
        ))
        await rig.publish("crash-seal", uri)
        await rig.wait_killed()

        # torn state: bytes staged, set NOT sealed
        assert await rig.staged_bytes("crash-seal") == PAYLOAD
        with pytest.raises(Exception):
            await rig.store.get_object(STAGING,
                                       "crash-seal/original/done")

        await rig.spawn_worker()
        await rig.wait_job_state("crash-seal", "DONE")
        await rig.assert_staged_ok("crash-seal")
        assert rig.orphan_workdirs() == []
        assert gets[0] >= 1
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_sigkill_pre_ack_idempotent_redelivery(tmp_path):
    """Crash point ``settle.ack``: everything staged and published, the
    delivery never settled.  The broker redelivers; the restarted
    worker's idempotency probe (done marker) skips the stages and the
    job settles DONE without re-staging a byte."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, gets = await start_origin()
    try:
        rig.write_config()
        await rig.spawn_worker(fault_plan=(
            '[{"seam": "settle.ack", "kind": "crash", "count": 1}]'
        ))
        await rig.publish("crash-ack", uri)
        await rig.wait_killed()

        # fully staged and sealed — only the ack is missing
        await rig.assert_staged_ok("crash-ack")
        state = rig.journal_state()
        assert state.jobs["crash-ack"].settle is None  # never settled

        origin_gets_before = gets[0]
        await rig.spawn_worker()
        body = await rig.wait_job_state("crash-ack", "DONE")
        assert body.get("recovered") is True
        await rig.assert_staged_ok("crash-ack")
        assert gets[0] == origin_gets_before  # idempotent skip: no refetch
        assert rig.orphan_workdirs() == []
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_retry_counter_survives_sigkill(tmp_path):
    """An attempt fails (counter = 1, journaled), the NEXT attempt is
    SIGKILLed mid-upload: after the restart the placeholder carries the
    restored counter — monotone across the crash, never reset by the
    redelivery — and the job still completes."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, _gets = await start_origin()
    try:
        rig.write_config()
        await rig.spawn_worker(fault_plan=(
            '[{"seam": "store.put", "kind": "error", "count": 1,'
            ' "fault": "transient"},'
            ' {"seam": "store.put", "kind": "crash", "after": 1,'
            ' "count": 1}]'
        ))
        await rig.publish("crash-retry", uri)
        await rig.wait_killed()

        # the pre-crash journal carries the first attempt's failure
        state = rig.journal_state()
        assert state.jobs["crash-retry"].failures == 1

        await rig.spawn_worker()
        body = await rig.wait_job_state("crash-retry", "DONE")
        assert body.get("recovered") is True
        await rig.assert_staged_ok("crash-retry")
        # monotone: the boot compaction snapshot preserved failures=1
        # (DONE then cleared it — never a reset to 0 mid-history)
        with open(rig.journal_path, "r", encoding="utf-8") as fh:
            first = fh.readline()
        assert '"failures":1' in first
        assert rig.orphan_workdirs() == []
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_sigkill_lease_holder_restart_reclaims(tmp_path):
    """Fleet enabled (bucket coordination on the staging bucket): the
    worker is killed at the fetch seam while HOLDING the content lease.
    The restarted worker (same WORKER_ID) reclaims its orphan lease at
    boot — far before the 120 s TTL — and the job completes with zero
    leases left behind."""
    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, _gets = await start_origin()
    try:
        rig.write_config(extra={
            "instance": {"download_path": rig.downloads,
                         "max_concurrent_jobs": 2,
                         "cache": {"enabled": True}},
            "fleet": {"enabled": True, "backend": "bucket",
                      "lease_ttl": 120.0, "heartbeat_interval": 1.0,
                      "liveness_ttl": 5.0},
        })
        await rig.spawn_worker(fault_plan=(
            '[{"seam": "http.fetch", "kind": "crash", "count": 1}]'
        ))
        await rig.publish("crash-lease", uri)
        await rig.wait_killed()

        # the dead worker's lease doc survives it (TTL far away)
        leases = await rig.live_leases()
        assert len(leases) == 1

        await rig.spawn_worker()
        _status, ready = await rig.admin("/readyz")
        recovery = ready.get("recovery") or {}
        assert recovery.get("reclaimedLeases", 0) == 1

        await rig.wait_job_state("crash-lease", "DONE")
        await rig.assert_staged_ok("crash-lease")
        assert await rig.live_leases() == []  # nothing leaked
        assert rig.orphan_workdirs() == []
    finally:
        await rig.stop()
        await origin.cleanup()


async def test_torn_tail_promote_demoted_on_restart(tmp_path):
    """ISSUE 20: the ``torn`` disk drill at the promote seam — the
    rename outlives the data pages (zeroed tail), then SIGKILL, the
    exact state a power cut leaves.  Boot recovery must re-verify the
    landing sidecar, DEMOTE the torn output (delete it for re-fetch,
    never promote the hole to staging), and the redelivered job must
    settle DONE exactly once with staged bytes hash-identical to the
    origin."""
    from downloader_tpu.platform.vfs import TORN_TAIL_BYTES
    from downloader_tpu.store import scrub

    rig = CrashRig(tmp_path)
    await rig.start_backends()
    origin, uri, gets = await start_origin()
    try:
        rig.write_config()
        await rig.spawn_worker(fault_plan=(
            '[{"seam": "disk.promote", "kind": "disk",'
            ' "disk_mode": "torn", "count": 1}]'
        ))
        await rig.publish("torn-dl", uri)
        await rig.wait_killed()

        # the torn world: the output IS renamed into place, its size
        # checks out, but the tail pages never reached the disk — and
        # the durably-promoted sidecar still holds the true digest
        workdir = os.path.join(rig.downloads, "torn-dl")
        out = os.path.join(workdir, "show.mkv")
        assert os.path.exists(out)
        data = open(out, "rb").read()
        assert len(data) == len(PAYLOAD)
        assert data != PAYLOAD
        assert data[-TORN_TAIL_BYTES:] == b"\0" * TORN_TAIL_BYTES
        landed = scrub.read_landed(workdir)
        assert landed.get("show.mkv")  # the digest survived the crash
        # nothing reached staging before the crash
        with pytest.raises(Exception):
            await rig.staged_bytes("torn-dl")

        await rig.spawn_worker()  # clean second life: no fault plan
        _status, ready = await rig.admin("/readyz")
        recovery = ready.get("recovery") or {}
        assert recovery.get("demotedOutputs", 0) >= 1
        assert recovery.get("resumableWorkdirs", 0) >= 1

        body = await rig.wait_job_state("torn-dl", "DONE")
        assert body.get("recovered") is True
        await rig.assert_staged_ok("torn-dl")
        assert gets[0] == 2  # demoted -> full re-fetch from origin
        assert rig.orphan_workdirs() == []
        final = rig.journal_state().jobs.get("torn-dl")
        assert final is not None and final.state == "DONE"
        assert final.settle == "ack"
    finally:
        await rig.stop()
        await origin.cleanup()
