"""Shared test helpers."""

import asyncio
import re


async def start_http_server(handler, path: str = "/show.mkv"):
    """Serve ``handler`` (an aiohttp GET coroutine) at ``path`` on an
    ephemeral localhost port.

    Returns ``(runner, base_url)``; callers own ``await runner.cleanup()``.
    """
    from aiohttp import web

    app = web.Application()
    app.router.add_get(path, handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def start_media_server(payload: bytes = b"V" * 4096,
                             delay: float = 0.0,
                             path: str = "/show.mkv"):
    """Serve ``payload`` at ``path`` on an ephemeral localhost port.

    Returns ``(runner, base_url)``; callers own ``await runner.cleanup()``.
    """
    from aiohttp import web

    async def serve(_request):
        if delay:
            await asyncio.sleep(delay)
        return web.Response(body=payload)

    return await start_http_server(serve, path)


class RangeOrigin:
    """One HTTP origin serving a single payload with byte-range +
    If-Range support — the fixture the origin-plane racing tests and
    the racing bench share.

    Knobs model origin pathologies deterministically:

    - ``rate``: bytes/s pacing (a throttled mirror)
    - ``fail_after``: total payload bytes this origin will ever serve;
      past the budget the connection is cut mid-body (an origin dying
      mid-range) and later requests are cut immediately (it stays dead)
    - ``hang``: never send response headers (a black-holed origin —
      exercises first-byte hedges and straggler duplication)

    Counters: ``served`` (payload bytes actually written to sockets)
    and ``requests``.
    """

    def __init__(self, payload: bytes, *, etag: str = '"range-origin"',
                 rate: float = 0.0, path: str = "/media.bin",
                 fail_after: int = None, hang: bool = False):
        self.payload = payload
        self.etag = etag
        self.rate = rate
        self.path = path
        self.fail_after = fail_after
        self.hang = hang
        self.served = 0
        self.requests = 0
        self._runner = None
        self.url = None

    async def start(self) -> str:
        self._runner, base = await start_http_server(self._serve,
                                                     self.path)
        self.url = base + self.path
        return self.url

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _serve(self, request):
        from aiohttp import web

        self.requests += 1
        if self.hang:
            await asyncio.Event().wait()  # until the client gives up
        payload = self.payload
        start, end, status = 0, len(payload), 200
        rng = request.headers.get("Range")
        if_range = request.headers.get("If-Range")
        if rng and (if_range is None or if_range == self.etag):
            match = re.fullmatch(r"bytes=(\d+)-(\d*)", rng)
            if match:
                start = int(match.group(1))
                end = (int(match.group(2)) + 1 if match.group(2)
                       else len(payload))
                end = min(end, len(payload))
                status = 206
        resp = web.StreamResponse(status=status)
        resp.headers["ETag"] = self.etag
        if status == 206:
            resp.headers["Content-Range"] = (
                f"bytes {start}-{end - 1}/{len(payload)}"
            )
        resp.content_length = end - start
        await resp.prepare(request)
        chunk = 64 << 10
        if self.rate:
            # small chunks keep the pacing smooth at low rates
            chunk = max(min(chunk, int(self.rate / 10)), 4 << 10)
        pos = start
        try:
            while pos < end:
                n = min(chunk, end - pos)
                if (self.fail_after is not None
                        and self.served + n > self.fail_after):
                    n = max(self.fail_after - self.served, 0)
                    if n:
                        await resp.write(payload[pos:pos + n])
                        self.served += n
                    # cut the connection mid-body: the origin is dead
                    request.transport.close()
                    return resp
                await resp.write(payload[pos:pos + n])
                self.served += n
                pos += n
                if self.rate:
                    await asyncio.sleep(n / self.rate)
        except (ConnectionError, OSError):
            # a racing loser's connection was cancelled mid-write:
            # normal, not a server error worth a traceback
            return resp
        return resp
