"""Shared test helpers."""

import asyncio


async def start_http_server(handler, path: str = "/show.mkv"):
    """Serve ``handler`` (an aiohttp GET coroutine) at ``path`` on an
    ephemeral localhost port.

    Returns ``(runner, base_url)``; callers own ``await runner.cleanup()``.
    """
    from aiohttp import web

    app = web.Application()
    app.router.add_get(path, handler)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def start_media_server(payload: bytes = b"V" * 4096,
                             delay: float = 0.0,
                             path: str = "/show.mkv"):
    """Serve ``payload`` at ``path`` on an ephemeral localhost port.

    Returns ``(runner, base_url)``; callers own ``await runner.cleanup()``.
    """
    from aiohttp import web

    async def serve(_request):
        if delay:
            await asyncio.sleep(delay)
        return web.Response(body=payload)

    return await start_http_server(serve, path)
