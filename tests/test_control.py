"""Control-plane tests: registry state machine, cooperative cancellation,
admin API (jobs/cancel/pause/drain), priority scheduling, and the
malformed-delivery guard.

The acceptance slice: an in-flight download-stage job cancelled through
``POST /v1/jobs/{id}/cancel`` settles its delivery without requeue,
leaves no partial files in the staging dir, and shows ``CANCELLED`` in
``GET /v1/jobs/{id}`` — against the in-memory broker + MiniS3.
"""

import asyncio
import os

import pytest
from aiohttp import web
from minis3 import MiniS3

from downloader_tpu import schemas
from downloader_tpu.control.cancel import CancelToken, JobCancelled
from downloader_tpu.control.registry import (
    ADMITTED, CANCELLED, DONE, FAILED, PUBLISHING, RECEIVED,
    RUNNING, IllegalTransition, JobRegistry,
)
from downloader_tpu.control.scheduler import PriorityScheduler, priority_rank
from downloader_tpu.health import build_app
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.orchestrator import Orchestrator
from downloader_tpu.platform import metrics as prom
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.logging import NullLogger
from downloader_tpu.platform.telemetry import STATUS_QUEUE, Telemetry
from downloader_tpu.stages.base import Job, StageContext, register_stage
from downloader_tpu.store import InMemoryObjectStore
from downloader_tpu.store.s3 import S3ObjectStore

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# Registry state machine
# ---------------------------------------------------------------------------

def test_registry_legal_walk_and_timing():
    registry = JobRegistry()
    record = registry.register("j1", "card-1", priority="HIGH")
    assert record.state == RECEIVED
    registry.transition(record, ADMITTED)
    registry.transition(record, RUNNING, stage="download")
    registry.transition(record, RUNNING, stage="process")
    registry.transition(record, RUNNING, stage="upload")
    registry.transition(record, PUBLISHING)
    registry.transition(record, DONE)
    assert record.terminal
    assert set(record.stage_seconds) == {"download", "process", "upload"}
    # terminal record keeps the last stage it entered for inspection
    assert record.stage == "upload"
    assert registry.get("j1") is record
    assert registry.counts() == {DONE: 1}


def test_registry_idempotent_skip_path():
    registry = JobRegistry()
    record = registry.register("j1", "c")
    registry.transition(record, ADMITTED)
    registry.transition(record, PUBLISHING)  # done marker already staged
    registry.transition(record, DONE)
    assert record.state == DONE


@pytest.mark.parametrize("walk,bad", [
    ([], PUBLISHING),                       # RECEIVED -> PUBLISHING
    ([], DONE),                             # RECEIVED -> DONE
    ([], RUNNING),                          # RECEIVED -> RUNNING (skips gate)
    ([ADMITTED, RUNNING, FAILED], RUNNING),  # out of terminal
    ([ADMITTED, PUBLISHING, DONE], CANCELLED),
    ([], PUBLISHING),                       # RECEIVED -> PUBLISHING (skips
                                            # admission; note ADMITTED ->
                                            # DROPPED_POISON became legal with
                                            # the classified probe/publish
                                            # failure paths)
])
def test_registry_illegal_transitions_raise(walk, bad):
    registry = JobRegistry()
    record = registry.register("j1", "c")
    for state in walk:
        registry.transition(record, state)
    with pytest.raises(IllegalTransition):
        registry.transition(record, bad)


def test_registry_unknown_state_raises():
    registry = JobRegistry()
    record = registry.register("j1", "c")
    with pytest.raises(IllegalTransition):
        registry.transition(record, "LIMBO")


def test_registry_terminal_ring_is_bounded():
    registry = JobRegistry(terminal_ring=4)
    for i in range(10):
        record = registry.register(f"j{i}", "c")
        registry.transition(record, FAILED, reason="test")
    assert len(registry.jobs()) == 4
    # oldest evicted, newest kept
    assert registry.get("j0") is None
    assert registry.get("j9") is not None
    assert registry.counts() == {FAILED: 4}


def test_registry_cancel_only_fires_live_records():
    registry = JobRegistry()
    record = registry.register("j1", "c")
    fired = registry.cancel("j1", reason="op")
    assert fired == [record]
    assert record.cancel.cancelled and record.cancel.reason == "op"
    assert record.state == RECEIVED  # state moves only when the job settles
    # second cancel is a no-op; unknown job fires nothing
    assert registry.cancel("j1") == []
    assert registry.cancel("nope") == []
    registry.transition(record, CANCELLED, reason="op")
    assert registry.cancel("j1") == []  # terminal: nothing live to fire


def test_registry_metrics_gauge_and_transitions():
    metrics = prom.new(f"ctl{os.urandom(3).hex()}")
    registry = JobRegistry(metrics=metrics, terminal_ring=1)
    a = registry.register("a", "c")
    b = registry.register("b", "c")
    registry.transition(a, ADMITTED)
    registry.transition(a, RUNNING, stage="download")
    registry.transition(a, FAILED, reason="x")
    registry.transition(b, FAILED, reason="x")  # evicts a from the ring

    def gauge(state):
        return metrics.jobs_by_state.labels(state=state)._value.get()

    assert gauge(RECEIVED) == 0
    assert gauge(FAILED) == 1  # ring holds only b
    assert metrics.job_state_transitions.labels(
        from_state=RECEIVED, to_state=ADMITTED)._value.get() == 1


# ---------------------------------------------------------------------------
# Cancel token
# ---------------------------------------------------------------------------

async def test_cancel_token_raise_and_guard():
    token = CancelToken("j1")
    token.raise_if_cancelled()  # live: no-op
    assert await token.guard(asyncio.sleep(0, result=42)) == 42

    async def fire_soon():
        await asyncio.sleep(0.05)
        token.cancel("test")

    firer = asyncio.create_task(fire_soon())
    with pytest.raises(JobCancelled) as err:
        await token.guard(asyncio.sleep(30))
    await firer
    assert err.value.job_id == "j1" and err.value.reason == "test"
    with pytest.raises(JobCancelled):
        token.raise_if_cancelled()
    # already-cancelled guard never runs the work
    ran = []

    async def work():
        ran.append(1)

    with pytest.raises(JobCancelled):
        await token.guard(work())
    assert ran == []


async def test_cancel_token_guard_propagates_inner_error():
    token = CancelToken("j1")

    async def boom():
        raise RuntimeError("inner")

    with pytest.raises(RuntimeError, match="inner"):
        await token.guard(boom())


# ---------------------------------------------------------------------------
# Priority scheduler
# ---------------------------------------------------------------------------

async def test_scheduler_grants_by_priority_class():
    sched = PriorityScheduler(slots=1, aging_seconds=60.0)
    await sched.acquire(priority_rank("NORMAL"))  # occupy the slot
    order = []

    async def worker(name, rank):
        await sched.acquire(rank)
        order.append(name)
        sched.release()

    tasks = []
    for name, rank in [("bulk", 2), ("normal", 1), ("high", 0),
                       ("high2", 0)]:
        tasks.append(asyncio.create_task(worker(name, rank)))
        await asyncio.sleep(0.01)  # deterministic enqueue order
    assert sched.waiting == 4
    sched.release()  # free the occupied slot -> cascade of grants
    async with asyncio.timeout(5):
        await asyncio.gather(*tasks)
    assert order == ["high", "high2", "normal", "bulk"]


async def test_scheduler_aging_beats_fresh_high_priority():
    sched = PriorityScheduler(slots=1, aging_seconds=0.05)
    await sched.acquire(0)  # occupy
    order = []

    async def worker(name, rank):
        await sched.acquire(rank)
        order.append(name)
        sched.release()

    bulk = asyncio.create_task(worker("bulk", 2))
    await asyncio.sleep(0.2)  # bulk ages >= 3 classes
    high = asyncio.create_task(worker("high", 0))
    await asyncio.sleep(0.01)
    sched.release()
    async with asyncio.timeout(5):
        await asyncio.gather(bulk, high)
    assert order == ["bulk", "high"]


async def test_scheduler_release_skips_cancelled_waiter_same_tick():
    """A waiter cancelled in the same tick as a release (cancel token
    guard racing a finishing job) must be dropped without consuming the
    slot — set_result on its cancelled future would raise out of the
    releasing job's finally and leak the slot forever."""
    sched = PriorityScheduler(slots=1)
    await sched.acquire(1)
    task = asyncio.create_task(sched.acquire(1))
    await asyncio.sleep(0.01)
    task.cancel()        # future cancelled; waiter still queued
    sched.release()      # same tick: must not raise, must keep the slot
    await asyncio.gather(task, return_exceptions=True)
    async with asyncio.timeout(1):
        await sched.acquire(0)  # the slot is genuinely free


async def test_scheduler_cancelled_waiter_releases_cleanly():
    sched = PriorityScheduler(slots=1)
    await sched.acquire(1)
    task = asyncio.create_task(sched.acquire(1))
    await asyncio.sleep(0.01)
    assert sched.waiting == 1
    task.cancel()
    await asyncio.gather(task, return_exceptions=True)
    assert sched.waiting == 0
    sched.release()
    # the slot is actually free again
    async with asyncio.timeout(1):
        await sched.acquire(0)


# ---------------------------------------------------------------------------
# Orchestrator wiring helpers
# ---------------------------------------------------------------------------

def make_download_msg(uri: str, job_id: str = "job-1",
                      priority: str = "NORMAL") -> bytes:
    return schemas.encode(
        schemas.Download(
            media=schemas.Media(
                id=job_id,
                creator_id="card-1",
                name="A Show",
                type=schemas.MediaType.Value("MOVIE"),
                source=schemas.SourceType.Value("HTTP"),
                source_uri=uri,
            ),
            priority=schemas.JobPriority.Value(priority),
        )
    )


async def make_orchestrator(tmp_path, broker, store, instance=None, **kwargs):
    config_data = {"instance": {
        "download_path": str(tmp_path / "downloads"),
        **(instance or {}),
    }}
    mq = MemoryQueue(broker)
    telem_mq = MemoryQueue(broker)
    await telem_mq.connect()
    orchestrator = Orchestrator(
        config=ConfigNode(config_data),
        mq=mq,
        store=store,
        telemetry=Telemetry(telem_mq),
        metrics=prom.new(f"ctl{os.urandom(4).hex()}"),
        logger=NullLogger(),
        **kwargs,
    )
    await orchestrator.start()
    return orchestrator


async def serve_admin(orchestrator):
    """Run the health+control app on an ephemeral port; returns
    (session, base_url, cleanup coroutine fn)."""
    import aiohttp

    app = build_app(orchestrator, orchestrator.metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    session = aiohttp.ClientSession()

    async def cleanup():
        await session.close()
        await runner.cleanup()

    return session, f"http://127.0.0.1:{port}", cleanup


async def start_slow_server(chunks=200, chunk=b"x" * 4096, delay=0.02,
                            etag=None):
    """A trickle HTTP server: GET streams chunked slowly (cancellable
    mid-transfer); HEAD answers instantly (with a strong validator when
    ``etag`` is set, so the content cache can key it)."""
    gets = [0]

    async def serve(request):
        headers = {"ETag": etag} if etag else {}
        if request.method == "HEAD":
            return web.Response(headers=headers)
        gets[0] += 1
        resp = web.StreamResponse(headers=headers)
        resp.enable_chunked_encoding()
        await resp.prepare(request)
        slow = gets[0] == 1  # later fetches (failover retries) are fast
        for _ in range(chunks):
            await resp.write(chunk)
            if slow and delay:
                await asyncio.sleep(delay)
        return resp

    app = web.Application()
    app.router.add_get("/media.mkv", serve)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}", gets


async def wait_for(predicate, timeout=10.0):
    async with asyncio.timeout(timeout):
        while not predicate():
            await asyncio.sleep(0.01)


# ---------------------------------------------------------------------------
# Malformed-delivery guard
# ---------------------------------------------------------------------------

async def test_malformed_delivery_is_acked_not_requeued(tmp_path):
    broker = InMemoryBroker()  # NO redelivery cap: a nack would hot-loop
    store = InMemoryObjectStore()
    orchestrator = await make_orchestrator(tmp_path, broker, store)
    try:
        broker.publish(schemas.DOWNLOAD_QUEUE, b"\xff\xff\xff\xff garbage")
        async with asyncio.timeout(5):
            await broker.join(schemas.DOWNLOAD_QUEUE)
        assert broker.idle(schemas.DOWNLOAD_QUEUE)
        assert broker.dropped == []
        assert orchestrator.metrics.jobs_failed.labels(
            reason="malformed")._value.get() == 1
        # never entered the registry (no job id to key it on)
        assert orchestrator.registry.jobs() == []
    finally:
        await orchestrator.shutdown(grace_seconds=1)


# ---------------------------------------------------------------------------
# Acceptance: cancel an in-flight download via the admin API
# ---------------------------------------------------------------------------

async def test_cancel_inflight_download_via_api(tmp_path):
    """POST /v1/jobs/{id}/cancel against a job mid-transfer: the delivery
    settles without requeue, the staging dir holds no partial files, and
    GET /v1/jobs/{id} reports CANCELLED — in-memory broker + MiniS3."""
    runner, base, gets = await start_slow_server(chunks=2000, delay=0.02)
    s3 = MiniS3()
    await s3.start()
    store = S3ObjectStore(f"http://127.0.0.1:{s3.port}", "AKIA", "SECRET")
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(tmp_path, broker, store)
    session, api, api_cleanup = await serve_admin(orchestrator)
    try:
        broker.publish(schemas.DOWNLOAD_QUEUE,
                       make_download_msg(f"{base}/media.mkv", "job-c"))
        # mid-transfer: the download stage is RUNNING and bytes flowed
        await wait_for(lambda: (r := orchestrator.registry.get("job-c"))
                       is not None and r.state == RUNNING)
        await wait_for(lambda: gets[0] >= 1)
        download_dir = tmp_path / "downloads" / "job-c"
        await wait_for(lambda: download_dir.exists())

        async with session.post(f"{api}/v1/jobs/job-c/cancel",
                                json={"reason": "operator test"}) as resp:
            assert resp.status == 202
            body = await resp.json()
            assert body["job"]["cancelRequested"] is True

        # delivery settles (ack, no requeue), queue fully drains
        async with asyncio.timeout(10):
            await broker.join(schemas.DOWNLOAD_QUEUE)
        assert broker.idle(schemas.DOWNLOAD_QUEUE)
        assert broker.depth(schemas.DOWNLOAD_QUEUE) == 0
        assert broker.published(schemas.CONVERT_QUEUE) == []

        # no partial files left in the staging dir
        assert not download_dir.exists()

        # the record is terminal CANCELLED, with the operator's reason
        await wait_for(
            lambda: orchestrator.registry.get("job-c").state == CANCELLED
        )
        async with session.get(f"{api}/v1/jobs/job-c") as resp:
            assert resp.status == 200
            job = await resp.json()
        assert job["state"] == CANCELLED
        assert job["reason"] == "operator test"
        # streaming dispatch: the combined RUNNING attribution is the
        # stage a mid-transfer cancel lands in
        assert job["stage"] == "pipeline"
        assert orchestrator.metrics.jobs_cancelled._value.get() == 1

        # telemetry announced the terminal CANCELLED status
        statuses = [
            schemas.decode(schemas.TelemetryStatusEvent, raw).status
            for raw in broker.published(STATUS_QUEUE)
        ]
        assert schemas.TelemetryStatus.Value("CANCELLED") in statuses
    finally:
        await api_cleanup()
        await orchestrator.shutdown(grace_seconds=2)
        await store.close()
        await s3.stop()
        await runner.cleanup()


async def test_cancel_transition_precedes_telemetry_emit(tmp_path):
    """Regression (graftlint ack-settle-atomicity, found by the PR 11
    tree-wide sweep): the cancel settle path used to await the CANCELLED
    telemetry emit BETWEEN delivery.ack() and registry.transition, so
    anything woken by the ack (broker join, drain, /v1/jobs pollers)
    could observe a settled-but-not-terminal record.  The terminal
    transition must already be visible when the telemetry emit runs."""
    runner, base, gets = await start_slow_server(chunks=2000, delay=0.02)
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore()
    )
    cancel_status = schemas.TelemetryStatus.Value("CANCELLED")
    states_at_emit = []
    real_emit = orchestrator.telemetry.emit_status

    async def spying_emit(job_id, status):
        if status == cancel_status:
            record = orchestrator.registry.get(job_id)
            states_at_emit.append(record.state if record else None)
        return await real_emit(job_id, status)

    orchestrator.telemetry.emit_status = spying_emit
    try:
        broker.publish(schemas.DOWNLOAD_QUEUE,
                       make_download_msg(f"{base}/media.mkv", "job-limbo"))
        await wait_for(lambda: (r := orchestrator.registry.get("job-limbo"))
                       is not None and r.state == RUNNING)
        await wait_for(lambda: gets[0] >= 1)
        assert orchestrator.registry.cancel("job-limbo", reason="limbo test")
        async with asyncio.timeout(10):
            await broker.join(schemas.DOWNLOAD_QUEUE)
        await wait_for(
            lambda: orchestrator.registry.get("job-limbo").state == CANCELLED
        )
        # the spy ran (telemetry did announce the cancel) and saw the
        # record ALREADY terminal — never the settled-but-RUNNING limbo
        assert states_at_emit == [CANCELLED]
    finally:
        await orchestrator.shutdown(grace_seconds=2)
        await runner.cleanup()


async def test_cancel_unknown_and_terminal_jobs(tmp_path):
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore()
    )
    session, api, api_cleanup = await serve_admin(orchestrator)
    try:
        async with session.post(f"{api}/v1/jobs/ghost/cancel") as resp:
            assert resp.status == 404
        # a finished job is known but not cancellable
        record = orchestrator.registry.register("done-job", "c")
        orchestrator.registry.transition(record, FAILED, reason="x")
        async with session.post(f"{api}/v1/jobs/done-job/cancel") as resp:
            assert resp.status == 409
    finally:
        await api_cleanup()
        await orchestrator.shutdown(grace_seconds=1)


# ---------------------------------------------------------------------------
# Coalesced waiter survives leader cancellation
# ---------------------------------------------------------------------------

async def test_coalesced_waiter_survives_leader_cancel(tmp_path):
    runner, base, gets = await start_slow_server(
        chunks=400, delay=0.02, etag='"v1"'
    )
    broker = InMemoryBroker()
    store = InMemoryObjectStore()
    orchestrator = await make_orchestrator(
        tmp_path, broker, store,
        instance={"cache": {"path": str(tmp_path / "cache")},
                  "max_concurrent_jobs": 4},
    )
    try:
        uri = f"{base}/media.mkv"
        broker.publish(schemas.DOWNLOAD_QUEUE, make_download_msg(uri, "lead"))
        # leader must be mid-fetch before the second job arrives
        await wait_for(lambda: gets[0] >= 1)
        broker.publish(schemas.DOWNLOAD_QUEUE, make_download_msg(uri, "wait"))
        flights = orchestrator.stage_resources["cache_singleflight"]
        await wait_for(lambda: any(
            f.waiters >= 1 for f in flights._inflight.values()
        ))

        assert orchestrator.registry.cancel("lead", reason="test")
        async with asyncio.timeout(30):
            await broker.join(schemas.DOWNLOAD_QUEUE)

        # the waiter failed over to its own fetch and completed
        converts = [
            schemas.decode(schemas.Convert, raw).media.id
            for raw in broker.published(schemas.CONVERT_QUEUE)
        ]
        assert converts == ["wait"]
        assert orchestrator.registry.get("lead").state == CANCELLED
        assert orchestrator.registry.get("wait").state == DONE
        assert gets[0] == 2  # leader's aborted GET + waiter's own
    finally:
        await orchestrator.shutdown(grace_seconds=2)
        await runner.cleanup()


# ---------------------------------------------------------------------------
# Intake pause / resume / drain
# ---------------------------------------------------------------------------

async def test_pause_resume_drain_endpoints(tmp_path):
    import fake_gate_stage

    fake_gate_stage.reset()
    fake_gate_stage.GATE = asyncio.Event()
    register_stage("gate", "fake_gate_stage")
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore(), stages=["gate"]
    )
    session, api, api_cleanup = await serve_admin(orchestrator)
    try:
        broker.publish(schemas.DOWNLOAD_QUEUE,
                       make_download_msg("http://x/", "j1"))
        await wait_for(lambda: fake_gate_stage.ORDER == ["j1"])

        # drain with the job parked on the gate: grace expires -> 504
        async with session.post(f"{api}/v1/drain?grace=0.2") as resp:
            assert resp.status == 504
            body = await resp.json()
            assert body["drained"] is False and body["intakePaused"] is True

        # paused: /readyz flips to 503, new publishes stay queued
        async with session.get(f"{api}/readyz") as resp:
            assert resp.status == 503
            assert (await resp.json())["status"] == "paused"
        broker.publish(schemas.DOWNLOAD_QUEUE,
                       make_download_msg("http://x/", "j2"))
        await asyncio.sleep(0.2)
        assert broker.depth(schemas.DOWNLOAD_QUEUE) == 1
        assert orchestrator.registry.get("j2") is None

        # release the in-flight job; a second drain succeeds
        fake_gate_stage.GATE.set()
        async with session.post(f"{api}/v1/drain?grace=5") as resp:
            assert resp.status == 200
            assert (await resp.json())["drained"] is True
        assert orchestrator.registry.get("j1").state == DONE

        # resume: the queued job is picked up and completes
        async with session.post(f"{api}/v1/intake/resume") as resp:
            assert resp.status == 200
        async with session.get(f"{api}/readyz") as resp:
            assert resp.status == 200
        async with asyncio.timeout(10):
            await broker.join(schemas.DOWNLOAD_QUEUE)
        assert orchestrator.registry.get("j2").state == DONE
        assert len(broker.published(schemas.CONVERT_QUEUE)) == 2
    finally:
        await api_cleanup()
        await orchestrator.shutdown(grace_seconds=2)


# ---------------------------------------------------------------------------
# Priority ordering end-to-end
# ---------------------------------------------------------------------------

async def test_priority_classes_reorder_job_starts(tmp_path):
    import fake_gate_stage

    fake_gate_stage.reset()
    fake_gate_stage.GATE = asyncio.Event()
    register_stage("gate", "fake_gate_stage")
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore(), stages=["gate"],
        prefetch=1, instance={"scheduler_backlog": 8},
    )
    try:
        broker.publish(schemas.DOWNLOAD_QUEUE,
                       make_download_msg("http://x/", "first"))
        await wait_for(lambda: fake_gate_stage.ORDER == ["first"])
        # while the slot is held, deliver one of each class (queue order
        # deliberately worst-first)
        for job_id, priority in [("bulk", "BULK"), ("norm", "NORMAL"),
                                 ("high", "HIGH")]:
            broker.publish(schemas.DOWNLOAD_QUEUE,
                           make_download_msg("http://x/", job_id, priority))
        await wait_for(lambda: orchestrator.scheduler.waiting == 3)
        # all three are already visible to operators while queued
        states = {r.job_id: r.state for r in orchestrator.registry.jobs()}
        assert states["bulk"] == ADMITTED
        fake_gate_stage.GATE.set()
        async with asyncio.timeout(10):
            await broker.join(schemas.DOWNLOAD_QUEUE)
        assert fake_gate_stage.ORDER == ["first", "high", "norm", "bulk"]
        assert orchestrator.registry.get("high").priority == "HIGH"
    finally:
        await orchestrator.shutdown(grace_seconds=2)


# ---------------------------------------------------------------------------
# Admin auth
# ---------------------------------------------------------------------------

async def test_mutating_endpoints_require_bearer_token(tmp_path, monkeypatch):
    monkeypatch.setenv("CONTROL_TOKEN", "sekrit")
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore()
    )
    session, api, api_cleanup = await serve_admin(orchestrator)
    try:
        # reads stay open (like /metrics)
        async with session.get(f"{api}/v1/jobs") as resp:
            assert resp.status == 200
        # mutations: 401 without/with a wrong token, through with the right
        async with session.post(f"{api}/v1/jobs/x/cancel") as resp:
            assert resp.status == 401
        async with session.post(
            f"{api}/v1/intake/pause",
            headers={"Authorization": "Bearer wrong"},
        ) as resp:
            assert resp.status == 401
        assert orchestrator.intake_paused is False
        async with session.post(
            f"{api}/v1/jobs/x/cancel",
            headers={"Authorization": "Bearer sekrit"},
        ) as resp:
            assert resp.status == 404  # authorized; job just doesn't exist
    finally:
        await api_cleanup()
        await orchestrator.shutdown(grace_seconds=1)


# ---------------------------------------------------------------------------
# Stage-level cooperative checks (process/upload)
# ---------------------------------------------------------------------------

class _SlowStore:
    """Store wrapper whose per-file put is slow enough to cancel into."""

    def __init__(self, inner, delay=0.2):
        self._inner = inner
        self.delay = delay

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def fput_object(self, *args, **kwargs):
        await asyncio.sleep(self.delay)
        return await self._inner.fput_object(*args, **kwargs)


def _media(job_id="u1"):
    return schemas.Media(id=job_id, creator_id="c", name="n",
                         type=schemas.MediaType.Value("MOVIE"),
                         source=schemas.SourceType.Value("HTTP"),
                         source_uri="http://x/")


async def test_upload_stage_cancels_between_files(tmp_path):
    from downloader_tpu.stages.upload import STAGING_BUCKET, stage_factory
    from downloader_tpu.utils import EventEmitter

    files = []
    for i in range(3):
        path = tmp_path / f"f{i}.mkv"
        path.write_bytes(b"v" * 64)
        files.append(str(path))
    inner = InMemoryObjectStore()
    token = CancelToken("u1")
    ctx = StageContext(
        config=ConfigNode({"instance": {}}),
        emitter=EventEmitter(), logger=NullLogger(),
        store=_SlowStore(inner), cancel=token,
    )
    upload = await stage_factory(ctx)
    job = Job(media=_media(), last_stage={
        "files": files, "downloadPath": str(tmp_path)})
    task = asyncio.create_task(upload(job))
    # cancel once the first file landed
    await wait_for(lambda: inner._buckets.get(STAGING_BUCKET))
    token.cancel("test")
    with pytest.raises(JobCancelled):
        async with asyncio.timeout(5):
            await task
    staged = inner._buckets.get(STAGING_BUCKET, {})
    assert 0 < len(staged) < 3
    assert "u1/original/done" not in staged  # never sealed


async def test_process_stage_checks_token(tmp_path):
    from downloader_tpu.stages.process import stage_factory
    from downloader_tpu.utils import EventEmitter

    (tmp_path / "show.mkv").write_bytes(b"v")
    token = CancelToken("p1")
    token.cancel("test")
    ctx = StageContext(
        config=ConfigNode({"instance": {}}),
        emitter=EventEmitter(), logger=NullLogger(), cancel=token,
    )
    process = await stage_factory(ctx)
    with pytest.raises(JobCancelled):
        await process(Job(media=_media("p1"),
                          last_stage={"path": str(tmp_path)}))


# ---------------------------------------------------------------------------
# Jobs listing shape
# ---------------------------------------------------------------------------

async def test_jobs_listing_and_state_filter(tmp_path):
    broker = InMemoryBroker()
    orchestrator = await make_orchestrator(
        tmp_path, broker, InMemoryObjectStore()
    )
    session, api, api_cleanup = await serve_admin(orchestrator)
    try:
        record = orchestrator.registry.register("jz", "card-z", "BULK")
        orchestrator.registry.transition(record, ADMITTED)
        async with session.get(f"{api}/v1/jobs") as resp:
            body = await resp.json()
        assert body["counts"] == {ADMITTED: 1}
        assert body["intakePaused"] is False
        (job,) = body["jobs"]
        assert job["id"] == "jz" and job["priority"] == "BULK"
        async with session.get(f"{api}/v1/jobs?state=RUNNING") as resp:
            assert (await resp.json())["jobs"] == []
        async with session.get(f"{api}/v1/jobs?state=BOGUS") as resp:
            assert resp.status == 400
    finally:
        await api_cleanup()
        await orchestrator.shutdown(grace_seconds=1)
