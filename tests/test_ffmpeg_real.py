"""Opt-in integration tests against a REAL ffmpeg binary.

The hermetic suite proves the decode/encode plumbing with scripted stubs
and the OpenCV shim; this file proves the exact ffmpeg invocation the
transcode module emits — flag spelling, pix_fmt negotiation, exit-code
behavior — against ffmpeg itself (VERDICT r3 next-round item 7: one flag
typo in the real invocation would pass every hermetic test).

Skips when ffmpeg is not on PATH; ``FFMPEG_REQUIRED=1`` (set by CI,
which apt-installs ffmpeg) turns the skip into a hard failure so the CI
job can never go green without actually running these.
"""

import io
import os
import shutil
import subprocess

import pytest

from downloader_tpu import schemas
from downloader_tpu.compute.transcode import decoder_command, encoder_command
from downloader_tpu.compute.video import Y4MReader

from tests.test_upscale import _upscale_config, make_y4m

pytestmark = pytest.mark.anyio

REQUIRED = os.environ.get("FFMPEG_REQUIRED", "") == "1"


@pytest.fixture
def ffmpeg():
    binary = shutil.which("ffmpeg")
    if binary is None:
        if REQUIRED:
            pytest.fail("FFMPEG_REQUIRED=1 but no ffmpeg on PATH")
        pytest.skip("no ffmpeg on PATH")
    return binary


# mpeg4 is built into every ffmpeg (no external encoder lib needed);
# the libx264 default needs a GPL build, which CI's apt ffmpeg has, but
# parity of the INVOCATION is what this file pins, not codec choice
ENCODE_ARGS = ("-c:v", "mpeg4", "-q:v", "5")


def _ffmpeg_make_container(ffmpeg, y4m: bytes, dst: str) -> None:
    """Create a real compressed container using the exact encoder
    command line the encode back-end runs."""
    proc = subprocess.run(
        encoder_command(ffmpeg, dst, ENCODE_ARGS),
        input=y4m, capture_output=True,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]


def _ffmpeg_decode(ffmpeg, src: str) -> Y4MReader:
    """Decode using the exact decoder command line the front-end runs."""
    proc = subprocess.run(
        decoder_command(ffmpeg, src), capture_output=True,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    return Y4MReader(io.BytesIO(proc.stdout))


def test_ffmpeg_accepts_both_command_lines(ffmpeg, tmp_path):
    """Encode then decode a clip through the verbatim command lines."""
    container = str(tmp_path / "clip.mkv")
    _ffmpeg_make_container(ffmpeg, make_y4m(64, 48, frames=6), container)
    assert os.path.getsize(container) > 0

    reader = _ffmpeg_decode(ffmpeg, container)
    assert (reader.header.width, reader.header.height) == (64, 48)
    assert reader.header.subsampling == (2, 2)  # -pix_fmt yuv420p honored
    assert len(list(reader)) == 6


def test_ffmpeg_decoder_failure_exit_code(ffmpeg, tmp_path):
    """A garbage container makes the real decoder exit nonzero with a
    diagnostic on stderr — the contract the stage's error path reads."""
    junk = tmp_path / "junk.mkv"
    junk.write_bytes(os.urandom(1 << 12))
    proc = subprocess.run(
        decoder_command(ffmpeg, str(junk)), capture_output=True,
    )
    assert proc.returncode != 0
    assert proc.stderr  # -loglevel error still surfaces real errors


async def test_stage_transcodes_through_real_ffmpeg(ffmpeg, tmp_path):
    """Full product path with ffmpeg on both ends: compressed .mkv in,
    upscaled compressed .mkv out."""
    from downloader_tpu.platform.logging import NullLogger
    from downloader_tpu.stages.base import Job, StageContext, load_stages
    from downloader_tpu.utils import EventEmitter

    movie = tmp_path / "movie.mkv"
    _ffmpeg_make_container(ffmpeg, make_y4m(32, 24, frames=5), str(movie))

    ctx = StageContext(
        config=_upscale_config(
            tmp_path, decode=True, decoder=ffmpeg,
            encode=True, encoder=ffmpeg, encode_args=list(ENCODE_ARGS),
        ),
        emitter=EventEmitter(),
        logger=NullLogger(),
    )
    table = await load_stages(ctx, ["upscale"])
    job = Job(
        media=schemas.Media(id="ff1", type=schemas.MediaType.Value("MOVIE")),
        last_stage={"files": [str(movie)], "downloadPath": str(tmp_path)},
    )
    result = await table["upscale"](job)

    (out,) = result["files"]
    assert out.endswith("movie.mkv.2x.mkv")
    reader = _ffmpeg_decode(ffmpeg, out)
    assert (reader.header.width, reader.header.height) == (64, 48)
    assert len(list(reader)) == 5
    raw_bytes = 64 * 48 * 3 // 2 * 5
    assert os.path.getsize(out) < raw_bytes  # stayed compressed
