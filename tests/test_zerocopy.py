"""Zero-copy staging ratchet tests (ISSUE 19).

Four disciplines, each proven byte-exact against its fallback:

- mmap-fed / sendfile S3 uploads (``store.zero_copy``) vs the buffered
  read() path — identical bytes AND identical etags (single-put md5
  and multipart md5-of-part-md5s);
- hash-on-land — the digest carried on ``job.landed_digests`` equals
  an independent two-pass ``md5_file_hex``, on BOTH landing regimes
  (kernel splice and the ``HTTP_NO_SPLICE`` chunked loop), with the
  hop ledger proving ONE read pass per staged byte;
- the peer hardlink shared tier — co-located fs-store materialization
  links inodes instead of copying, and an ``EXDEV``-style link failure
  falls back to the byte-exact ``fget_object`` stream;
- the io_uring landing spike — probe-gated, byte-identical to pwrite.
"""

import errno
import hashlib
import os

import pytest
from helpers import start_http_server
from minis3 import MiniS3

from aiohttp import web

from downloader_tpu import schemas
from downloader_tpu.fleet import FleetPlane, MemoryCoordStore
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.logging import NullLogger
from downloader_tpu.platform.telemetry import Telemetry
from downloader_tpu.stages.base import Job, StageContext
from downloader_tpu.stages.download import stage_factory
from downloader_tpu.stages.upload import STAGING_BUCKET
from downloader_tpu.store import FilesystemObjectStore
from downloader_tpu.store.cache import ContentCache, cache_key
from downloader_tpu.store.s3 import S3ObjectStore
from downloader_tpu.utils import EventEmitter
from downloader_tpu.utils.hashing import md5_file_hex

pytestmark = pytest.mark.anyio


# ---------------------------------------------------------------------------
# mmap / sendfile upload parity (store.zero_copy A/B)
# ---------------------------------------------------------------------------

@pytest.fixture
async def server():
    s3 = MiniS3()
    await s3.start()
    yield s3
    await s3.stop()


def _client(server, zero_copy: bool) -> S3ObjectStore:
    return S3ObjectStore(
        f"http://127.0.0.1:{server.port}", "AKIA", "SECRET",
        zero_copy=zero_copy,
    )


async def test_multipart_mmap_vs_read_byte_exact_and_etag_equal(
        server, tmp_path):
    """zero_copy multipart (mmap slices / sendfile parts, unsigned
    payload) must land the SAME bytes and the SAME multipart etag as
    the buffered read() path."""
    payload = bytes(range(256)) * 1024 + b"tail"  # 256 KiB + odd tail
    src = tmp_path / "big.mkv"
    src.write_bytes(payload)
    etags = {}
    for flag in (True, False):
        client = _client(server, flag)
        client.multipart_threshold = 1 << 16
        client.multipart_part_size = 1 << 16
        try:
            if not await client.bucket_exists("staging"):
                await client.make_bucket("staging")
            key = f"zc/{flag}.mkv"
            await client.fput_object("staging", key, str(src))
            assert server.buckets["staging"][key] == payload
            etags[flag] = (await client.stat_object("staging", key)).etag
        finally:
            await client.close()
    assert not server.multipart_uploads  # both completed, none dangling
    assert etags[True] == etags[False]
    assert etags[True].endswith("-5")  # genuinely multipart both times
    assert server.auth_failures == []


async def test_single_put_sendfile_vs_read_byte_exact(server, tmp_path):
    """Below the multipart threshold on plain http the whole PUT rides
    os.sendfile; bytes and md5 etag must match the buffered path."""
    payload = os.urandom(96 << 10)
    src = tmp_path / "small.mkv"
    src.write_bytes(payload)
    etags = {}
    for flag in (True, False):
        client = _client(server, flag)
        try:
            if not await client.bucket_exists("staging"):
                await client.make_bucket("staging")
            key = f"single/{flag}.mkv"
            await client.fput_object("staging", key, str(src))
            assert server.buckets["staging"][key] == payload
            etags[flag] = (await client.stat_object("staging", key)).etag
        finally:
            await client.close()
    assert etags[True] == etags[False] == hashlib.md5(payload).hexdigest()
    assert server.auth_failures == []


async def test_fput_content_md5_hint_accepted(server, tmp_path):
    """The landed-digest hint (hash-on-land -> Content-MD5) survives
    SigV4 on both the sendfile and buffered paths."""
    payload = b"landed-once" * 4096
    src = tmp_path / "hinted.mkv"
    src.write_bytes(payload)
    digest = hashlib.md5(payload).hexdigest()
    for flag in (True, False):
        client = _client(server, flag)
        try:
            if not await client.bucket_exists("staging"):
                await client.make_bucket("staging")
            key = f"hint/{flag}.mkv"
            await client.fput_object("staging", key, str(src),
                                     content_md5=digest)
            assert server.buckets["staging"][key] == payload
            assert (await client.stat_object("staging",
                                             key)).etag == digest
        finally:
            await client.close()
    assert server.auth_failures == []


async def test_get_object_caps_unbounded_bodies(server, monkeypatch):
    """The in-memory GET path refuses to slurp a body past the cap
    (PERMANENT, names fget_object) instead of ballooning the worker
    heap.  Shrinking the module cap trips the Content-Length precheck
    without allocating 64 MiB for real."""
    import downloader_tpu.store.s3 as s3mod
    from downloader_tpu.platform.errors import PERMANENT

    client = _client(server, True)
    try:
        await client.make_bucket("b")
        await client.put_object("b", "ok", b"x" * 1024)
        assert await client.get_object("b", "ok") == b"x" * 1024
        monkeypatch.setattr(s3mod, "GET_OBJECT_MAX_BYTES", 16)
        with pytest.raises(RuntimeError, match="fget_object") as exc:
            await client.get_object("b", "ok")
        assert exc.value.fault_class is PERMANENT
    finally:
        await client.close()


# ---------------------------------------------------------------------------
# hash-on-land: one read pass per staged byte, digest identity
# ---------------------------------------------------------------------------

class _Record:
    """Hop-ledger shaped test double for StageContext.record."""

    def __init__(self):
        self.hops = {}
        self.events = []

    def note_hop(self, hop, nbytes, seconds):
        got = self.hops.setdefault(hop, [0, 0.0])
        got[0] += int(nbytes)
        got[1] += float(seconds)

    def note_transfer(self, *a, **k):
        pass

    def add_bytes(self, *a, **k):
        pass

    def event(self, kind, **fields):
        self.events.append((kind, fields))


async def _run_http_job(tmp_path, payload, media_id="job-z"):
    async def serve(request):
        return web.Response(body=payload, headers={"ETag": '"zc-1"'})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        mq = MemoryQueue(InMemoryBroker())
        await mq.connect()
        record = _Record()
        ctx = StageContext(
            config=ConfigNode({"instance": {
                "download_path": str(tmp_path / "dl")}}),
            emitter=EventEmitter(),
            logger=NullLogger(),
            telemetry=Telemetry(mq),
            record=record,
        )
        stage = await stage_factory(ctx)
        job = Job(media=schemas.Media(
            id=media_id,
            source=schemas.SourceType.Value("HTTP"),
            source_uri=f"{base}/media/file.mkv",
        ))
        await stage(job)
        out = tmp_path / "dl" / media_id / "file.mkv"
        return job, record, out
    finally:
        await runner.cleanup()


@pytest.mark.parametrize("no_splice", [False, True],
                         ids=["splice", "chunked"])
async def test_hash_on_land_digest_identity(tmp_path, monkeypatch,
                                            no_splice):
    """The landed digest equals an independent full re-read, on both
    the splice landing and the HTTP_NO_SPLICE chunked loop."""
    if no_splice:
        monkeypatch.setenv("HTTP_NO_SPLICE", "1")
    payload = bytes(range(256)) * 8192  # 2 MiB
    job, record, out = await _run_http_job(tmp_path, payload)
    assert out.read_bytes() == payload
    digest = job.landed_digests.get(str(out))
    assert digest == hashlib.md5(payload).hexdigest()
    assert digest == md5_file_hex(str(out))
    # one read pass per staged byte: the hash hop saw the file exactly
    # once (inline on the chunked path; one hot post-promote pass on
    # the splice path — never the historical two stat-side passes)
    hashed = record.hops.get("hash", [0, 0.0])[0]
    assert hashed == len(payload)


async def test_hash_on_land_off_with_integrity_disabled(tmp_path):
    """integrity.enabled: false restores the no-digest path (empty
    landed_digests, no hash hop billed at the download stage)."""
    payload = b"n" * (1 << 20)

    async def serve(request):
        return web.Response(body=payload)

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        mq = MemoryQueue(InMemoryBroker())
        await mq.connect()
        record = _Record()
        ctx = StageContext(
            config=ConfigNode({
                "instance": {"download_path": str(tmp_path / "dl")},
                "integrity": {"enabled": False},
            }),
            emitter=EventEmitter(),
            logger=NullLogger(),
            telemetry=Telemetry(mq),
            record=record,
        )
        stage = await stage_factory(ctx)
        job = Job(media=schemas.Media(
            id="job-n", source=schemas.SourceType.Value("HTTP"),
            source_uri=f"{base}/media/file.mkv",
        ))
        await stage(job)
        assert job.landed_digests == {}
        assert "hash" not in record.hops
    finally:
        await runner.cleanup()


async def test_fs_store_memo_skips_rehash_after_hinted_fput(tmp_path,
                                                           monkeypatch):
    """fput with a content_md5 hint seeds the etag memo: the following
    stat answers from (size, mtime, inode) without a full re-read."""
    import downloader_tpu.store.fs as fs_mod

    calls = {"n": 0}
    real = fs_mod._stat_with_md5

    def counting(path):
        calls["n"] += 1
        return real(path)

    monkeypatch.setattr(fs_mod, "_stat_with_md5", counting)
    store = FilesystemObjectStore(str(tmp_path / "store"))
    payload = b"memo" * 4096
    src = tmp_path / "src.bin"
    src.write_bytes(payload)
    digest = hashlib.md5(payload).hexdigest()
    await store.make_bucket("b")
    await store.fput_object("b", "k", str(src), content_md5=digest)
    info = await store.stat_object("b", "k")
    assert (info.etag, info.size) == (digest, len(payload))
    assert calls["n"] == 0  # the hint retired the re-read
    # an un-hinted foreign object still derives (and then memoizes)
    (tmp_path / "src2.bin").write_bytes(b"foreign")
    await store.fput_object("b", "k2", str(tmp_path / "src2.bin"))
    info2 = await store.stat_object("b", "k2")
    assert info2.etag == hashlib.md5(b"foreign").hexdigest()
    assert calls["n"] == 1
    await store.stat_object("b", "k2")
    assert calls["n"] == 1  # memoized on the miss


# ---------------------------------------------------------------------------
# peer hardlink shared tier
# ---------------------------------------------------------------------------

PAYLOAD = b"H" * (192 << 10)


def _fill_src(tmp_path, name="media.mkv", data=PAYLOAD):
    src = tmp_path / "src"
    src.mkdir(exist_ok=True)
    (src / name).write_bytes(data)
    return str(src)


async def test_peer_fetch_hardlinks_colocated_fs_store(tmp_path):
    """A co-located FilesystemObjectStore materializes by inode link —
    zero bucket round-trip — and bills the shared_fetch hop's bytes."""
    store = FilesystemObjectStore(str(tmp_path / "store"))
    await store.make_bucket(STAGING_BUCKET)
    key = cache_key("http", "http://x/media.mkv", '"zc"')
    cache_a = ContentCache(str(tmp_path / "cache-a"))
    cache_b = ContentCache(str(tmp_path / "cache-b"))
    plane_a = FleetPlane(MemoryCoordStore(), "wa", store=store)
    plane_b = FleetPlane(MemoryCoordStore(), "wb", store=store)

    await cache_a.insert(key, _fill_src(tmp_path))
    assert await plane_a.publish_entry(key, cache_a)

    record = _Record()
    assert await plane_b.fetch_entry(key, cache_b, record=record)
    entry = await cache_b.lookup(key)
    assert entry is not None and entry.size == len(PAYLOAD)
    # the materialized file shares the store object's inode
    stored = store.local_object_path(
        STAGING_BUCKET, plane_b._shared_name(key, "media.mkv"))
    assert stored is not None
    local = os.path.join(cache_b.entry_path(key), "media.mkv")
    assert os.stat(local).st_ino == os.stat(stored).st_ino
    # bytes noted on the shared_fetch hop (seconds ride the lease bill)
    assert record.hops["shared_fetch"][0] == len(PAYLOAD)
    # the flight-recorder origin event reports the linked count
    kinds = {k: f for k, f in record.events}
    assert kinds.get("shared_origin", {}).get("linked") == 1
    # ... and serves byte-exact
    dest = str(tmp_path / "job")
    assert await cache_b.materialize(key, dest) == len(PAYLOAD)
    assert open(os.path.join(dest, "media.mkv"), "rb").read() == PAYLOAD


async def test_peer_fetch_falls_back_on_exdev(tmp_path, monkeypatch):
    """A link failure (EXDEV: cache volume on another device) degrades
    to the streamed fget_object copy — byte-exact, zero links."""
    store = FilesystemObjectStore(str(tmp_path / "store"))
    await store.make_bucket(STAGING_BUCKET)
    key = cache_key("http", "http://x/media.mkv", '"zc2"')
    cache_a = ContentCache(str(tmp_path / "cache-a"))
    cache_b = ContentCache(str(tmp_path / "cache-b"))
    plane_a = FleetPlane(MemoryCoordStore(), "wa", store=store)
    plane_b = FleetPlane(MemoryCoordStore(), "wb", store=store)
    await cache_a.insert(key, _fill_src(tmp_path))
    assert await plane_a.publish_entry(key, cache_a)

    real_link = os.link

    def exdev_link(src, dst, **kwargs):
        if ".fleet-cache" in src.replace(os.sep, "/"):
            raise OSError(errno.EXDEV, "cross-device link")
        return real_link(src, dst, **kwargs)

    monkeypatch.setattr(os, "link", exdev_link)
    record = _Record()
    assert await plane_b.fetch_entry(key, cache_b, record=record)
    entry = await cache_b.lookup(key)
    assert entry is not None and entry.size == len(PAYLOAD)
    kinds = {k: f for k, f in record.events}
    assert kinds.get("shared_origin", {}).get("linked") == 0
    dest = str(tmp_path / "job")
    assert await cache_b.materialize(key, dest) == len(PAYLOAD)
    assert open(os.path.join(dest, "media.mkv"), "rb").read() == PAYLOAD


async def test_peer_fetch_streams_from_remote_store(tmp_path):
    """A store without local_object_path (real S3) streams exactly as
    before the hardlink tier existed."""
    from downloader_tpu.store import InMemoryObjectStore

    store = InMemoryObjectStore()
    await store.make_bucket(STAGING_BUCKET)
    key = cache_key("http", "http://x/media.mkv", '"zc3"')
    cache_a = ContentCache(str(tmp_path / "cache-a"))
    cache_b = ContentCache(str(tmp_path / "cache-b"))
    plane_a = FleetPlane(MemoryCoordStore(), "wa", store=store)
    plane_b = FleetPlane(MemoryCoordStore(), "wb", store=store)
    await cache_a.insert(key, _fill_src(tmp_path))
    assert await plane_a.publish_entry(key, cache_a)
    assert await plane_b.fetch_entry(key, cache_b)
    dest = str(tmp_path / "job")
    assert await cache_b.materialize(key, dest) == len(PAYLOAD)
    assert open(os.path.join(dest, "media.mkv"), "rb").read() == PAYLOAD


# ---------------------------------------------------------------------------
# io_uring landing spike
# ---------------------------------------------------------------------------

def test_uring_probe_is_a_clean_bool():
    from downloader_tpu.utils import uring

    assert uring.available() in (True, False)
    assert uring.available() == uring.available()  # memoized


def test_uring_pwrite_matches_os_pwrite(tmp_path):
    from downloader_tpu.utils import uring

    if not uring.available():
        pytest.skip("io_uring unavailable (kernel/seccomp)")
    data = os.urandom(3 << 20)
    a = tmp_path / "uring.bin"
    b = tmp_path / "pwrite.bin"
    with uring.UringWriter() as writer:
        fd = os.open(a, os.O_CREAT | os.O_WRONLY)
        try:
            assert writer.pwrite(fd, data, 4096) == len(data)
            assert writer.pwrite(fd, b"head", 0) == 4
        finally:
            os.close(fd)
    fd = os.open(b, os.O_CREAT | os.O_WRONLY)
    try:
        os.pwrite(fd, data, 4096)
        os.pwrite(fd, b"head", 0)
    finally:
        os.close(fd)
    assert a.read_bytes() == b.read_bytes()


async def test_segmented_download_with_io_uring_knob(tmp_path,
                                                    monkeypatch):
    """download.io_uring lands segmented chunks through the ring (when
    the probe allows) and the output stays byte-identical."""
    from downloader_tpu.stages import download as dl_mod
    from downloader_tpu.utils import uring

    monkeypatch.setattr(dl_mod, "SEG_MIN_SIZE", 1 << 16)
    monkeypatch.setenv("HTTP_SEGMENTS", "4")
    monkeypatch.setenv("HTTP_NO_SPLICE", "1")  # force the chunk loop
    payload = bytes(range(256)) * 4096  # 1 MiB, position-dependent
    etag = '"seg-zc"'

    async def serve(request):
        rng = request.headers.get("Range")
        if rng:
            start_s, _, end_s = rng.removeprefix("bytes=").partition("-")
            start = int(start_s)
            end = (min(int(end_s), len(payload) - 1)
                   if end_s else len(payload) - 1)
            return web.Response(
                status=206, body=payload[start:end + 1],
                headers={"ETag": etag, "Content-Range":
                         f"bytes {start}-{end}/{len(payload)}"})
        return web.Response(body=payload, headers={"ETag": etag})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        mq = MemoryQueue(InMemoryBroker())
        await mq.connect()
        ctx = StageContext(
            config=ConfigNode({
                "instance": {"download_path": str(tmp_path / "dl")},
                "download": {"io_uring": True},
            }),
            emitter=EventEmitter(),
            logger=NullLogger(),
            telemetry=Telemetry(mq),
        )
        stage = await stage_factory(ctx)
        job = Job(media=schemas.Media(
            id="job-u", source=schemas.SourceType.Value("HTTP"),
            source_uri=f"{base}/media/file.mkv",
        ))
        await stage(job)
        out = tmp_path / "dl" / "job-u" / "file.mkv"
        assert out.read_bytes() == payload
        if uring.available():
            # the landed digest doubles as the integrity check that the
            # ring path wrote every byte where pwrite would have
            assert job.landed_digests[str(out)] == hashlib.md5(
                payload).hexdigest()
    finally:
        await runner.cleanup()
