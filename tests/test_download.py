"""Download-stage tests: protocol dispatch, http streaming, file gating,
bucket fan-in (reference /root/reference/lib/download.js)."""

import asyncio
import os

import pytest
from aiohttp import web

from helpers import start_http_server

from downloader_tpu import schemas
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.logging import NullLogger
from downloader_tpu.platform.telemetry import PROGRESS_QUEUE, Telemetry
from downloader_tpu.stages.base import Job, StageContext
from downloader_tpu.stages.download import parse_bucket_uri, stage_factory
from downloader_tpu.store import InMemoryObjectStore
from downloader_tpu.store import scrub
from downloader_tpu.utils import EventEmitter

pytestmark = pytest.mark.anyio


@pytest.fixture
def broker():
    return InMemoryBroker()


def make_config(tmp_path):
    return ConfigNode(
        {"instance": {"download_path": str(tmp_path / "downloads")}}
    )


async def make_stage(tmp_path, broker, bucket_client_factory=None):
    mq = MemoryQueue(broker)
    await mq.connect()
    ctx = StageContext(
        config=make_config(tmp_path),
        emitter=EventEmitter(),
        logger=NullLogger(),
        telemetry=Telemetry(mq),
        bucket_client_factory=bucket_client_factory,
    )
    return await stage_factory(ctx)


def make_job(source: str, uri: str, media_id: str = "job-1") -> Job:
    return Job(
        media=schemas.Media(
            id=media_id,
            source=schemas.SourceType.Value(source),
            source_uri=uri,
        )
    )


@pytest.fixture
async def http_server():
    payload = b"M" * (1 << 20)  # 1 MiB

    async def serve(request):
        if request.path.endswith("missing.mkv"):
            return web.Response(status=404)
        return web.Response(body=payload)

    runner, base = await start_http_server(serve, path="/media/{name}")
    yield base, payload
    await runner.cleanup()


async def test_http_download_streams_to_disk(tmp_path, broker, http_server):
    base, payload = http_server
    stage = await make_stage(tmp_path, broker)
    result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    expected_dir = str(tmp_path / "downloads" / "job-1")
    assert result == {"path": expected_dir}
    with open(os.path.join(expected_dir, "file.mkv"), "rb") as fh:
        assert fh.read() == payload


async def test_http_emits_progress_0_and_50(tmp_path, broker, http_server):
    base, _ = http_server
    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    events = [
        schemas.decode(schemas.TelemetryProgressEvent, raw)
        for raw in broker.published(PROGRESS_QUEUE)
    ]
    # (reference lib/download.js:255,272)
    assert [e.percent for e in events] == [0, 50]


async def test_http_error_status_raises(tmp_path, broker, http_server):
    base, _ = http_server
    stage = await make_stage(tmp_path, broker)
    with pytest.raises(Exception):
        await stage(make_job("HTTP", f"{base}/media/missing.mkv"))


async def test_http_honors_proxy_env(tmp_path, broker, http_server,
                                     monkeypatch):
    """The reference's request lib routes through HTTP_PROXY et al by
    default; the aiohttp sessions run trust_env=True for parity.  A
    dead proxy proves the env is consulted (the fetch fails instead of
    going direct)."""
    base, _ = http_server
    monkeypatch.setenv("http_proxy", "http://127.0.0.1:9")  # discard port
    stage = await make_stage(tmp_path, broker)
    with pytest.raises(Exception):
        await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    # and NO_PROXY punches through, standard env semantics
    monkeypatch.setenv("no_proxy", "127.0.0.1")
    stage = await make_stage(tmp_path, broker)
    result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert result == {"path": str(tmp_path / "downloads" / "job-1")}


async def test_file_urls_gated_by_env(tmp_path, broker, monkeypatch):
    src = tmp_path / "local.mkv"
    src.write_bytes(b"local-bytes")
    uri = src.as_uri()
    stage = await make_stage(tmp_path, broker)

    monkeypatch.delenv("ALLOW_FILE_URLS", raising=False)
    with pytest.raises(PermissionError):
        await stage(make_job("FILE", uri))

    monkeypatch.setenv("ALLOW_FILE_URLS", "true")
    result = await stage(make_job("FILE", uri))
    out = os.path.join(result["path"], "local.mkv")
    with open(out, "rb") as fh:
        assert fh.read() == b"local-bytes"


async def test_file_copy_runs_off_the_event_loop(tmp_path, broker,
                                                 monkeypatch):
    """Regression (graftlint blocking-call-in-async, PR 11 sweep): the
    file:// handler used to shutil.copyfile synchronously on the event
    loop — a multi-GB source stalled every other job's transfer for the
    whole copy.  The copy must run on a worker thread."""
    import shutil
    import threading

    src = tmp_path / "local.mkv"
    src.write_bytes(b"local-bytes")
    monkeypatch.setenv("ALLOW_FILE_URLS", "true")

    copy_threads = []
    real_copyfile = shutil.copyfile

    def spying_copyfile(a, b, **kwargs):
        copy_threads.append(threading.current_thread())
        return real_copyfile(a, b, **kwargs)

    monkeypatch.setattr(shutil, "copyfile", spying_copyfile)
    stage = await make_stage(tmp_path, broker)
    result = await stage(make_job("FILE", src.as_uri()))

    out = os.path.join(result["path"], "local.mkv")
    with open(out, "rb") as fh:
        assert fh.read() == b"local-bytes"
    assert copy_threads and all(
        t is not threading.main_thread() for t in copy_threads
    )


async def test_join_offloaded_joins_worker_before_cancel_propagates():
    """Regression (PR 11 review): a cancelled offloaded copy/touch must
    be JOINED before CancelledError propagates — the cancel settle
    removes the workdir immediately after, and an abandoned worker
    thread would race that rmtree (re-creating deleted directories)."""
    import threading
    import time as _time

    from downloader_tpu.stages.download import _join_offloaded

    finished = threading.Event()

    def slow_worker():
        _time.sleep(0.3)
        finished.set()

    task = asyncio.create_task(_join_offloaded(slow_worker))
    await asyncio.sleep(0.05)   # worker is mid-flight
    task.cancel()
    with pytest.raises(asyncio.CancelledError):
        await task
    # by the time the cancel unwound, the thread had fully finished —
    # nothing can race the settle path's rmtree
    assert finished.is_set()


async def test_bucket_download_strips_subfolder(tmp_path, broker):
    remote = InMemoryObjectStore()
    await remote.make_bucket("media")
    await remote.put_object("media", "show/ep1.mkv", b"ep1")
    await remote.put_object("media", "show/sub/ep2.mkv", b"ep2")
    await remote.put_object("media", "other/ep3.mkv", b"nope")

    captured = {}

    def factory(endpoint, access_key, secret_key, ssl=True):
        captured.update(
            endpoint=endpoint, access_key=access_key, secret_key=secret_key
        )
        return remote

    stage = await make_stage(tmp_path, broker, bucket_client_factory=factory)
    uri = "bucket://minio.example:9000,media,AKIA,SECRET,show"
    result = await stage(make_job("BUCKET", uri))

    assert captured == {
        "endpoint": "minio.example:9000",
        "access_key": "AKIA",
        "secret_key": "SECRET",
    }
    root = result["path"]
    with open(os.path.join(root, "ep1.mkv"), "rb") as fh:
        assert fh.read() == b"ep1"
    with open(os.path.join(root, "sub", "ep2.mkv"), "rb") as fh:
        assert fh.read() == b"ep2"
    assert not os.path.exists(os.path.join(root, "ep3.mkv"))


async def test_bucket_download_rejects_traversal_keys(tmp_path, broker):
    """Object keys are untrusted remote data; '..' segments must not
    escape the download directory."""
    remote = InMemoryObjectStore()
    await remote.make_bucket("media")
    await remote.put_object("media", "show/../../evil.mkv", b"evil")
    await remote.put_object("media", "show/ok.mkv", b"ok")

    stage = await make_stage(
        tmp_path, broker, bucket_client_factory=lambda *a, **k: remote
    )
    uri = "bucket://minio.example:9000,media,AKIA,SECRET,show"
    result = await stage(make_job("BUCKET", uri, media_id="trav"))

    root = result["path"]
    with open(os.path.join(root, "ok.mkv"), "rb") as fh:
        assert fh.read() == b"ok"
    # nothing escaped above the per-job download dir
    assert not os.path.exists(str(tmp_path / "evil.mkv"))
    assert not os.path.exists(str(tmp_path / "downloads" / "evil.mkv"))
    # the traversal key was either skipped or flattened inside the job dir
    for dirpath, _dirs, files in os.walk(str(tmp_path)):
        for f in files:
            if f == "evil.mkv":
                assert dirpath.startswith(root)


ETAG = '"v1-abc"'


@pytest.fixture
async def range_server():
    """Fixture server with byte-range + If-Range support and request log."""
    payload = bytes(range(256)) * 4096  # 1 MiB, position-dependent bytes
    requests = []

    async def serve(request):
        rng = request.headers.get("Range")
        if request.method == "GET":  # HEADs (output validation) are noise
            requests.append((rng, request.headers.get("If-Range")))
        if rng:
            # If-Range miss -> entity changed -> full 200 (RFC 7233 §3.2)
            if request.headers.get("If-Range") not in (None, ETAG):
                return web.Response(body=payload, headers={"ETag": ETAG})
            start_s, _, end_s = rng.removeprefix("bytes=").partition("-")
            start = int(start_s)
            end = min(int(end_s), len(payload) - 1) if end_s else len(payload) - 1
            if start >= len(payload):
                return web.Response(
                    status=416,
                    headers={"Content-Range": f"bytes */{len(payload)}"},
                )
            return web.Response(
                status=206,
                body=payload[start:end + 1],
                headers={
                    "ETag": ETAG,
                    "Content-Range": f"bytes {start}-{end}/{len(payload)}",
                },
            )
        return web.Response(body=payload, headers={"ETag": ETAG})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    yield base, payload, requests
    await runner.cleanup()


def seed_partial(target_dir, data: bytes, validator: str = ETAG):
    target_dir.mkdir(parents=True, exist_ok=True)
    (target_dir / "file.mkv.partial").write_bytes(data)
    if validator:
        (target_dir / "file.mkv.partial.meta").write_text(validator)


async def test_http_resumes_from_partial(tmp_path, broker, range_server):
    """A leftover .partial file (with its validator) resumes with a
    Range+If-Range request instead of restarting from zero (the reference
    restarts, SURVEY.md §5)."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    offset = 300_000
    seed_partial(target_dir, payload[:offset])

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    assert requests == [(f"bytes={offset}-", ETAG)]
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload
    assert not (target_dir / "file.mkv.partial").exists()
    assert not (target_dir / "file.mkv.partial.meta").exists()


@pytest.fixture
def splice_probe(monkeypatch):
    """Count _splice_body entries AND worker slices, so tests can prove
    the fast path ran even when aiohttp had already buffered the whole
    body (the head-drain then lands it without a worker slice)."""
    import downloader_tpu.stages.download as dl

    calls = {"slices": 0, "bodies": 0}
    orig_slice = dl._splice_slice_blocking

    def counting_slice(*args, **kwargs):
        calls["slices"] += 1
        return orig_slice(*args, **kwargs)

    orig_spliceable = dl._spliceable

    def counting_spliceable(resp):
        ok = orig_spliceable(resp)
        if ok:
            calls["bodies"] += 1
        return ok

    monkeypatch.setattr(dl, "_splice_slice_blocking", counting_slice)
    monkeypatch.setattr(dl, "_spliceable", counting_spliceable)
    return calls


async def _run_splice_ab(tmp_path, broker, base, payload, splice_probe,
                         monkeypatch, min_bodies):
    """Shared A/B body: fast path engaged + byte-identical to the
    HTTP_NO_SPLICE streaming fallback."""
    import downloader_tpu.stages.download as dl

    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    spliced = (tmp_path / "downloads" / "job-1" / "file.mkv").read_bytes()
    assert spliced == payload
    if dl.SPLICE_OK:
        assert splice_probe["bodies"] >= min_bodies

    monkeypatch.setenv("HTTP_NO_SPLICE", "1")
    splice_probe["slices"] = splice_probe["bodies"] = 0
    stage2 = await make_stage(tmp_path, broker)
    await stage2(make_job("HTTP", f"{base}/media/file.mkv",
                          media_id="job-2"))
    plain = (tmp_path / "downloads" / "job-2" / "file.mkv").read_bytes()
    assert plain == payload
    assert splice_probe["slices"] == splice_probe["bodies"] == 0


async def test_http_splice_path_engaged_and_byte_identical(
        tmp_path, broker, range_server, splice_probe, monkeypatch):
    """The zero-copy splice landing (r5) actually runs for plain HTTP
    with a known length, and produces byte-identical output to the
    streaming fallback (HTTP_NO_SPLICE=1)."""
    base, payload, _requests = range_server
    await _run_splice_ab(tmp_path, broker, base, payload, splice_probe,
                         monkeypatch, min_bodies=1)


async def test_http_cancel_mid_splice_leaves_no_leaks_and_resumes(
        tmp_path, broker):
    """Cancelling (even twice, racing the cleanup join) mid-splice must
    leak no fds, preserve the .partial for resume, and a retry must
    finish byte-exact (the r5 splice path's cancellation contract)."""
    import downloader_tpu.stages.download as dl

    payload = os.urandom(6 << 20)

    async def serve(req):
        resp = web.StreamResponse(headers={
            "ETag": '"x"', "Content-Length": str(len(payload))})
        await resp.prepare(req)
        for off in range(0, len(payload), 1 << 20):
            await resp.write(payload[off:off + (1 << 20)])
            await asyncio.sleep(0.03)  # drip: cancels land mid-body
        return resp

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    stage = await make_stage(tmp_path, broker)
    fds_before = len(os.listdir("/proc/self/fd"))
    try:
        for _ in range(3):
            task = asyncio.create_task(
                stage(make_job("HTTP", f"{base}/media/file.mkv")))
            await asyncio.sleep(0.08)
            task.cancel()
            await asyncio.sleep(0.001)
            task.cancel()  # double-cancel: the deferred-cleanup path
            with pytest.raises(asyncio.CancelledError):
                await task
        await asyncio.sleep(0.2)
        leaked = len(os.listdir("/proc/self/fd")) - fds_before
        assert leaked <= 4, f"fd leak after cancel storm: {leaked}"

        # the partial survived for resume, and the retry completes
        target_dir = tmp_path / "downloads" / "job-1"
        if dl.SPLICE_OK:
            assert (target_dir / "file.mkv.partial").exists()
        await stage(make_job("HTTP", f"{base}/media/file.mkv"))
        assert (target_dir / "file.mkv").read_bytes() == payload
    finally:
        await runner.cleanup()


async def test_http_resume_with_complete_partial(tmp_path, broker, range_server):
    """A partial that already holds the full entity (416 + matching
    validator) is promoted without re-downloading."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    seed_partial(target_dir, payload)

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    assert requests == [(f"bytes={len(payload)}-", ETAG)]
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload


async def test_http_skips_completed_download(tmp_path, broker, range_server):
    """A fully-downloaded file from a prior attempt short-circuits the
    fetch entirely."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    (target_dir / "file.mkv").write_bytes(payload)

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert requests == []


async def test_http_restart_when_entity_changed(tmp_path, broker, range_server):
    """If the origin's entity changed since the partial was written
    (If-Range miss -> 200), stale bytes are discarded, not stitched."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    seed_partial(target_dir, b"OLD-VERSION-BYTES", validator='"v0-old"')

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    assert requests == [("bytes=17-", '"v0-old"')]
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload  # no v0 bytes survived


async def test_http_no_validator_means_clean_restart(tmp_path, broker, range_server):
    """A partial with no recorded validator cannot be safely resumed;
    the download restarts from zero with no Range header."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    seed_partial(target_dir, payload[:1000], validator="")

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    assert requests == [(None, None)]
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload


async def test_http_capped_206_resumes_in_rounds(tmp_path, broker):
    """A server that caps open-ended ranges (returns fewer bytes than the
    remainder) must not yield a silently-truncated file: the stage keeps
    requesting the next range until the entity is complete."""
    payload = bytes(range(256)) * 4096  # 1 MiB
    cap = 200_000
    requests = []

    async def serve(request):
        rng = request.headers.get("Range")
        requests.append(rng)
        if rng:
            start = int(rng.removeprefix("bytes=").split("-")[0])
            end = min(start + cap, len(payload)) - 1
            return web.Response(
                status=206,
                body=payload[start : end + 1],
                headers={
                    "ETag": ETAG,
                    "Content-Range": f"bytes {start}-{end}/{len(payload)}",
                },
            )
        return web.Response(body=payload, headers={"ETag": ETAG})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        stage = await make_stage(tmp_path, broker)
        target_dir = tmp_path / "downloads" / "job-1"
        offset = 100_000
        seed_partial(target_dir, payload[:offset])

        await stage(make_job("HTTP", f"{base}/media/file.mkv"))

        # 100k seed + ceil(948576/200000) = 5 range rounds
        assert requests == [
            f"bytes={o}-" for o in range(offset, len(payload), cap)
        ]
        with open(target_dir / "file.mkv", "rb") as fh:
            assert fh.read() == payload
    finally:
        await runner.cleanup()


async def test_http_weak_etag_never_recorded_as_validator(tmp_path, broker):
    """A weak ETag (W/"...") must not become an If-Range validator
    (RFC 7232 §3.2: If-Range needs a strong validator) — with no
    Last-Modified fallback, no .meta is written at all."""

    async def serve(request):
        return web.Response(body=b"x" * 2048, headers={"ETag": 'W/"weak-1"'})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        stage = await make_stage(tmp_path, broker)
        result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))
        target = os.path.join(result["path"], "file.mkv")
        assert os.path.getsize(target) == 2048
        assert not os.path.exists(target + ".partial.meta")
    finally:
        await runner.cleanup()


def test_weak_etag_rejected_unit(tmp_path):
    """Unit-level check of the validator policy without a live download."""
    from downloader_tpu.stages.download import choose_validator

    lm = "Mon, 01 Jan 2024 00:00:00 GMT"
    later = "Mon, 01 Jan 2024 00:02:05 GMT"
    barely = "Mon, 01 Jan 2024 00:00:05 GMT"

    assert choose_validator({"ETag": 'W/"weak"'}) is None
    # a weak ETag means the origin admits byte-level ambiguity: no resume
    # even with a plausible Last-Modified (RFC 7232 §2.2.2)
    assert choose_validator(
        {"ETag": 'W/"weak"', "Last-Modified": lm, "Date": later}
    ) is None
    assert choose_validator({"ETag": '"strong"'}) == '"strong"'
    assert choose_validator({}) is None
    # Last-Modified counts as strong only when >=60s older than Date
    # (RFC 7232 §2.2.2: outside the clock-skew/regeneration window)
    assert choose_validator({"Last-Modified": lm, "Date": later}) == lm
    assert choose_validator({"Last-Modified": lm, "Date": barely}) is None
    assert choose_validator({"Last-Modified": lm, "Date": lm}) is None
    assert choose_validator({"Last-Modified": lm}) is None  # no Date header


async def test_http_truncated_preexisting_output_redownloads(tmp_path, broker, range_server):
    """A pre-existing but truncated final file (e.g. left by a non-atomic
    writer) fails the HEAD size check and is re-downloaded instead of
    being treated as a completion marker."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    (target_dir / "file.mkv").write_bytes(payload[:1000])  # truncated

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload


async def test_http_intact_preexisting_output_skips(tmp_path, broker, range_server):
    """A pre-existing final file that matches the origin's Content-Length
    is trusted — only a HEAD goes over the wire."""
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    (target_dir / "file.mkv").write_bytes(payload)

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert requests == []  # fixture only logs GETs; no GET happened


async def test_http_forced_gzip_body_is_decoded(tmp_path, broker):
    """A server that sends Content-Encoding: gzip despite
    'Accept-Encoding: identity' must not get raw gzip bytes staged as
    media — the stage decodes them."""
    import gzip as gzip_mod

    payload = b"media-bytes-" * 1000

    async def serve(request):
        assert request.headers.get("Accept-Encoding") == "identity"
        body = gzip_mod.compress(payload)
        resp = web.Response(
            body=body, headers={"Content-Encoding": "gzip", "ETag": ETAG}
        )
        # aiohttp would otherwise re-encode; mark the body pre-compressed
        resp._compressed_body = body
        return resp

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        stage = await make_stage(tmp_path, broker)
        result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))
        with open(os.path.join(result["path"], "file.mkv"), "rb") as fh:
            assert fh.read() == payload
    finally:
        await runner.cleanup()


async def test_http_restarts_when_server_lacks_ranges(tmp_path, broker, http_server):
    """Against a server without range support (plain 200), a stale partial
    is discarded and the download restarts cleanly."""
    base, payload = http_server
    stage = await make_stage(tmp_path, broker)

    target_dir = tmp_path / "downloads" / "job-1"
    seed_partial(target_dir, b"stale-junk")

    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    with open(target_dir / "file.mkv", "rb") as fh:
        assert fh.read() == payload


def test_parse_bucket_uri():
    parsed = parse_bucket_uri("bucket://e:9000,b,ak,sk,folder/")
    assert parsed == {
        "endpoint": "e:9000",
        "bucket": "b",
        "access_key": "ak",
        "secret_key": "sk",
        "sub_folder": "folder/",
    }
    with pytest.raises(ValueError):
        parse_bucket_uri("bucket://missing,parts")


async def test_unsupported_protocol_raises(tmp_path, broker):
    stage = await make_stage(tmp_path, broker)
    job = make_job("HTTP", "http://x/file.mkv")
    job.media.source = 17  # not a known SourceType
    with pytest.raises(ValueError):
        await stage(job)


# -- segmented (parallel ranged) HTTP downloads -------------------------


@pytest.fixture
def small_segments(monkeypatch):
    """Shrink the segmentation threshold so the 1 MiB fixture qualifies,
    and enable 4 segments via the env knob."""
    from downloader_tpu.stages import download as download_module

    monkeypatch.setattr(download_module, "SEG_MIN_SIZE", 1 << 16)
    monkeypatch.setenv("HTTP_SEGMENTS", "4")


async def test_http_segmented_download(tmp_path, broker, range_server,
                                       small_segments):
    base, payload, requests = range_server
    stage = await make_stage(tmp_path, broker)
    result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    target = tmp_path / "downloads" / "job-1" / "file.mkv"
    assert result == {"path": str(tmp_path / "downloads" / "job-1")}
    assert target.read_bytes() == payload
    # probe + one bounded range per segment, each carrying If-Range
    assert requests[0] == ("bytes=0-0", None)
    span = -(-len(payload) // 4)
    expected = {
        (f"bytes={lo}-{min(lo + span, len(payload)) - 1}", ETAG)
        for lo in range(0, len(payload), span)
    }
    assert set(requests[1:]) == expected
    # no stray working files besides the durable landing sidecar
    assert sorted(p.name for p in target.parent.iterdir()) == [
        scrub.LANDED_SIDECAR, "file.mkv"]
    assert "file.mkv" in scrub.read_landed(target.parent)


async def test_http_segmented_splice_engaged_and_byte_identical(
        tmp_path, broker, range_server, small_segments, splice_probe,
        monkeypatch):
    """The segmented path lands ranges via positioned kernel splice
    (r5): the fast path actually runs for every segment, and output
    matches the streaming fallback byte-for-byte."""
    base, payload, _requests = range_server
    await _run_splice_ab(tmp_path, broker, base, payload, splice_probe,
                         monkeypatch, min_bodies=4)


async def test_http_segmented_resume_skips_done_bytes(
        tmp_path, broker, range_server, small_segments):
    """A crashed segmented download resumes each segment from its
    checkpointed position instead of refetching."""
    import json as json_mod

    base, payload, requests = range_server
    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    total = len(payload)
    span = -(-total // 4)
    segments = [[lo, lo, min(lo + span, total)]
                for lo in range(0, total, span)]
    # first two segments already complete, third half done
    segments[0][1] = segments[0][2]
    segments[1][1] = segments[1][2]
    segments[2][1] = segments[2][0] + span // 2
    seg_partial = target_dir / "file.mkv.partial-seg"
    body = bytearray(total)
    for start, pos, _end in segments:
        body[start:pos] = payload[start:pos]
    seg_partial.write_bytes(bytes(body))
    (target_dir / "file.mkv.partial-seg.state").write_text(json_mod.dumps({
        "validator": ETAG, "total": total, "segments": segments,
    }))

    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    assert (target_dir / "file.mkv").read_bytes() == payload
    ranges = [r for r, _ in requests[1:]]
    # completed segments were not refetched
    assert f"bytes={segments[0][0]}-{segments[0][2] - 1}" not in ranges
    assert f"bytes={segments[2][1]}-{segments[2][2] - 1}" in ranges


async def test_http_segmented_stale_state_restarts_clean(
        tmp_path, broker, range_server, small_segments):
    """A state file from a different entity (validator mismatch) is
    ignored: all segments refetch from their starts."""
    import json as json_mod

    base, payload, _requests = range_server
    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    total = len(payload)
    (target_dir / "file.mkv.partial-seg").write_bytes(b"\0" * total)
    (target_dir / "file.mkv.partial-seg.state").write_text(json_mod.dumps({
        "validator": '"old-etag"', "total": total,
        "segments": [[0, total, total]],
    }))

    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert (target_dir / "file.mkv").read_bytes() == payload


async def test_http_segmented_orphan_state_without_data_refetches(
        tmp_path, broker, range_server, small_segments):
    """A checkpoint whose data file is missing (crash between discards,
    operator freed disk) must NOT be honored — 'resuming' over a fresh
    zero-filled file would promote zero runs as media bytes."""
    import json as json_mod

    base, payload, _requests = range_server
    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    total = len(payload)
    # state claims everything is done, but there is NO .partial-seg file
    (target_dir / "file.mkv.partial-seg.state").write_text(json_mod.dumps({
        "validator": ETAG, "total": total,
        "segments": [[0, total, total]],
    }))

    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert (target_dir / "file.mkv").read_bytes() == payload


async def test_http_segmented_cancel_midflight_then_resume(
        tmp_path, broker, range_server, small_segments):
    """Cancelling a segmented download mid-transfer must tear down
    cleanly (checkpoint written by the drained writer thread, fd closed,
    no torn tmp files) and a later attempt must RESUME from the
    checkpoint rather than refetching from zero."""
    import asyncio
    import json as json_mod

    from aiohttp import web

    from tests.helpers import start_http_server

    _base, payload, fast_requests = range_server
    started = asyncio.Event()
    stop = asyncio.Event()  # lets runner.cleanup() finish promptly

    async def trickle(request):
        rng = request.headers.get("Range")
        if rng == "bytes=0-0":
            return web.Response(
                status=206, body=b"\x00",
                headers={"ETag": ETAG,
                         "Content-Range": f"bytes 0-0/{len(payload)}"},
            )
        start_s, _, _end_s = rng.removeprefix("bytes=").partition("-")
        start = int(start_s)
        resp = web.StreamResponse(
            status=206,
            headers={"ETag": ETAG,
                     "Content-Range":
                         f"bytes {start}-{len(payload) - 1}/{len(payload)}"},
        )
        await resp.prepare(request)
        # trickle a little real data, then stall until cancelled
        await resp.write(payload[start:start + 2048])
        started.set()
        try:
            await asyncio.wait_for(stop.wait(), 60)
        except TimeoutError:
            pass
        return resp

    runner, slow_base = await start_http_server(trickle,
                                                path="/media/file.mkv")
    stage = await make_stage(tmp_path, broker)
    try:
        task = asyncio.create_task(
            stage(make_job("HTTP", f"{slow_base}/media/file.mkv")))
        async with asyncio.timeout(30):
            await started.wait()
            await asyncio.sleep(0.1)  # let some bytes land + a flush
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
    finally:
        stop.set()
        await runner.cleanup()

    target_dir = tmp_path / "downloads" / "job-1"
    state_path = target_dir / "file.mkv.partial-seg.state"
    # the teardown checkpoint is present and VALID json (the dedicated
    # writer thread was drained, not killed mid-write)
    state = json_mod.loads(state_path.read_text())
    assert state["validator"] == ETAG and state["total"] == len(payload)
    assert (target_dir / "file.mkv.partial-seg").stat().st_size == len(payload)
    resumed = sum(pos - start for start, pos, _end in state["segments"])
    assert resumed > 0  # some progress was checkpointed

    # second attempt against the normal fixture server resumes
    result = await stage(make_job("HTTP",
                                  f"{_base}/media/file.mkv"))
    assert result == {"path": str(target_dir)}
    assert (target_dir / "file.mkv").read_bytes() == payload
    # at least one segment range did NOT start from its segment origin
    # (proof bytes were credited from the cancelled attempt)
    span = -(-len(payload) // 4)
    origins = {f"bytes={lo}-{min(lo + span, len(payload)) - 1}"
               for lo in range(0, len(payload), span)}
    resumed_ranges = [r for r, _ in fast_requests
                      if r and r != "bytes=0-0" and r not in origins]
    assert resumed_ranges, "no segment resumed from a checkpointed offset"


async def test_http_segmented_falls_back_without_ranges(
        tmp_path, broker, http_server, small_segments):
    """A server with no byte-range support gets the sequential path."""
    base, payload = http_server
    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    target = tmp_path / "downloads" / "job-1" / "file.mkv"
    assert target.read_bytes() == payload


async def test_http_segmented_entity_change_midflight(
        tmp_path, broker, small_segments):
    """The origin swaps the entity between the probe and the segment
    requests: every If-Range misses (200), the attempt aborts, and the
    sequential restart stages the NEW entity consistently."""
    old = bytes(range(256)) * 1024
    new = bytes(reversed(range(256))) * 1024
    state = {"served_probe": False}

    async def serve(request):
        rng = request.headers.get("Range")
        if rng == "bytes=0-0" and not state["served_probe"]:
            state["served_probe"] = True
            return web.Response(
                status=206, body=old[:1],
                headers={"ETag": '"gen-1"',
                         "Content-Range": f"bytes 0-0/{len(old)}"})
        # generation 2: any conditional range misses
        if rng and request.headers.get("If-Range") == '"gen-2"':
            start = int(rng.removeprefix("bytes=").split("-")[0])
            return web.Response(
                status=206, body=new[start:],
                headers={"ETag": '"gen-2"',
                         "Content-Range":
                         f"bytes {start}-{len(new)-1}/{len(new)}"})
        return web.Response(body=new, headers={"ETag": '"gen-2"'})

    runner, base = await start_http_server(serve, path="/media/file.mkv")
    try:
        stage = await make_stage(tmp_path, broker)
        await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    finally:
        await runner.cleanup()
    target = tmp_path / "downloads" / "job-1" / "file.mkv"
    assert target.read_bytes() == new
    assert sorted(p.name for p in target.parent.iterdir()) == [
        scrub.LANDED_SIDECAR, "file.mkv"]


async def test_http_segments_config_validation(tmp_path, broker,
                                               monkeypatch):
    monkeypatch.setenv("HTTP_SEGMENTS", "nope")
    with pytest.raises(ValueError, match="http_segments"):
        await make_stage(tmp_path, broker)
    monkeypatch.setenv("HTTP_SEGMENTS", "0")
    with pytest.raises(ValueError, match="http_segments"):
        await make_stage(tmp_path, broker)


# -- disk-space preflight ----------------------------------------------


async def test_http_insufficient_disk_fails_fast(tmp_path, broker,
                                                 http_server, monkeypatch):
    """A volume that can't hold the advertised Content-Length errors
    before streaming, not at ENOSPC mid-write."""
    import collections
    import shutil

    base, _payload = http_server
    fake = collections.namedtuple("usage", "total used free")(100, 90, 10)
    monkeypatch.setattr(shutil, "disk_usage", lambda _p: fake)
    stage = await make_stage(tmp_path, broker)
    with pytest.raises(OSError, match="insufficient disk space"):
        await stage(make_job("HTTP", f"{base}/media/file.mkv"))


async def test_torrent_insufficient_disk_fails_fast(tmp_path, monkeypatch):
    import collections
    import shutil

    from downloader_tpu.torrent import Seeder, TorrentClient, make_metainfo
    from downloader_tpu.torrent.tracker import Peer

    src = tmp_path / "seed" / "payload"
    src.mkdir(parents=True)
    (src / "big.mkv").write_bytes(os.urandom(1 << 20))
    meta = make_metainfo(str(src), piece_length=1 << 18)
    seeder = Seeder(meta, str(tmp_path / "seed"))
    port = await seeder.start()
    torrent = tmp_path / "t.torrent"
    torrent.write_bytes(meta.to_torrent_bytes())

    fake = collections.namedtuple("usage", "total used free")(100, 90, 10)
    monkeypatch.setattr(shutil, "disk_usage", lambda _p: fake)
    try:
        with pytest.raises(OSError, match="insufficient disk space"):
            await TorrentClient().download(
                str(torrent), str(tmp_path / "dl"),
                peers=[Peer("127.0.0.1", port)], listen=False,
            )
    finally:
        await seeder.stop()


async def test_segmented_resume_credits_done_bytes_in_preflight(
        tmp_path, broker, range_server, small_segments, monkeypatch):
    """An 80%-done segmented download on a nearly-full volume must still
    resume: only the REMAINING bytes count against free space."""
    import collections
    import json as json_mod
    import shutil

    base, payload, _requests = range_server
    target_dir = tmp_path / "downloads" / "job-1"
    target_dir.mkdir(parents=True)
    total = len(payload)
    done = int(total * 0.8)
    segments = [[0, done, total]]
    body = bytearray(total)
    body[:done] = payload[:done]
    (target_dir / "file.mkv.partial-seg").write_bytes(bytes(body))
    (target_dir / "file.mkv.partial-seg.state").write_text(json_mod.dumps({
        "validator": ETAG, "total": total, "segments": segments,
    }))

    # free space holds the remainder but NOT the whole entity
    fake = collections.namedtuple("usage", "total used free")(
        total * 2, total, total - done + 4096)
    monkeypatch.setattr(shutil, "disk_usage", lambda _p: fake)
    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))
    assert (target_dir / "file.mkv").read_bytes() == payload


def test_allocated_bytes_sees_through_sparse_files(tmp_path):
    """Sparse preallocation must not count as resume credit."""
    from downloader_tpu.utils.disk import allocated_bytes

    sparse = tmp_path / "sparse.bin"
    with open(sparse, "wb") as fh:
        fh.truncate(1 << 20)
    dense = tmp_path / "dense.bin"
    dense.write_bytes(b"x" * (1 << 20))
    if os.stat(sparse).st_blocks * 512 >= (1 << 20):
        # the filesystem materialized the hole (gVisor/overlayfs hosts
        # back truncate with real blocks): there IS no sparseness here
        # for allocated_bytes to see through — the production concern
        # (st_blocks < st_size) cannot occur on this volume at all
        pytest.skip("filesystem does not create sparse files")
    assert allocated_bytes(str(sparse)) < (1 << 16)
    assert allocated_bytes(str(dense)) >= (1 << 20) - 4096
    assert allocated_bytes(str(tmp_path / "missing")) == 0
