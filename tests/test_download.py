"""Download-stage tests: protocol dispatch, http streaming, file gating,
bucket fan-in (reference /root/reference/lib/download.js)."""

import os

import pytest
from aiohttp import web

from downloader_tpu import schemas
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.logging import NullLogger
from downloader_tpu.platform.telemetry import PROGRESS_QUEUE, Telemetry
from downloader_tpu.stages.base import Job, StageContext
from downloader_tpu.stages.download import parse_bucket_uri, stage_factory
from downloader_tpu.store import InMemoryObjectStore
from downloader_tpu.utils import EventEmitter

pytestmark = pytest.mark.anyio


@pytest.fixture
def broker():
    return InMemoryBroker()


def make_config(tmp_path):
    return ConfigNode(
        {"instance": {"download_path": str(tmp_path / "downloads")}}
    )


async def make_stage(tmp_path, broker, bucket_client_factory=None):
    mq = MemoryQueue(broker)
    await mq.connect()
    ctx = StageContext(
        config=make_config(tmp_path),
        emitter=EventEmitter(),
        logger=NullLogger(),
        telemetry=Telemetry(mq),
        bucket_client_factory=bucket_client_factory,
    )
    return await stage_factory(ctx)


def make_job(source: str, uri: str, media_id: str = "job-1") -> Job:
    return Job(
        media=schemas.Media(
            id=media_id,
            source=schemas.SourceType.Value(source),
            source_uri=uri,
        )
    )


@pytest.fixture
async def http_server():
    app = web.Application()
    payload = b"M" * (1 << 20)  # 1 MiB

    async def serve(request):
        return web.Response(body=payload)

    async def missing(request):
        return web.Response(status=404)

    app.router.add_get("/media/file.mkv", serve)
    app.router.add_get("/media/missing.mkv", missing)

    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    yield f"http://127.0.0.1:{port}", payload
    await runner.cleanup()


async def test_http_download_streams_to_disk(tmp_path, broker, http_server):
    base, payload = http_server
    stage = await make_stage(tmp_path, broker)
    result = await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    expected_dir = str(tmp_path / "downloads" / "job-1")
    assert result == {"path": expected_dir}
    with open(os.path.join(expected_dir, "file.mkv"), "rb") as fh:
        assert fh.read() == payload


async def test_http_emits_progress_0_and_50(tmp_path, broker, http_server):
    base, _ = http_server
    stage = await make_stage(tmp_path, broker)
    await stage(make_job("HTTP", f"{base}/media/file.mkv"))

    events = [
        schemas.decode(schemas.TelemetryProgressEvent, raw)
        for raw in broker.published(PROGRESS_QUEUE)
    ]
    # (reference lib/download.js:255,272)
    assert [e.percent for e in events] == [0, 50]


async def test_http_error_status_raises(tmp_path, broker, http_server):
    base, _ = http_server
    stage = await make_stage(tmp_path, broker)
    with pytest.raises(Exception):
        await stage(make_job("HTTP", f"{base}/media/missing.mkv"))


async def test_file_urls_gated_by_env(tmp_path, broker, monkeypatch):
    src = tmp_path / "local.mkv"
    src.write_bytes(b"local-bytes")
    uri = src.as_uri()
    stage = await make_stage(tmp_path, broker)

    monkeypatch.delenv("ALLOW_FILE_URLS", raising=False)
    with pytest.raises(PermissionError):
        await stage(make_job("FILE", uri))

    monkeypatch.setenv("ALLOW_FILE_URLS", "true")
    result = await stage(make_job("FILE", uri))
    out = os.path.join(result["path"], "local.mkv")
    with open(out, "rb") as fh:
        assert fh.read() == b"local-bytes"


async def test_bucket_download_strips_subfolder(tmp_path, broker):
    remote = InMemoryObjectStore()
    await remote.make_bucket("media")
    await remote.put_object("media", "show/ep1.mkv", b"ep1")
    await remote.put_object("media", "show/sub/ep2.mkv", b"ep2")
    await remote.put_object("media", "other/ep3.mkv", b"nope")

    captured = {}

    def factory(endpoint, access_key, secret_key, ssl=True):
        captured.update(
            endpoint=endpoint, access_key=access_key, secret_key=secret_key
        )
        return remote

    stage = await make_stage(tmp_path, broker, bucket_client_factory=factory)
    uri = "bucket://minio.example:9000,media,AKIA,SECRET,show"
    result = await stage(make_job("BUCKET", uri))

    assert captured == {
        "endpoint": "minio.example:9000",
        "access_key": "AKIA",
        "secret_key": "SECRET",
    }
    root = result["path"]
    with open(os.path.join(root, "ep1.mkv"), "rb") as fh:
        assert fh.read() == b"ep1"
    with open(os.path.join(root, "sub", "ep2.mkv"), "rb") as fh:
        assert fh.read() == b"ep2"
    assert not os.path.exists(os.path.join(root, "ep3.mkv"))


async def test_bucket_download_rejects_traversal_keys(tmp_path, broker):
    """Object keys are untrusted remote data; '..' segments must not
    escape the download directory."""
    remote = InMemoryObjectStore()
    await remote.make_bucket("media")
    await remote.put_object("media", "show/../../evil.mkv", b"evil")
    await remote.put_object("media", "show/ok.mkv", b"ok")

    stage = await make_stage(
        tmp_path, broker, bucket_client_factory=lambda *a, **k: remote
    )
    uri = "bucket://minio.example:9000,media,AKIA,SECRET,show"
    result = await stage(make_job("BUCKET", uri, media_id="trav"))

    root = result["path"]
    with open(os.path.join(root, "ok.mkv"), "rb") as fh:
        assert fh.read() == b"ok"
    # nothing escaped above the per-job download dir
    assert not os.path.exists(str(tmp_path / "evil.mkv"))
    assert not os.path.exists(str(tmp_path / "downloads" / "evil.mkv"))
    # the traversal key was either skipped or flattened inside the job dir
    for dirpath, _dirs, files in os.walk(str(tmp_path)):
        for f in files:
            if f == "evil.mkv":
                assert dirpath.startswith(root)


def test_parse_bucket_uri():
    parsed = parse_bucket_uri("bucket://e:9000,b,ak,sk,folder/")
    assert parsed == {
        "endpoint": "e:9000",
        "bucket": "b",
        "access_key": "ak",
        "secret_key": "sk",
        "sub_folder": "folder/",
    }
    with pytest.raises(ValueError):
        parse_bucket_uri("bucket://missing,parts")


async def test_unsupported_protocol_raises(tmp_path, broker):
    stage = await make_stage(tmp_path, broker)
    job = make_job("HTTP", "http://x/file.mkv")
    job.media.source = 17  # not a known SourceType
    with pytest.raises(ValueError):
        await stage(job)
