"""uTP (BEP 29) transport: codec, reliability under loss/reorder, stream
semantics, and the full torrent stack (MSE included) running over it.

The reference's webtorrent dials peers over TCP *and* uTP
(/root/reference/lib/download.js:19 — utp-native); this suite proves the
rebuilt datagram transport carries the same workloads."""

import asyncio
import hashlib
import os
import socket
import struct

import pytest

from downloader_tpu.torrent import Seeder, TorrentClient, make_metainfo
from downloader_tpu.torrent.tracker import Peer
from downloader_tpu.torrent.utp import (
    ST_DATA,
    ST_RESET,
    ST_STATE,
    ST_SYN,
    UtpEndpoint,
    decode_packet,
    encode_packet,
    open_utp_connection,
)

pytestmark = pytest.mark.anyio


# -- codec -------------------------------------------------------------


def test_packet_roundtrip():
    raw = encode_packet(ST_DATA, 0xBEEF, 123456, 654321, 1 << 20,
                        777, 776, payload=b"hello")
    (ptype, conn_id, ts, ts_diff, wnd, seq, ack, sack,
     payload) = decode_packet(raw)
    assert (ptype, conn_id, ts, ts_diff, wnd, seq, ack, sack, payload) == (
        ST_DATA, 0xBEEF, 123456, 654321, 1 << 20, 777, 776, b"", b"hello")


def test_packet_sack_extension():
    mask = bytes([0b101, 0, 0, 0, 0, 0, 0, 1])
    raw = encode_packet(ST_STATE, 1, 0, 0, 0, 5, 4, sack=mask)
    *_head, sack, payload = decode_packet(raw)
    assert sack == mask and payload == b""


def test_packet_rejects_garbage():
    from downloader_tpu.torrent.utp import PacketError

    with pytest.raises(PacketError):
        decode_packet(b"short")
    with pytest.raises(PacketError):
        decode_packet(b"\xff" * 20)  # bad version nibble


def test_packet_decode_fuzz():
    """decode_packet must never raise anything but PacketError on
    arbitrary bytes (same discipline as the bencode fuzz): it parses
    untrusted datagrams straight off the wire."""
    import random as stdlib_random

    from downloader_tpu.torrent.utp import PacketError

    rng = stdlib_random.Random(0xDEC0DE)
    for _ in range(2000):
        size = rng.randrange(0, 64)
        blob = bytes(rng.randrange(256) for _ in range(size))
        try:
            decode_packet(blob)
        except PacketError:
            pass
    # mutated valid packets: flip bytes in a well-formed SACK packet
    base = bytearray(encode_packet(
        ST_STATE, 7, 1, 2, 3, 4, 5, sack=bytes(8), payload=b"xyz"))
    for _ in range(2000):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 4)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        try:
            decode_packet(bytes(blob))
        except PacketError:
            pass


# -- stream transfer ---------------------------------------------------


class _Lossy:
    """Deterministic drop/reorder wrapper around a DatagramTransport."""

    def __init__(self, inner, drop_every=0, swap_every=0):
        self._inner = inner
        self._n = 0
        self._drop = drop_every
        self._swap = swap_every
        self._held = None

    def sendto(self, data, addr=None):
        self._n += 1
        if self._drop and self._n % self._drop == 0:
            return
        if self._swap and self._n % self._swap == 0 and self._held is None:
            self._held = (data, addr)
            return
        self._send(data, addr)
        if self._held is not None:
            held, self._held = self._held, None
            self._send(*held)

    def _send(self, data, addr):
        if addr is None:
            self._inner.sendto(data)
        else:
            self._inner.sendto(data, addr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


async def _echo_digest_transfer(payload: bytes, drop=0, swap=0) -> bytes:
    """Send ``payload`` (length-prefixed) to a digesting acceptor, return
    the 20-byte sha1 it computed."""

    async def handler(reader, writer):
        (n,) = struct.unpack(">I", await reader.readexactly(4))
        digest = hashlib.sha1()
        left = n
        while left:
            chunk = await reader.read(min(left, 1 << 16))
            if not chunk:
                return
            digest.update(chunk)
            left -= len(chunk)
        writer.write(digest.digest())
        await writer.drain()
        writer.close()
        await writer.wait_closed()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    if drop or swap:
        server._transport = _Lossy(server._transport, drop, swap)
    try:
        reader, writer = await open_utp_connection(*server.local_addr)
        if drop or swap:
            endpoint = writer._conn.endpoint
            endpoint._transport = _Lossy(endpoint._transport, drop, swap)
        writer.write(struct.pack(">I", len(payload)) + payload)
        await writer.drain()
        reply = await reader.readexactly(20)
        writer.close()
        await writer.wait_closed()
        return reply
    finally:
        server.close()


async def test_transfer_integrity():
    payload = os.urandom(2 << 20)
    async with asyncio.timeout(30):
        digest = await _echo_digest_transfer(payload)
    assert digest == hashlib.sha1(payload).digest()


async def test_utp_vs_tcp_ratio_floor():
    """Paired loopback stream transfer: uTP must hold >= 0.7x TCP's
    throughput measured in the same process, interleaved (VERDICT r4
    item 3 — nothing previously failed if the ratio regressed).  The
    ratio, not absolute MB/s, is asserted: host contention moves both
    lanes together.  Best-of-2 interleaved rounds for CI safety."""
    import time

    payload = os.urandom(12 << 20)

    async def measure(start_server, open_conn, stop_server) -> float:
        """One timed send of ``payload`` incl. both closes; the SAME
        code body measures both transports so they can never diverge."""
        done = asyncio.Event()

        async def handler(reader, writer):
            n = 0
            while n < len(payload):
                chunk = await reader.read(1 << 20)
                if not chunk:
                    break
                n += len(chunk)
            writer.close()
            await writer.wait_closed()
            done.set()

        server = await start_server(handler)
        reader, writer = await open_conn(server)
        t0 = time.monotonic()
        writer.write(payload)
        await writer.drain()
        writer.close()
        await writer.wait_closed()
        await done.wait()
        dt = time.monotonic() - t0
        await stop_server(server)
        return len(payload) / dt

    async def tcp_start(handler):
        return await asyncio.start_server(handler, "127.0.0.1", 0)

    async def tcp_open(server):
        return await asyncio.open_connection(
            "127.0.0.1", server.sockets[0].getsockname()[1])

    async def tcp_stop(server):
        server.close()
        await server.wait_closed()

    async def utp_start(handler):
        return await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)

    async def utp_open(server):
        return await open_utp_connection(*server.local_addr)

    async def utp_stop(server):
        server.close()

    def contended() -> bool:
        """Host-contention probe: with the 1-minute load average at or
        above the core count, the uTP user-space stack and the kernel
        TCP path no longer get comparable scheduling — the documented
        full-suite single-core flake regime, where the ratio floor
        measures the scheduler, not the transport."""
        try:
            return os.getloadavg()[0] >= max(os.cpu_count() or 1, 1)
        except OSError:
            return False

    best = 0.0
    async with asyncio.timeout(120):
        for _ in range(2):
            tcp_rate = await measure(tcp_start, tcp_open, tcp_stop)
            utp_rate = await measure(utp_start, utp_open, utp_stop)
            best = max(best, utp_rate / tcp_rate)
    # 0.85 ratchets the floor to the r5 level (shipping 0.93-1.41 after
    # the FIN-drain/TLP/coalescing work; 0.7 only guarded r4) while
    # keeping margin for CI noise — best-of-2 already de-noises.
    # ISSUE 13 satellite: a sub-floor ratio measured on a CONTENDED
    # host is the documented load flake (green standalone since PR 8),
    # not a transport regression — skip with the probe on record
    # instead of paying an intermittent tier-1 red; an idle-host miss
    # still fails hard.
    if best < 0.85 and contended():
        pytest.skip(
            f"utp/tcp ratio {best:.3f} under host load "
            f"{os.getloadavg()[0]:.1f} >= {os.cpu_count()} cores: "
            "single-core contention flake, not a transport regression"
        )
    assert best >= 0.85, f"utp/tcp ratio {best:.3f} below the 0.85 floor"


async def test_connection_churn_no_socket_accumulation():
    """Short-lived connections must retire their sockets promptly: the
    LAST_ACK drain window ends early once the peer's FIN completes the
    handshake (r5 — without that, churn accumulates a dead socket per
    close for the full linger, and before r5 every close stalled ~3 s
    in FIN retransmits)."""
    import time

    async def handler(reader, writer):
        data = await reader.read(65536)
        writer.write(data)
        await writer.drain()
        writer.close()
        await writer.wait_closed()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        base = len(os.listdir("/proc/self/fd"))
        t0 = time.monotonic()
        async with asyncio.timeout(60):
            for _ in range(30):
                reader, writer = await open_utp_connection(
                    *server.local_addr)
                writer.write(b"x" * 4096)
                await writer.drain()
                await reader.read(4096)
                writer.close()
                await writer.wait_closed()
        elapsed = time.monotonic() - t0
        after = len(os.listdir("/proc/self/fd"))
        assert after - base <= 2, f"socket accumulation: {after - base} fds"
        assert len(server._conns) == 0
        # pre-r5 the FIN stall was ~3 s per close; 30 must not crawl
        assert elapsed < 30, f"close path stalling again ({elapsed:.1f}s)"
    finally:
        server.close()


async def test_proactor_fallback_transport(monkeypatch):
    """Loops without ``add_reader`` (Windows' ProactorEventLoop) must
    fall back to asyncio's stock datagram transport instead of failing
    endpoint creation (advisor r4).  Simulated by making the public
    add_reader raise; the selector loop's own datagram plumbing uses the
    private registration path, so the fallback still works here."""
    from downloader_tpu.torrent.utp import _RawUdpTransport

    loop = asyncio.get_running_loop()

    def _no_add_reader(*a, **kw):
        raise NotImplementedError

    monkeypatch.setattr(loop, "add_reader", _no_add_reader,
                        raising=False)
    payload = os.urandom(256 << 10)
    async with asyncio.timeout(30):

        async def handler(reader, writer):
            data = await reader.readexactly(len(payload))
            writer.write(hashlib.sha1(data).digest())
            await writer.drain()
            writer.close()
            await writer.wait_closed()

        server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
        assert not isinstance(server._transport, _RawUdpTransport)
        try:
            reader, writer = await open_utp_connection(*server.local_addr)
            assert not isinstance(
                writer._conn.endpoint._transport, _RawUdpTransport)
            writer.write(payload)
            await writer.drain()
            reply = await reader.readexactly(20)
            writer.close()
            await writer.wait_closed()
        finally:
            server.close()
    assert reply == hashlib.sha1(payload).digest()


@pytest.mark.parametrize("drop,swap", [(0, 5), (17, 0), (13, 7)])
async def test_transfer_survives_loss_and_reorder(drop, swap):
    payload = os.urandom(512 << 10)
    async with asyncio.timeout(60):
        digest = await _echo_digest_transfer(payload, drop=drop, swap=swap)
    assert digest == hashlib.sha1(payload).digest()


async def test_close_delivers_eof():
    got = bytearray()
    done = asyncio.Event()

    async def handler(reader, writer):
        while True:
            chunk = await reader.read(1 << 16)
            if not chunk:
                break
            got.extend(chunk)
        done.set()
        writer.close()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        _reader, writer = await open_utp_connection(*server.local_addr)
        writer.write(b"tail bytes")
        writer.close()
        await writer.wait_closed()
        async with asyncio.timeout(10):
            await done.wait()
        assert bytes(got) == b"tail bytes"
    finally:
        server.close()


async def test_zero_window_recovery(monkeypatch):
    """A slow consumer that fills the receive window must not deadlock.

    Once the receiver advertises wnd=0 and the sender's flight drains,
    acks (which are only sent in response to data) stop flowing in both
    directions; without the zero-window probe / unsolicited window
    update, the connection would sit dead until IDLE_TIMEOUT (300 s).
    The test drives the connection into exactly that state, then lets
    the consumer drain and requires completion orders of magnitude
    faster than the idle timeout."""
    from downloader_tpu.torrent import utp as utp_mod

    monkeypatch.setattr(utp_mod, "RECV_WINDOW", 64 << 10)
    release = asyncio.Event()
    got = bytearray()
    done = asyncio.Event()

    async def handler(reader, writer):
        await release.wait()
        while True:
            chunk = await reader.read(1 << 16)
            if not chunk:
                break
            got.extend(chunk)
        done.set()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        payload = os.urandom(512 << 10)
        reader, writer = await open_utp_connection(*server.local_addr)
        conn = writer._conn
        writer.write(payload)
        async with asyncio.timeout(30):
            # the deadlock state: peer quenched us, nothing in flight,
            # bytes still waiting to be sent
            while not (conn._peer_wnd < conn.max_payload
                       and not conn._inflight and conn._send_q_len):
                await asyncio.sleep(0.02)
            release.set()
            writer.close()
            await done.wait()
        assert bytes(got) == payload
    finally:
        server.close()


async def test_zero_window_probe_is_minimal(monkeypatch):
    """The sender-side probe past a closed window carries ONE byte, not a
    full (up to 60 KiB on loopback) chunk — a stalled receiver's buffer
    overshoot stays bounded near zero instead of piling toward the 4x
    backstop (advisor r3)."""
    from downloader_tpu.torrent import utp as utp_mod

    monkeypatch.setattr(utp_mod, "RECV_WINDOW", 64 << 10)
    release = asyncio.Event()
    got = bytearray()
    done = asyncio.Event()

    async def handler(reader, writer):
        await release.wait()
        while True:
            chunk = await reader.read(1 << 16)
            if not chunk:
                break
            got.extend(chunk)
        done.set()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        payload = os.urandom(512 << 10)
        reader, writer = await open_utp_connection(*server.local_addr)
        conn = writer._conn
        writer.write(payload)
        async with asyncio.timeout(30):
            # reach the stall: peer quenched us, flight empty, data queued
            while not (conn._peer_wnd < conn.max_payload
                       and not conn._inflight and conn._send_q_len):
                await asyncio.sleep(0.02)
            # record what the stalled sender puts on the wire from here on
            sent = []
            orig_send = conn.endpoint._send

            def spy(data, addr):
                sent.append(bytes(data))
                orig_send(data, addr)

            conn.endpoint._send = spy
            while not any(decode_packet(d)[0] == ST_DATA for d in sent):
                await asyncio.sleep(0.05)
            probe_payloads = [decode_packet(d)[8] for d in sent
                              if decode_packet(d)[0] == ST_DATA]
            assert all(len(p) == 1 for p in probe_payloads), (
                [len(p) for p in probe_payloads]
            )
            conn.endpoint._send = orig_send
            release.set()
            writer.close()
            await done.wait()
        assert bytes(got) == payload
    finally:
        server.close()


async def test_take_chunk_matches_bytearray_reference():
    """The zero-memmove send queue must hand out exactly the bytes a
    plain bytearray buffer would, across random interleavings of writes
    and arbitrary-size takes (the r4 rewrite's byte accounting).
    Async so UtpConnection's asyncio primitives see a running loop."""
    import random as stdlib_random

    from downloader_tpu.torrent.utp import UtpConnection, UtpEndpoint

    rng = stdlib_random.Random(0x5EED)
    for _ in range(50):
        conn = UtpConnection(UtpEndpoint(), ("127.0.0.1", 1),
                             recv_id=1, send_id=2, seq=1)
        reference = bytearray()
        stream = bytearray()
        taken = bytearray()
        for _ in range(rng.randrange(2, 30)):
            if rng.random() < 0.6 or not conn._send_q_len:
                blob = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(0, 2000)))
                stream += blob
                reference += blob
                if blob:  # _write would flush; append directly instead
                    conn._send_q.append(blob)
                    conn._send_q_len += len(blob)
            else:
                want = rng.randrange(1, 1500)
                size = min(want, conn._send_q_len)
                chunk = conn._take_chunk(size)
                assert chunk == bytes(reference[:size])
                del reference[:size]
                taken += chunk
        while conn._send_q_len:
            size = min(777, conn._send_q_len)
            taken += conn._take_chunk(size)
        assert bytes(taken) == bytes(stream)
        assert conn._send_q_len == 0 and not conn._send_q


async def test_delayed_acks_halve_ack_rate():
    """On a clean in-order bulk transfer the receiver acks far less
    than once per data packet (cumulative ack_nr makes this
    protocol-legal) — the r3 profile measured one ack per data packet
    as roughly half the per-packet processing budget.  Two mechanisms
    compound: delayed acks (every Nth in-order packet) and the r4
    draining read loop, whose call_soon coalescer folds a whole
    RECV_BATCH burst into ONE ack.  The floor is one ack per drained
    batch; the ceiling is one per DELAYED_ACK_EVERY packets."""
    from downloader_tpu.torrent.utp import (
        DELAYED_ACK_EVERY,
        _RawUdpTransport,
    )

    counts = {"data": 0, "state": 0}

    class Counting:
        def __init__(self, inner):
            self._inner = inner

        def sendto(self, data, addr=None):
            kind = decode_packet(bytes(data))[0]
            if kind == ST_DATA:
                counts["data"] += 1
            elif kind == ST_STATE:
                counts["state"] += 1
            if addr is None:
                self._inner.sendto(data)
            else:
                self._inner.sendto(data, addr)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    done = asyncio.Event()

    async def handler(reader, writer):
        while True:
            chunk = await reader.read(1 << 18)
            if not chunk:
                break
        done.set()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    server._transport = Counting(server._transport)  # counts server acks
    try:
        _reader, writer = await open_utp_connection(*server.local_addr)
        conn = writer._conn
        payload = os.urandom(4 << 20)
        view = memoryview(payload)
        async with asyncio.timeout(30):
            for off in range(0, len(view), 1 << 18):
                writer.write(view[off:off + (1 << 18)])
                await writer.drain()
            writer.close()
            await writer.wait_closed()
            await done.wait()
        data_pkts = 4 * (1 << 20) // conn.max_payload
        # the server's ST_STATEs ack the client's data stream: at most
        # 1/DELAYED_ACK_EVERY of the data packets (slack for handshake/
        # FIN/timer-flushed odd tails), at least one per drained batch
        assert counts["state"] <= data_pkts / DELAYED_ACK_EVERY + 10, counts
        assert counts["state"] >= max(
            2, data_pkts // _RawUdpTransport.RECV_BATCH), counts
    finally:
        server.close()


async def test_transfer_over_ipv6():
    """Trackers/PEX hand out IPv6 peers (BEP 7); the uTP dial must work
    there too.  The 4-tuple IPv6 addr normalizes to (host, port) for the
    connection registry."""

    async def handler(reader, writer):
        (n,) = struct.unpack(">I", await reader.readexactly(4))
        digest = hashlib.sha1()
        left = n
        while left:
            chunk = await reader.read(min(left, 1 << 16))
            digest.update(chunk)
            left -= len(chunk)
        writer.write(digest.digest())
        await writer.drain()
        writer.close()
        await writer.wait_closed()

    server = await UtpEndpoint.create("::1", 0, accept_cb=handler)
    try:
        assert server.local_addr[0] == "::1"
        reader, writer = await open_utp_connection(*server.local_addr)
        payload = os.urandom(256 << 10)
        writer.write(struct.pack(">I", len(payload)) + payload)
        await writer.drain()
        async with asyncio.timeout(20):
            reply = await reader.readexactly(20)
        assert reply == hashlib.sha1(payload).digest()
        writer.close()
        await writer.wait_closed()
    finally:
        server.close()


async def test_connect_refused_is_fast():
    """Dialing a dead UDP port must fail via ICMP, not a long timeout."""
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # now nothing listens there
    async with asyncio.timeout(5):
        with pytest.raises((ConnectionRefusedError, TimeoutError)):
            await open_utp_connection("127.0.0.1", port, timeout=4)


async def test_unknown_connection_gets_reset():
    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=None)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    try:
        # ST_DATA for a connection that doesn't exist
        bogus = encode_packet(ST_DATA, 4242, 0, 0, 0, 9, 8, payload=b"?")
        sock.sendto(bogus, server.local_addr)
        loop = asyncio.get_running_loop()
        async with asyncio.timeout(5):
            data = await loop.sock_recv(sock, 64)
        ptype, conn_id, *_rest = decode_packet(data)
        assert ptype == ST_RESET
        assert conn_id == 4242
        # and a bare SYN with no acceptor must NOT create state
        syn = encode_packet(ST_SYN, 7, 0, 0, 0, 1, 0)
        sock.sendto(syn, server.local_addr)
        await asyncio.sleep(0.1)
        assert not server._conns
    finally:
        sock.close()
        server.close()


async def test_syn_retransmit_reacks_existing_connection():
    """A retransmitted SYN (lost/slow ST_STATE) must re-ack through the
    live acceptor connection, not clobber it with a fresh one whose new
    random seq would desynchronize the initiator."""

    async def handler(reader, _writer):
        await reader.read(1)

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setblocking(False)
    loop = asyncio.get_running_loop()
    try:
        syn = encode_packet(ST_SYN, 777, 0, 0, 1 << 20, 1, 0)
        sock.sendto(syn, server.local_addr)
        async with asyncio.timeout(5):
            first = decode_packet(await loop.sock_recv(sock, 64))
        assert first[0] == ST_STATE
        assert len(server._conns) == 1
        conn = next(iter(server._conns.values()))

        sock.sendto(syn, server.local_addr)  # retransmit
        async with asyncio.timeout(5):
            second = decode_packet(await loop.sock_recv(sock, 64))
        assert second[0] == ST_STATE
        assert second[5] == first[5]  # same seq_nr: same connection
        assert len(server._conns) == 1
        assert next(iter(server._conns.values())) is conn
    finally:
        sock.close()
        server.close()


async def test_syn_flood_is_bounded(monkeypatch):
    """An attacker spraying SYNs with distinct conn-ids must not mint
    unbounded connection state on the acceptor."""
    from downloader_tpu.torrent import utp as utp_mod

    monkeypatch.setattr(utp_mod, "MAX_ACCEPTED_CONNS", 16)

    async def handler(reader, _writer):
        await reader.read(1)

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for conn_id in range(200):
            sock.sendto(encode_packet(ST_SYN, conn_id, 0, 0, 0, 1, 0),
                        server.local_addr)
        await asyncio.sleep(0.2)
        assert len(server._conns) <= 16
        # and a real connection still works once load drops (the cap
        # bounds state, it doesn't break the endpoint)
        for conn in list(server._conns.values()):
            conn.abort()
        reader, writer = await open_utp_connection(*server.local_addr)
        writer.write(b"!")
        await writer.drain()
        writer.close()
        await writer.wait_closed()
    finally:
        sock.close()
        server.close()


async def test_idle_connection_reaped(monkeypatch):
    """A connected peer that goes silent is aborted after IDLE_TIMEOUT
    (healthy BT connections keep-alive every 60 s).

    Margins are deliberately wide (ISSUE 9 satellite): under full-suite
    load the event loop can stall long enough that a 0.2 s idle window
    expires between the handshake and the first assert — the conn was
    then reaped *early*, which the old ``== 1`` read as a failure — and
    a loaded box can also need more than 5 s of wall for a timer that
    only has to fire once.  The reap itself is proven by the accept
    handler's pending read raising the idle-timeout reset (not by conn
    counts, which are also empty when tracking never happened at all).
    """
    from downloader_tpu.torrent import utp as utp_mod

    monkeypatch.setattr(utp_mod, "IDLE_TIMEOUT", 0.75)
    reaped = asyncio.get_running_loop().create_future()

    async def handler(reader, _writer):
        try:
            await reader.read(1)
        except ConnectionResetError as err:  # the reap's abort(exc)
            if not reaped.done():
                reaped.set_result(str(err))

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        _reader, writer = await open_utp_connection(*server.local_addr)
        assert len(server._conns) <= 1  # 0 = already reaped, still a reap
        async with asyncio.timeout(20):
            assert "idle" in await reaped
            while server._conns:
                await asyncio.sleep(0.05)
        writer.close()
    finally:
        server.close()


def test_seq_compare_wraps():
    from downloader_tpu.torrent.utp import _seq_lt, _seq_lte

    assert _seq_lte(5, 5) and not _seq_lt(5, 5)
    assert _seq_lt(65535, 0)          # wrap: 65535 < 0
    assert _seq_lt(65530, 5)
    assert not _seq_lt(5, 65530)
    assert _seq_lte(0, 32766) and not _seq_lte(0, 40000)


async def test_transfer_across_seq_wrap(monkeypatch):
    """A server->client stream starting near 65535 must cross the 16-bit
    wrap without stalling or reordering (the acceptor's initial seq is
    random, so real connections hit this)."""
    from downloader_tpu.torrent import utp as utp_mod

    # NB: utp_mod.random is the stdlib module — this pins every randrange
    # in the process for the test's duration (incl. connect()'s conn-id,
    # which is harmless here); *a keeps any arity working
    monkeypatch.setattr(utp_mod.random, "randrange", lambda *a: 0xFFF8)
    payload = os.urandom(600 << 10)  # ~440 packets: far past the wrap

    async def handler(reader, writer):
        await reader.readexactly(4)
        writer.write(payload)
        await writer.drain()
        writer.close()
        await writer.wait_closed()

    server = await UtpEndpoint.create("127.0.0.1", 0, accept_cb=handler)
    try:
        reader, writer = await open_utp_connection(*server.local_addr)
        writer.write(b"go!!")
        await writer.drain()
        async with asyncio.timeout(30):
            got = await reader.readexactly(len(payload))
        assert hashlib.sha1(got).digest() == hashlib.sha1(payload).digest()
        writer.close()
        await writer.wait_closed()
    finally:
        server.close()


# -- the torrent stack over uTP ----------------------------------------


def _make_swarm(tmp_path, mib=4):
    src = tmp_path / "seed" / "payload"
    src.mkdir(parents=True)
    (src / "media.mkv").write_bytes(os.urandom(mib << 20))
    meta = make_metainfo(str(tmp_path / "seed" / "payload"),
                         piece_length=1 << 18)
    torrent = tmp_path / "t.torrent"
    torrent.write_bytes(meta.to_torrent_bytes())
    return meta, str(torrent)


async def test_torrent_download_over_utp(tmp_path):
    meta, torrent = _make_swarm(tmp_path)
    seeder = Seeder(meta, str(tmp_path / "seed"))
    port = await seeder.start()
    try:
        async with asyncio.timeout(60):
            await TorrentClient(transport="utp").download(
                torrent, str(tmp_path / "dl"),
                peers=[Peer("127.0.0.1", port)], listen=False,
            )
    finally:
        await seeder.stop()
    out = tmp_path / "dl" / "payload" / "media.mkv"
    assert (hashlib.sha1(out.read_bytes()).digest()
            == hashlib.sha1(
                (tmp_path / "seed" / "payload" / "media.mkv").read_bytes()
            ).digest())


async def test_torrent_mse_over_utp(tmp_path):
    """MSE/PE is a stream-layer handshake: it must run unchanged over the
    datagram transport (crypto=require leaves no plaintext fallback)."""
    meta, torrent = _make_swarm(tmp_path, mib=2)
    seeder = Seeder(meta, str(tmp_path / "seed"))
    port = await seeder.start()
    try:
        async with asyncio.timeout(60):
            await TorrentClient(transport="utp", crypto="require").download(
                torrent, str(tmp_path / "dl"),
                peers=[Peer("127.0.0.1", port)], listen=False,
            )
    finally:
        await seeder.stop()
    assert (tmp_path / "dl" / "payload" / "media.mkv").stat().st_size == 2 << 20


async def test_auto_falls_back_to_utp(tmp_path):
    """transport=auto must reach a peer whose TCP port is closed but whose
    uTP (UDP) listener is up — the NAT'd-peer scenario uTP exists for."""
    meta, torrent = _make_swarm(tmp_path, mib=1)
    seeder = Seeder(meta, str(tmp_path / "seed"))
    await seeder.start(utp=False)  # TCP only, for the piece source below

    # uTP-only address: a raw endpoint accepting into the seeder's shared
    # connection handler, with no TCP socket on that port
    utp_only = await UtpEndpoint.create(
        "127.0.0.1", 0, accept_cb=seeder._on_connect)
    try:
        async with asyncio.timeout(60):
            await TorrentClient(transport="auto").download(
                torrent, str(tmp_path / "dl"),
                peers=[Peer(*utp_only.local_addr)], listen=False,
            )
    finally:
        utp_only.close()
        await seeder.stop()
    assert (tmp_path / "dl" / "payload" / "media.mkv").stat().st_size == 1 << 20


async def test_mixed_transport_swarm(tmp_path):
    """One client in auto mode drains a swarm of one TCP-only and one
    uTP-only peer concurrently — the per-peer fallback composes with the
    worker pool."""
    meta, torrent = _make_swarm(tmp_path, mib=2)
    tcp_seeder = Seeder(meta, str(tmp_path / "seed"))
    tcp_port = await tcp_seeder.start(utp=False)

    utp_seeder = Seeder(meta, str(tmp_path / "seed"))
    utp_only = await UtpEndpoint.create(
        "127.0.0.1", 0, accept_cb=utp_seeder._on_connect)
    try:
        async with asyncio.timeout(60):
            await TorrentClient(transport="auto").download(
                torrent, str(tmp_path / "dl"),
                peers=[Peer("127.0.0.1", tcp_port),
                       Peer(*utp_only.local_addr)],
                listen=False,
            )
    finally:
        utp_only.close()
        await tcp_seeder.stop()
        await utp_seeder.stop()
    assert ((tmp_path / "dl" / "payload" / "media.mkv").stat().st_size
            == 2 << 20)


async def test_seeder_serves_tcp_and_utp_concurrently(tmp_path):
    meta, torrent = _make_swarm(tmp_path, mib=2)
    seeder = Seeder(meta, str(tmp_path / "seed"))
    port = await seeder.start()
    try:
        async with asyncio.timeout(60):
            await asyncio.gather(
                TorrentClient(transport="tcp").download(
                    torrent, str(tmp_path / "dl-tcp"),
                    peers=[Peer("127.0.0.1", port)], listen=False,
                ),
                TorrentClient(transport="utp").download(
                    torrent, str(tmp_path / "dl-utp"),
                    peers=[Peer("127.0.0.1", port)], listen=False,
                ),
            )
    finally:
        await seeder.stop()
    for sub in ("dl-tcp", "dl-utp"):
        assert ((tmp_path / sub / "payload" / "media.mkv").stat().st_size
                == 2 << 20)
