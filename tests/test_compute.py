"""Compute subsystem tests (virtual 8-device CPU mesh via conftest)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from downloader_tpu.compute.models.upscaler import (  # noqa: E402
    Upscaler,
    UpscalerConfig,
)
from downloader_tpu.compute.ops.pixel_shuffle import (  # noqa: E402
    _pallas_shuffle_clip,
    pixel_shuffle,
    pixel_shuffle_clip_u8,
)
from downloader_tpu.compute.parallel.mesh import (  # noqa: E402
    make_mesh,
    shard_batch,
    shard_params,
)
from downloader_tpu.compute.train import make_train_step  # noqa: E402

TINY = UpscalerConfig(features=16, depth=2, scale=2)


def test_pixel_shuffle_matches_reference():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 4, 12)).astype(np.float32)  # C=3*2*2
    out = np.asarray(pixel_shuffle(jnp.asarray(x), 2))
    assert out.shape == (2, 6, 8, 3)
    # spot-check the sub-pixel interleave: output[b, h*r+dr, w*r+dc, c]
    # == input[b, h, w, (dr*r + dc)*C + c]
    for b, h, w, dr, dc, c in [(0, 1, 2, 0, 1, 1), (1, 2, 3, 1, 0, 2), (0, 0, 0, 1, 1, 0)]:
        expected = x[b, h, w, (dr * 2 + dc) * 3 + c]
        assert out[b, h * 2 + dr, w * 2 + dc, c] == expected


def test_pallas_kernel_matches_xla_path():
    rng = np.random.default_rng(1)
    x = rng.uniform(-20, 300, (2, 4, 8, 12)).astype(np.float32)
    xla = pixel_shuffle_clip_u8(jnp.asarray(x), 2)
    pallas = _pallas_shuffle_clip(jnp.asarray(x), 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(xla), np.asarray(pallas))


def test_upscaler_shapes_and_dtype():
    model = Upscaler(TINY)
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 16, 16, 3)
    assert out.dtype == jnp.bfloat16


def test_train_step_reduces_loss():
    train_step, init_state = make_train_step(TINY, learning_rate=3e-3)
    rng = jax.random.PRNGKey(0)
    params, opt_state = init_state(rng, sample_shape=(1, 8, 8, 3))

    low = jax.random.uniform(rng, (4, 8, 8, 3))
    # target correlated with input (upscaled nearest) so the model can learn
    high = jnp.repeat(jnp.repeat(low, 2, axis=1), 2, axis=2)

    step = jax.jit(train_step)
    first_loss = None
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state, low, high)
        if first_loss is None:
            first_loss = float(loss)
    assert float(loss) < first_loss


def test_mesh_sharded_train_step_runs_and_matches_single_device():
    """The multi-chip path computes the same loss as single-device."""
    train_step, init_state = make_train_step(TINY)
    rng = jax.random.PRNGKey(42)
    params, opt_state = init_state(rng, sample_shape=(1, 8, 8, 3))
    low = jax.random.uniform(rng, (8, 8, 8, 3))
    high = jax.random.uniform(rng, (8, 16, 16, 3))

    # single device reference
    _, _, ref_loss = jax.jit(train_step)(params, opt_state, low, high)

    # 4x2 mesh: dp over 4, tp over 2
    plan = make_mesh(8, model_axis=2)
    sharded_params = shard_params(plan, params)
    sharded_opt = shard_params(plan, opt_state)
    slow = shard_batch(plan, low)
    shigh = shard_batch(plan, high)
    with plan.mesh:
        _, _, mesh_loss = jax.jit(train_step)(
            sharded_params, sharded_opt, slow, shigh
        )
    np.testing.assert_allclose(
        float(ref_loss), float(mesh_loss), rtol=2e-2
    )


def test_param_sharding_layout():
    plan = make_mesh(8, model_axis=2)
    _, init_state = make_train_step(TINY)[0], make_train_step(TINY)[1]
    params, _ = init_state(jax.random.PRNGKey(0), sample_shape=(1, 8, 8, 3))
    sharded = shard_params(plan, params)

    stem = sharded["params"]["stem"]["kernel"]
    # conv kernels split on the output-channel (last) dim across 'model'
    assert stem.sharding.spec == jax.sharding.PartitionSpec(
        None, None, None, "model"
    )
    sub = sharded["params"]["subpixel"]["kernel"]
    assert sub.sharding.spec == jax.sharding.PartitionSpec()


def test_graft_entry_contract():
    """The driver contract: entry() compiles; dryrun_multichip(8) runs."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 128, 128, 3)

    mod.dryrun_multichip(8)
