"""Compute subsystem tests (virtual 8-device CPU mesh via conftest)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from downloader_tpu.compute.models.upscaler import (  # noqa: E402
    Upscaler,
    UpscalerConfig,
)
from downloader_tpu.compute.ops.pixel_shuffle import (  # noqa: E402
    _pallas_shuffle_clip,
    pixel_shuffle,
    pixel_shuffle_clip_u8,
)
from downloader_tpu.compute.parallel.mesh import (  # noqa: E402
    make_mesh,
    shard_batch,
    shard_params,
)
from downloader_tpu.compute.train import make_train_step  # noqa: E402

TINY = UpscalerConfig(features=16, depth=2, scale=2)


def test_pixel_shuffle_matches_reference():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 3, 4, 12)).astype(np.float32)  # C=3*2*2
    out = np.asarray(pixel_shuffle(jnp.asarray(x), 2))
    assert out.shape == (2, 6, 8, 3)
    # spot-check the sub-pixel interleave: output[b, h*r+dr, w*r+dc, c]
    # == input[b, h, w, (dr*r + dc)*C + c]
    for b, h, w, dr, dc, c in [(0, 1, 2, 0, 1, 1), (1, 2, 3, 1, 0, 2), (0, 0, 0, 1, 1, 0)]:
        expected = x[b, h, w, (dr * 2 + dc) * 3 + c]
        assert out[b, h * 2 + dr, w * 2 + dc, c] == expected


def test_pallas_kernel_matches_xla_path():
    rng = np.random.default_rng(1)
    x = rng.uniform(-20, 300, (2, 4, 8, 12)).astype(np.float32)
    xla = pixel_shuffle_clip_u8(jnp.asarray(x), 2)
    pallas = _pallas_shuffle_clip(jnp.asarray(x), 2, interpret=True)
    np.testing.assert_array_equal(np.asarray(xla), np.asarray(pallas))


def test_upscaler_shapes_and_dtype():
    model = Upscaler(TINY)
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(params, x)
    assert out.shape == (2, 16, 16, 3)
    assert out.dtype == jnp.bfloat16


def test_train_step_reduces_loss():
    train_step, init_state = make_train_step(TINY, learning_rate=3e-3)
    rng = jax.random.PRNGKey(0)
    params, opt_state = init_state(rng, sample_shape=(1, 8, 8, 3))

    low = jax.random.uniform(rng, (4, 8, 8, 3))
    # target correlated with input (upscaled nearest) so the model can learn
    high = jnp.repeat(jnp.repeat(low, 2, axis=1), 2, axis=2)

    step = jax.jit(train_step)
    first_loss = None
    for _ in range(12):
        params, opt_state, loss = step(params, opt_state, low, high)
        if first_loss is None:
            first_loss = float(loss)
    assert float(loss) < first_loss


def test_mesh_sharded_train_step_runs_and_matches_single_device():
    """The multi-chip path computes the same loss as single-device."""
    train_step, init_state = make_train_step(TINY)
    rng = jax.random.PRNGKey(42)
    params, opt_state = init_state(rng, sample_shape=(1, 8, 8, 3))
    low = jax.random.uniform(rng, (8, 8, 8, 3))
    high = jax.random.uniform(rng, (8, 16, 16, 3))

    # single device reference
    _, _, ref_loss = jax.jit(train_step)(params, opt_state, low, high)

    # 4x2 mesh: dp over 4, tp over 2
    plan = make_mesh(8, model_axis=2)
    sharded_params = shard_params(plan, params)
    sharded_opt = shard_params(plan, opt_state)
    slow = shard_batch(plan, low)
    shigh = shard_batch(plan, high)
    with plan.mesh:
        _, _, mesh_loss = jax.jit(train_step)(
            sharded_params, sharded_opt, slow, shigh
        )
    np.testing.assert_allclose(
        float(ref_loss), float(mesh_loss), rtol=2e-2
    )


def test_param_sharding_layout():
    plan = make_mesh(8, model_axis=2)
    _, init_state = make_train_step(TINY)[0], make_train_step(TINY)[1]
    params, _ = init_state(jax.random.PRNGKey(0), sample_shape=(1, 8, 8, 3))
    sharded = shard_params(plan, params)

    stem = sharded["params"]["stem"]["kernel"]
    # conv kernels split on the output-channel (last) dim across 'model'
    assert stem.sharding.spec == jax.sharding.PartitionSpec(
        None, None, None, "model"
    )
    sub = sharded["params"]["subpixel"]["kernel"]
    assert sub.sharding.spec == jax.sharding.PartitionSpec()


def test_graft_entry_contract():
    """The driver contract: entry() compiles; dryrun_multichip(8) runs."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "__graft_entry__",
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "__graft_entry__.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    fn, args = mod.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 128, 128, 3)

    mod.dryrun_multichip(8)


def test_checkpoint_save_restore_roundtrip(tmp_path):
    """Train a few steps, save, restore into a fresh state, and verify the
    restored state continues training identically."""
    import numpy as np

    from downloader_tpu.compute.checkpoint import (
        latest_step,
        restore_state,
        save_state,
    )
    from downloader_tpu.compute.train import make_train_step
    from downloader_tpu.compute.models.upscaler import UpscalerConfig

    config = UpscalerConfig(features=128, depth=2)
    train_step, init_state = make_train_step(config)
    step_fn = jax.jit(train_step)
    rng = jax.random.PRNGKey(7)
    params, opt_state = init_state(rng)
    low = jax.random.uniform(rng, (2, 16, 16, 3), jnp.float32)
    high = jax.random.uniform(rng, (2, 32, 32, 3), jnp.float32)
    for _ in range(3):
        params, opt_state, loss = step_fn(params, opt_state, low, high)

    ckpt_dir = str(tmp_path / "ckpt")
    save_state(ckpt_dir, 3, params, opt_state)
    assert latest_step(ckpt_dir) == 3

    fresh_params, fresh_opt = init_state(jax.random.PRNGKey(99))
    step, r_params, r_opt = restore_state(ckpt_dir, fresh_params, fresh_opt)
    assert step == 3
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(r_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # one more step from both states must agree bit-for-bit
    p1, _o1, l1 = step_fn(params, opt_state, low, high)
    p2, _o2, l2 = step_fn(r_params, r_opt, low, high)
    assert float(l1) == float(l2)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_restore_onto_mesh(tmp_path):
    """A single-device checkpoint restores onto a multi-device mesh with
    the plan's shardings applied."""
    import numpy as np

    from downloader_tpu.compute.checkpoint import restore_state, save_state
    from downloader_tpu.compute.parallel.mesh import make_mesh
    from downloader_tpu.compute.train import make_train_step
    from downloader_tpu.compute.models.upscaler import UpscalerConfig

    config = UpscalerConfig(features=128, depth=2)
    _train, init_state = make_train_step(config)
    params, opt_state = init_state(jax.random.PRNGKey(1))
    ckpt_dir = str(tmp_path / "ckpt-mesh")
    save_state(ckpt_dir, 0, params, opt_state)

    plan = make_mesh(len(jax.devices()), model_axis=2)
    fresh_params, fresh_opt = init_state(jax.random.PRNGKey(2))
    _step, r_params, _opt = restore_state(
        ckpt_dir, fresh_params, fresh_opt, plan=plan
    )
    # values intact and sharded per plan (body conv kernels split on model)
    flat = jax.tree_util.tree_flatten_with_path(r_params)[0]
    for path, value in flat:
        name = "/".join(str(p) for p in path)
        if "body" in name and value.ndim == 4:
            assert value.sharding.spec == plan.param_spec(path, value)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(r_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_infer_pipeline_uint8_roundtrip():
    """uint8 frames in, correctly-shaped uint8 frames out, matching the
    unfused reference computation."""
    import numpy as np

    from downloader_tpu.compute.infer import make_infer_fn, upscale_frames
    from downloader_tpu.compute.models.upscaler import (
        UpscalerConfig,
        init_params,
    )

    config = UpscalerConfig(features=128, depth=2)
    _model, params = init_params(jax.random.PRNGKey(3), config,
                                 sample_shape=(1, 16, 16, 3))
    frames = np.random.randint(0, 256, (2, 16, 16, 3), dtype=np.uint8)

    out = np.asarray(make_infer_fn(config)(params, jnp.asarray(frames)))
    assert out.shape == (2, 32, 32, 3)
    assert out.dtype == np.uint8

    # reference path: forward + clip/round/cast without the fused tail
    from downloader_tpu.compute.models.upscaler import Upscaler

    model = Upscaler(config)
    x = jnp.asarray(frames).astype(jnp.float32) / 255.0
    ref = jnp.clip(
        jnp.round(model.apply(params, x).astype(jnp.float32) * 255.0),
        0, 255,
    ).astype(jnp.uint8)
    np.testing.assert_array_equal(out, np.asarray(ref))

    # cached wrapper produces the same result
    again = np.asarray(upscale_frames(params, jnp.asarray(frames), config))
    np.testing.assert_array_equal(out, again)
