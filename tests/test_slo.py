"""In-process SLO accounting (downloader_tpu/control/slo.py; ISSUE 15).

Three layers:

- pure burn-rate / error-budget math against HAND-COMPUTED windows
  (breach, recovery past the fast window, budget exhaustion and its
  clamp) on a fake clock;
- settle classification through a real registry record: good inside
  target, latency breach, availability breach, nacks/cancels excluded,
  the ``slo_breach`` flight-recorder event, tenant-scoped objectives,
  config parsing (defaults, overrides, typo'd objective keys);
- the serving surfaces: ``/readyz`` ``slo`` block + the
  ``slo_burn_rate`` / ``slo_error_budget_remaining`` gauges off a real
  orchestrator settling real jobs, and the per-hop budget guard
  (``evaluate_hop_budgets``) failing BY NAME when a hop's baseline is
  artificially tightened — the bench v20 ``--slo`` contract.
"""

import os

import pytest
from aiohttp import web

from downloader_tpu import schemas
from downloader_tpu.control.registry import JobRegistry
from downloader_tpu.control.slo import (DEFAULT_OBJECTIVES, Objective,
                                        SloTracker, evaluate_hop_budgets,
                                        hop_budget_baseline, percentile,
                                        top_hops)
from downloader_tpu.health import build_app
from downloader_tpu.mq import InMemoryBroker, MemoryQueue
from downloader_tpu.orchestrator import Orchestrator
from downloader_tpu.platform import metrics as prom
from downloader_tpu.platform.config import ConfigNode
from downloader_tpu.platform.logging import NullLogger
from downloader_tpu.platform.telemetry import Telemetry
from downloader_tpu.store import InMemoryObjectStore

pytestmark = pytest.mark.anyio


class FakeClock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now


def make_tracker(availability=0.99, p99_ms=1000.0, clock=None, **kwargs):
    clock = clock or FakeClock()
    tracker = SloTracker(
        {"NORMAL": Objective("NORMAL", p99_ms, availability)},
        fast_window=300.0, slow_window=3600.0, budget_window=86400.0,
        clock=clock, **kwargs)
    return tracker, clock


class Settled:
    """The minimal record shape note_settle reads (a real JobRecord is
    used in the classification tests below; this one pins the clock)."""

    def __init__(self, clock, age_s=0.1, priority="NORMAL",
                 tenant="default"):
        self._created_mono = clock.now - age_s
        self.priority = priority
        self.tenant = tenant
        self.hops = None
        self.stage_seconds = {"pipeline": age_s}
        self.events = []

    def event(self, kind, **fields):
        self.events.append({"kind": kind, **fields})


# ---------------------------------------------------------------------------
# burn-rate math vs hand-computed windows
# ---------------------------------------------------------------------------

def test_burn_rate_hand_computed_breach():
    # availability 0.99 -> budget fraction 0.01.  9 good + 1 bad in the
    # fast window: bad_fraction = 0.1 -> burn = 0.1 / 0.01 = 10.
    tracker, clock = make_tracker(availability=0.99)
    for _ in range(9):
        tracker.note_settle(Settled(clock), "ack", "done")
    tracker.note_settle(Settled(clock), "ack", "permanent")
    assert tracker.burn_rate("NORMAL", 300.0) == pytest.approx(10.0)
    assert tracker.burn_rate("NORMAL", 3600.0) == pytest.approx(10.0)
    snap = tracker.snapshot()["objectives"]["NORMAL"]
    assert snap["breached"] is True
    assert snap["bad"] == 1


def test_burn_rate_recovery_fast_window_clears_first():
    # the bad event ages out of the 300 s fast window but stays in the
    # 3600 s slow window: fast burn 0 well before slow burn clears —
    # exactly the multiwindow "is it still happening" distinction.
    tracker, clock = make_tracker(availability=0.99)
    for _ in range(9):
        tracker.note_settle(Settled(clock), "ack", "done")
    tracker.note_settle(Settled(clock), "ack", "permanent")
    clock.now += 600.0  # past fast, inside slow
    for _ in range(10):
        tracker.note_settle(Settled(clock), "ack", "done")
    tracker._memo["snap"] = None  # new window, fresh scan
    assert tracker.burn_rate("NORMAL", 300.0) == pytest.approx(0.0)
    # slow window: 1 bad / 20 total = 0.05 -> burn 5
    assert tracker.burn_rate("NORMAL", 3600.0) == pytest.approx(5.0)
    snap = tracker.snapshot()["objectives"]["NORMAL"]
    assert snap["breached"] is False  # fast cleared: not paging


def test_budget_exhaustion_and_clamp():
    # availability 0.9 -> 10% budget.  10 resolutions allow exactly 1
    # bad: 1 bad -> remaining 0; more bad stays clamped at 0.
    tracker, clock = make_tracker(availability=0.9)
    for _ in range(9):
        tracker.note_settle(Settled(clock), "ack", "done")
    tracker.note_settle(Settled(clock), "ack", "permanent")
    assert tracker.budget_remaining("NORMAL") == pytest.approx(0.0)
    tracker.note_settle(Settled(clock), "ack", "permanent")
    assert tracker.budget_remaining("NORMAL") == 0.0
    # half the budget: 20 resolutions, 1 bad -> 1 - 1/2 = 0.5
    tracker2, clock2 = make_tracker(availability=0.9)
    for _ in range(19):
        tracker2.note_settle(Settled(clock2), "ack", "done")
    tracker2.note_settle(Settled(clock2), "ack", "permanent")
    assert tracker2.budget_remaining("NORMAL") == pytest.approx(0.5)


def test_no_events_is_quiet():
    tracker, _clock = make_tracker()
    assert tracker.burn_rate("NORMAL", 300.0) == 0.0
    assert tracker.budget_remaining("NORMAL") == 1.0
    snap = tracker.snapshot()["objectives"]["NORMAL"]
    assert snap["breached"] is False and snap["resolved"] == 0


def test_ring_is_bounded():
    tracker, clock = make_tracker(max_events=64)
    for _ in range(500):
        tracker.note_settle(Settled(clock), "ack", "done")
    assert len(tracker._series["NORMAL"].ring) == 64
    # cumulative totals keep counting past the ring
    assert tracker._series["NORMAL"].good_total == 500


# ---------------------------------------------------------------------------
# settle classification
# ---------------------------------------------------------------------------

def test_latency_breach_is_bad_and_stamps_slo_breach():
    tracker, clock = make_tracker(p99_ms=1000.0)
    record = Settled(clock, age_s=2.5)  # 2500 ms > 1000 ms target
    tracker.note_settle(record, "ack", "done")
    assert tracker.burn_rate("NORMAL", 300.0) > 0
    (event,) = [e for e in record.events if e["kind"] == "slo_breach"]
    assert event["breach"] == "latency"
    assert event["objective"] == "NORMAL"
    assert event["latency_ms"] == pytest.approx(2500.0, abs=50)
    assert event["target_ms"] == 1000.0


def test_availability_breach_names_the_why():
    tracker, clock = make_tracker()
    record = Settled(clock)
    tracker.note_settle(record, "ack", "poison")
    (event,) = [e for e in record.events if e["kind"] == "slo_breach"]
    assert event["breach"] == "availability"
    assert event["why"] == "poison"


def test_nacks_and_cancels_are_not_resolutions():
    tracker, clock = make_tracker()
    for why in ("stage_error", "breaker_open", "overload_shed"):
        tracker.note_settle(Settled(clock), "nack", why)
    tracker.note_settle(Settled(clock), "ack", "cancelled")
    series = tracker._series["NORMAL"]
    assert series.good_total == 0 and series.bad_total == 0


def test_good_settle_no_breach_event():
    tracker, clock = make_tracker()
    record = Settled(clock, age_s=0.05)
    tracker.note_settle(record, "ack", "done")
    assert not [e for e in record.events if e["kind"] == "slo_breach"]
    assert tracker._series["NORMAL"].good_total == 1


def test_unknown_priority_resolves_to_normal():
    tracker, clock = make_tracker()
    record = Settled(clock, priority="WEIRD")
    tracker.note_settle(record, "ack", "done")
    assert tracker._series["NORMAL"].good_total == 1


def test_tenant_objective_tracks_alongside_class():
    clock = FakeClock()
    tracker = SloTracker(
        {"NORMAL": Objective("NORMAL", 60000.0, 0.999)},
        tenant_objectives={"vip": Objective("vip", 100.0, 0.999)},
        clock=clock)
    record = Settled(clock, age_s=0.5, tenant="vip")  # 500 ms
    tracker.note_settle(record, "ack", "done")
    # inside NORMAL's 60 s target, outside vip's 100 ms target
    assert tracker._series["NORMAL"].good_total == 1
    assert tracker._series["vip"].bad_total == 1
    assert "vip" in tracker.snapshot()["objectives"]


def test_hop_and_stage_accumulation_feeds_digest():
    tracker, clock = make_tracker()
    registry = JobRegistry()
    record = registry.register("slo-digest-1", "card")
    record.note_hop("upload", 2 << 20, 0.25)
    record.stage_seconds["pipeline"] = 0.5
    record._created_mono = clock.now - 0.1
    tracker.note_settle(record, "ack", "done")
    digest = tracker.digest()
    assert digest["hops"]["upload"]["bytes"] == 2 << 20
    assert digest["hopSeconds"] == pytest.approx(0.25)
    assert digest["stageSeconds"] == pytest.approx(0.5)
    assert digest["hopReconcileRatio"] == pytest.approx(0.5)
    assert digest["burn"]["NORMAL"] == {"fast": 0.0, "slow": 0.0}


# ---------------------------------------------------------------------------
# config parsing
# ---------------------------------------------------------------------------

def test_from_config_defaults_and_overrides():
    tracker = SloTracker.from_config(ConfigNode({"slo": {
        "objectives": {"HIGH": {"p99_ms": 5000,
                                "availability": 0.9999}},
        "fast_window": 60,
    }}))
    assert tracker.objectives["HIGH"].p99_ms == 5000
    assert tracker.objectives["HIGH"].availability == 0.9999
    # untouched classes keep defaults
    p99, avail = DEFAULT_OBJECTIVES["BULK"]
    assert tracker.objectives["BULK"].p99_ms == p99
    assert tracker.fast_window == 60.0


def test_tenant_objective_defaults_inherit_configured_normal():
    """A tenant key without its own numbers defaults to NORMAL's
    RESOLVED bounds — including a configured NORMAL override, not the
    stock constant."""
    tracker = SloTracker.from_config(
        ConfigNode({"slo": {"objectives": {
            "NORMAL": {"p99_ms": 10000, "availability": 0.95},
            "vip": {},
        }}}),
        tenant_names=("vip",))
    assert tracker.tenant_objectives["vip"].p99_ms == 10000
    assert tracker.tenant_objectives["vip"].availability == 0.95


def test_from_config_disabled_and_tenant_and_typo():
    assert SloTracker.from_config(
        ConfigNode({"slo": {"enabled": False}})) is None
    tracker = SloTracker.from_config(
        ConfigNode({"slo": {"objectives": {"vip": {"p99_ms": 1500}}}}),
        tenant_names=("vip",))
    assert tracker.tenant_objectives["vip"].p99_ms == 1500
    with pytest.raises(ValueError, match="neither a priority class"):
        SloTracker.from_config(
            ConfigNode({"slo": {"objectives": {"vipp": {}}}}),
            tenant_names=("vip",))


def test_objective_validation():
    with pytest.raises(ValueError):
        Objective("X", 1000.0, 1.0)
    with pytest.raises(ValueError):
        Objective("X", 0.0, 0.99)


# ---------------------------------------------------------------------------
# hop budgets: the guilty hop is NAMED
# ---------------------------------------------------------------------------

def test_hop_budget_green_and_guilty_hop_named():
    measured = {"splice": 1.2, "upload": 6.0}
    baseline = {"hops": {
        "splice": {"budget_s_per_gb": 5.0, "p99_s_per_gb": 1.3},
        "upload": {"budget_s_per_gb": 25.0, "p99_s_per_gb": 6.3},
    }}
    ok, failures = evaluate_hop_budgets(measured, baseline)
    assert ok and not failures
    # artificially tighten ONE hop's budget below its measurement: the
    # guard must fail and the failure must name that hop (the whole
    # point of per-hop budgets vs one aggregate floor)
    baseline["hops"]["upload"]["budget_s_per_gb"] = 1.0
    ok, failures = evaluate_hop_budgets(measured, baseline)
    assert not ok
    assert len(failures) == 1
    assert "'upload'" in failures[0]
    assert "'splice'" not in failures[0]


def test_hop_budget_missing_hop_is_attribution_drift():
    ok, failures = evaluate_hop_budgets(
        {"upload": 6.0},
        {"hops": {"splice": {"budget_s_per_gb": 5.0},
                  "upload": {"budget_s_per_gb": 25.0}}})
    assert not ok
    assert "'splice'" in failures[0] and "missing" in failures[0]


def test_hop_budget_baseline_shape():
    doc = hop_budget_baseline(
        {"splice": [1.0, 1.1, 1.2, 1.3, 2.0]}, headroom=4.0)
    row = doc["hops"]["splice"]
    assert row["p50_s_per_gb"] == pytest.approx(percentile(
        [1.0, 1.1, 1.2, 1.3, 2.0], 50.0), abs=1e-4)
    assert row["budget_s_per_gb"] == pytest.approx(
        row["p99_s_per_gb"] * 4.0, rel=1e-3)
    assert row["samples"] == 5


def test_top_hops_orders_by_seconds_per_gb_and_skips_noise():
    rows = top_hops({
        "upload": {"bytes": 1 << 30, "seconds": 8.0},
        "splice": {"bytes": 1 << 30, "seconds": 1.0},
        "hash": {"bytes": 1 << 30, "seconds": 2.0},
        "filter": {"bytes": 100, "seconds": 50.0},  # < 1 MiB: noise
    })
    assert [r["hop"] for r in rows] == ["upload", "hash", "splice"]


# ---------------------------------------------------------------------------
# the serving surfaces, end to end
# ---------------------------------------------------------------------------

async def _serve(orchestrator):
    app = build_app(orchestrator, orchestrator.metrics)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    return runner, f"http://127.0.0.1:{port}"


async def test_readyz_and_metrics_surface_live_slo(tmp_path):
    """A real orchestrator settles a real job; /readyz carries the slo
    block and /metrics carries the burn/budget gauges with literal
    label sets."""
    import aiohttp

    payload = b"D" * (1 << 20)

    async def serve_media(_request):
        return web.Response(body=payload)

    media_app = web.Application()
    media_app.router.add_get("/m.mkv", serve_media)
    media_runner = web.AppRunner(media_app)
    await media_runner.setup()
    media_site = web.TCPSite(media_runner, "127.0.0.1", 0)
    await media_site.start()
    media_port = media_site._server.sockets[0].getsockname()[1]

    broker = InMemoryBroker()
    telem_mq = MemoryQueue(broker)
    await telem_mq.connect()
    orchestrator = Orchestrator(
        config=ConfigNode({
            "instance": {"download_path": str(tmp_path / "dl"),
                         "max_concurrent_jobs": 1},
            # a deliberately-impossible NORMAL target: the settle must
            # classify as a latency breach and burn budget
            "slo": {"objectives": {"NORMAL": {"p99_ms": 0.001}}},
        }),
        mq=MemoryQueue(broker), store=InMemoryObjectStore(),
        telemetry=Telemetry(telem_mq),
        metrics=prom.new(f"slo{os.urandom(4).hex()}"),
        logger=NullLogger(),
    )
    await orchestrator.start()
    runner = None
    try:
        runner, base = await _serve(orchestrator)
        msg = schemas.Download(media=schemas.Media(
            id="slo-e2e-1", creator_id="c",
            type=schemas.MediaType.Value("MOVIE"),
            source=schemas.SourceType.Value("HTTP"),
            source_uri=f"http://127.0.0.1:{media_port}/m.mkv",
        ))
        broker.publish(schemas.DOWNLOAD_QUEUE, schemas.encode(msg))
        await broker.join(schemas.DOWNLOAD_QUEUE, timeout=30)
        record = orchestrator.registry.get("slo-e2e-1")
        assert record.state == "DONE"
        # the breach rides the job's own timeline
        kinds = [e["kind"] for e in record.recorder.events()]
        assert "slo_breach" in kinds

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{base}/readyz") as resp:
                assert resp.status == 200
                body = await resp.json()
            assert "slo" in body
            normal = body["slo"]["objectives"]["NORMAL"]
            assert normal["burnFast"] > 0
            assert normal["bad"] >= 1
            assert body["slo"]["windows"]["fastS"] > 0
            async with session.get(f"{base}/metrics") as resp:
                text = await resp.text()
        assert 'slo_burn_rate{class="NORMAL",window="fast"}' in text
        assert 'slo_error_budget_remaining{class="NORMAL"}' in text
        # the breached objective's fast burn gauge is live and nonzero
        for line in text.splitlines():
            if ('slo_burn_rate{class="NORMAL",window="fast"}'
                    in line):
                assert float(line.rsplit(" ", 1)[1]) > 0
    finally:
        if runner is not None:
            await runner.cleanup()
        await orchestrator.shutdown(grace_seconds=5)
        await media_runner.cleanup()


async def test_slo_disabled_keeps_surfaces_silent(tmp_path):
    broker = InMemoryBroker()
    telem_mq = MemoryQueue(broker)
    await telem_mq.connect()
    orchestrator = Orchestrator(
        config=ConfigNode({
            "instance": {"download_path": str(tmp_path / "dl")},
            "slo": {"enabled": False},
        }),
        mq=MemoryQueue(broker), store=InMemoryObjectStore(),
        telemetry=Telemetry(telem_mq), logger=NullLogger(),
    )
    await orchestrator.start()
    runner = None
    try:
        assert orchestrator.slo is None
        runner, base = await _serve(orchestrator)
        import aiohttp

        async with aiohttp.ClientSession() as session:
            async with session.get(f"{base}/readyz") as resp:
                body = await resp.json()
        assert "slo" not in body
    finally:
        if runner is not None:
            await runner.cleanup()
        await orchestrator.shutdown(grace_seconds=5)


# ---------------------------------------------------------------------------
# UPSCALE workload class (ISSUE 16: compute is a first-class worker class)
# ---------------------------------------------------------------------------

def test_workload_objective_from_config_defaults_and_overrides():
    from downloader_tpu.control.slo import DEFAULT_WORKLOAD_OBJECTIVES

    tracker = SloTracker.from_config(ConfigNode({"slo": {}}))
    p99, avail = DEFAULT_WORKLOAD_OBJECTIVES["UPSCALE"]
    assert tracker.workload_objectives["UPSCALE"].p99_ms == p99
    assert tracker.workload_objectives["UPSCALE"].availability == avail
    assert "UPSCALE" in tracker.objective_names()

    tuned = SloTracker.from_config(ConfigNode({"slo": {"objectives": {
        "UPSCALE": {"p99_ms": 5000, "availability": 0.9},
    }}}))
    assert tuned.workload_objectives["UPSCALE"].p99_ms == 5000
    assert tuned.workload_objectives["UPSCALE"].availability == 0.9
    # the workload key is NOT a typo'd priority class
    assert "UPSCALE" not in tuned.objectives


def test_workload_objective_tracks_alongside_class():
    """A settle whose record is stamped ``workload = "UPSCALE"`` burns
    the workload budget AND its priority-class budget; an unstamped one
    leaves the workload series untouched."""
    clock = FakeClock()
    tracker = SloTracker(
        {"NORMAL": Objective("NORMAL", 60000.0, 0.999)},
        workload_objectives={"UPSCALE": Objective("UPSCALE", 100.0, 0.99)},
        clock=clock)

    plain = Settled(clock, age_s=0.5)
    tracker.note_settle(plain, "ack", "done")
    upscale_series = tracker._series["UPSCALE"]
    assert upscale_series.good_total == 0
    assert upscale_series.bad_total == 0

    upscaled = Settled(clock, age_s=0.5)  # 500 ms: past the 100 ms target
    upscaled.workload = "UPSCALE"
    tracker.note_settle(upscaled, "ack", "done")
    assert tracker._series["NORMAL"].good_total == 2
    assert upscale_series.bad_total == 1
    assert "UPSCALE" in tracker.snapshot()["objectives"]
    assert tracker.burn_rate("UPSCALE", tracker.fast_window) > 0
