"""Fleet overview aggregation + degradation (ISSUE 15 piece 2).

Layers:

- pure ``build_overview`` folding: totals, worst-of-fleet burn,
  min-of-fleet budget, tenant shares, top hops — and the
  rolling-upgrade contract (a pre-PR-15 heartbeat with no digest is
  listed with ``digest: null``, never an aggregation error);
- the election/publish tick on a real coordination store (elected
  oldest publishes, a younger worker just notes the age, a stale doc
  triggers takeover);
- bounded degradation under PR 14 windowed brownout: the overview
  fetch budget actually bounds a browned-out coordination store, and
  the trace assembler's 5 s/peer budget actually bounds a browned-out
  peer — both come back ``degraded: true`` with the slow party in
  ``errors`` (previously only hard failures were covered);
- ``cli fleet top`` frame rendering;
- the acceptance run: a REAL 3-worker subprocess fleet (SoakRig) with
  one worker under a windowed store brownout — ``GET
  /v1/fleet/overview`` on a healthy worker shows all 3 members, the
  browned-out worker's slow-opened breaker and elevated burn rate, and
  fleet-wide tenant queue shares with ``degraded`` false; killing the
  coordination store degrades to the local-only view with ``degraded:
  true`` and zero job failures.
"""

import asyncio
import time

import pytest
from aiohttp import web

from downloader_tpu.cli import render_overview
from downloader_tpu.control.slo import Objective, SloTracker
from downloader_tpu.fleet.coord import ANY, MemoryCoordStore
from downloader_tpu.fleet.plane import (OVERVIEW_KEY, WORKERS_PREFIX,
                                        FleetPlane, build_overview)

pytestmark = pytest.mark.anyio


def _digest(burn_fast=0.0, burn_slow=0.0, budget=1.0, breakers=None,
            tenants=None, hops=None, hop_s=0.0, stage_s=0.0):
    return {
        "burn": {"NORMAL": {"fast": burn_fast, "slow": burn_slow}},
        "budget": {"NORMAL": budget},
        "breached": [],
        "openBreakers": breakers or {},
        "tenantQueued": tenants or {},
        "hops": hops or {},
        "hopSeconds": hop_s,
        "stageSeconds": stage_s,
    }


def _worker_doc(worker_id, started_at, digest="absent", signals=None):
    doc = {
        "workerId": worker_id,
        "startedAt": started_at,
        "heartbeatAt": time.time(),
        "expiresAt": time.time() + 60,
        "leases": [],
        "stats": {},
    }
    if signals is not None:
        doc["signals"] = signals
    if digest != "absent":
        doc["digest"] = digest
    return doc


# ---------------------------------------------------------------------------
# build_overview folding
# ---------------------------------------------------------------------------

def test_build_overview_folds_totals_and_worst_of_fleet():
    docs = [
        _worker_doc("w0", 1.0,
                    digest=_digest(burn_fast=4.0, burn_slow=2.0,
                                   budget=0.2,
                                   breakers={"store": {
                                       "state": "open",
                                       "reason": "slow"}},
                                   tenants={"vip": 3},
                                   hops={"upload": {
                                       "bytes": 1 << 30,
                                       "seconds": 8.0}},
                                   hop_s=8.0, stage_s=10.0),
                    signals={"queue_depth": 5, "active_jobs": 2}),
        _worker_doc("w1", 2.0,
                    digest=_digest(burn_fast=0.5, burn_slow=0.1,
                                   budget=0.9, tenants={"vip": 1,
                                                        "batch": 4},
                                   hops={"upload": {
                                       "bytes": 1 << 30,
                                       "seconds": 2.0}},
                                   hop_s=2.0, stage_s=10.0),
                    signals={"queue_depth": 3, "active_jobs": 1}),
    ]
    doc = build_overview("w1", docs)
    totals = doc["totals"]
    assert doc["updatedBy"] == "w1"
    assert totals["workers"] == 2
    assert totals["queueDepth"] == 8 and totals["activeJobs"] == 3
    # worst-of-fleet burn, min-of-fleet budget: one sick worker shows
    assert totals["burn"]["NORMAL"] == {"fast": 4.0, "slow": 2.0}
    assert totals["budget"]["NORMAL"] == 0.2
    assert totals["openBreakers"] == {
        "w0": {"store": {"state": "open", "reason": "slow"}}}
    # fleet-wide tenant shares: vip 4/8, batch 4/8
    assert totals["tenantQueued"] == {"vip": 4, "batch": 4}
    assert totals["tenantShares"] == {"vip": 0.5, "batch": 0.5}
    # fleet per-hop rate: 10 s over 2 GiB
    (hop,) = totals["topHops"]
    assert hop["hop"] == "upload"
    assert hop["secondsPerGb"] == pytest.approx(
        10.0 / ((2 << 30) / 1e9), rel=1e-3)
    # the soak's unguarded mixed-phase ratio, surfaced live
    assert totals["hopReconcileRatioMixed"] == pytest.approx(0.5)


def test_build_overview_tolerates_pre_digest_heartbeats():
    """Rolling-upgrade compat: a worker on the pre-PR-15 heartbeat
    shape (no digest, no signals) aggregates as a member with
    ``digest: null`` — never an aggregation error."""
    docs = [
        _worker_doc("old-worker", 1.0),  # pre-PR-15 shape
        _worker_doc("new-worker", 2.0,
                    digest=_digest(burn_fast=1.5, tenants={"vip": 2}),
                    signals={"queue_depth": 2, "active_jobs": 1}),
    ]
    doc = build_overview("new-worker", docs)
    members = {m["workerId"]: m for m in doc["workers"]}
    assert set(members) == {"old-worker", "new-worker"}
    assert members["old-worker"]["digest"] is None
    assert members["old-worker"]["signals"] is None
    # digest-derived totals come from the modern worker alone
    assert doc["totals"]["workers"] == 2
    assert doc["totals"]["burn"]["NORMAL"]["fast"] == 1.5
    assert doc["totals"]["tenantQueued"] == {"vip": 2}
    # a digest of the WRONG TYPE (garbage) is normalized to null too
    docs.append(_worker_doc("weird", 3.0, digest="not-a-dict"))
    doc = build_overview("new-worker", docs)
    members = {m["workerId"]: m for m in doc["workers"]}
    assert members["weird"]["digest"] is None


# ---------------------------------------------------------------------------
# election + publish tick on a real coordination store
# ---------------------------------------------------------------------------

async def test_overview_tick_elected_oldest_publishes_mixed_fleet():
    coord = MemoryCoordStore()
    plane = FleetPlane(coord, "new-worker",
                       digest_fn=lambda: _digest(burn_fast=0.25))
    # an OLD-shape peer heartbeat, younger than this plane (so the
    # plane stays the elected oldest)
    await coord.put(
        WORKERS_PREFIX + "old-worker",
        _worker_doc("old-worker", plane.started_at + 100),
        expect=ANY)
    await plane._beat_once()
    await plane._overview_tick()
    doc = await plane.fetch_overview()
    assert doc is not None and doc["updatedBy"] == "new-worker"
    members = {m["workerId"]: m for m in doc["workers"]}
    assert set(members) == {"old-worker", "new-worker"}
    assert members["old-worker"]["digest"] is None
    assert members["new-worker"]["digest"]["burn"]["NORMAL"]["fast"] \
        == 0.25
    assert plane.overview_age() is not None


async def test_overview_tick_younger_worker_defers_then_takes_over():
    coord = MemoryCoordStore()
    older = FleetPlane(coord, "older", digest_fn=lambda: _digest())
    younger = FleetPlane(coord, "younger", digest_fn=lambda: _digest())
    younger.started_at = older.started_at + 10
    await older._beat_once()
    await younger._beat_once()
    await older._overview_tick()
    # a fresh doc written by the elected older worker: the younger one
    # only notes the age (one GET — no listing, no publish)
    await younger._overview_tick()
    doc = (await coord.get(OVERVIEW_KEY))[0]
    assert doc["updatedBy"] == "older"
    assert younger.overview_age() is not None
    # the aggregator dies: its heartbeat doc vanishes and the overview
    # goes stale — the survivor must take over within its tick
    await coord.delete(WORKERS_PREFIX + "older")
    stale = dict(doc)
    stale["updatedAt"] = time.time() - 120.0
    await coord.put(OVERVIEW_KEY, stale, expect=ANY)
    await younger._overview_tick()
    doc = (await coord.get(OVERVIEW_KEY))[0]
    assert doc["updatedBy"] == "younger"


async def test_overview_tick_stands_down_on_empty_liveness_view():
    """An EMPTY workers() view (own registration failed, or a
    partition/clock issue expired every heartbeat doc) must STAND
    DOWN, not let every worker 'win' the election and overwrite the
    overview with an empty-members doc mid-incident."""
    coord = MemoryCoordStore()
    plane = FleetPlane(coord, "w0", digest_fn=lambda: _digest())
    await plane._beat_once()
    await plane._overview_tick()
    good = (await coord.get(OVERVIEW_KEY))[0]
    assert [m["workerId"] for m in good["workers"]] == ["w0"]
    # every heartbeat doc expires (never beats again; view goes empty)
    entry = await coord.get(WORKERS_PREFIX + "w0")
    dead = dict(entry[0])
    dead["expiresAt"] = time.time() - 60
    await coord.put(WORKERS_PREFIX + "w0", dead, expect=ANY)
    # age the doc so the tick cannot take the fresh-doc early return
    stale = dict(good)
    stale["updatedAt"] = time.time() - 120.0
    await coord.put(OVERVIEW_KEY, stale, expect=ANY)
    await plane._overview_tick()
    doc = (await coord.get(OVERVIEW_KEY))[0]
    # the last GOOD membership view survives (stale but honest — the
    # age gauge surfaces the staleness); no empty-members overwrite
    assert [m["workerId"] for m in doc["workers"]] == ["w0"]
    assert doc["updatedAt"] == stale["updatedAt"]


# ---------------------------------------------------------------------------
# bounded degradation under windowed brownout (PR 14 satellite)
# ---------------------------------------------------------------------------

class BrownedOutCoord(MemoryCoordStore):
    """A coordination store under a latency-only brownout: every read
    succeeds, slowly — the PR 14 failure mode only hard errors covered
    before."""

    def __init__(self, delay: float):
        super().__init__()
        self.delay = delay

    async def get(self, key):
        await asyncio.sleep(self.delay)
        return await super().get(key)


async def test_overview_fetch_budget_bounds_a_browned_out_coord_store():
    plane = FleetPlane(BrownedOutCoord(8.0), "w0")
    started = time.monotonic()
    with pytest.raises(TimeoutError):
        await plane.fetch_overview()
    elapsed = time.monotonic() - started
    # the 5 s budget actually bounds: well under the 8 s brownout
    assert 4.0 <= elapsed < 7.0


async def test_overview_endpoint_degrades_on_brownout_never_5xx():
    import aiohttp

    from downloader_tpu.health import build_app

    class StubOrchestrator:
        config = None
        registry = None
        worker_id = "stub-worker"
        active_jobs: list = []
        consuming = True

        def __init__(self, plane):
            self.fleet = plane

        def autoscale_signals(self):
            return {"queue_depth": 1, "oldest_queued_seconds": 0.0,
                    "cache_headroom_bytes": 1 << 30, "active_jobs": 0}

        def slo_digest(self):
            return _digest(burn_fast=0.1)

    plane = FleetPlane(BrownedOutCoord(8.0), "stub-worker")
    app = build_app(StubOrchestrator(plane), None)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        async with aiohttp.ClientSession() as session:
            started = time.monotonic()
            async with session.get(
                    f"http://127.0.0.1:{port}/v1/fleet/overview"
            ) as resp:
                assert resp.status == 200  # NEVER a 5xx
                body = await resp.json()
        assert time.monotonic() - started < 7.0
        assert body["degraded"] is True
        assert any("coord overview" in err for err in body["errors"])
        # the local view is always served
        assert body["local"]["workerId"] == "stub-worker"
        assert body["local"]["digest"]["burn"]["NORMAL"]["fast"] == 0.1
        assert body["local"]["signals"]["queue_depth"] == 1
        assert body["overview"] is None
    finally:
        await runner.cleanup()


async def test_trace_peer_budget_bounds_a_browned_out_peer(tmp_path):
    """The trace assembler's 5 s/peer budget against a peer that
    ANSWERS, slowly (brownout) — only hard failures were tested
    before.  The response must come back degraded with the slow peer
    named in errors, inside the budget."""
    from downloader_tpu.mq import InMemoryBroker, MemoryQueue
    from downloader_tpu.orchestrator import Orchestrator
    from downloader_tpu.platform.config import ConfigNode
    from downloader_tpu.platform.logging import NullLogger
    from downloader_tpu.platform.telemetry import Telemetry
    from downloader_tpu.store import InMemoryObjectStore

    async def slow_trace(_request):
        await asyncio.sleep(8.0)  # browned out, not down
        return web.json_response({"segments": [], "spans": []})

    peer_app = web.Application()
    peer_app.router.add_get("/v1/trace/{id}", slow_trace)
    peer_runner = web.AppRunner(peer_app)
    await peer_runner.setup()
    peer_site = web.TCPSite(peer_runner, "127.0.0.1", 0)
    await peer_site.start()
    peer_port = peer_site._server.sockets[0].getsockname()[1]

    coord = MemoryCoordStore()
    plane = FleetPlane(coord, "local-worker")
    await coord.put(
        WORKERS_PREFIX + "slow-peer",
        {**_worker_doc("slow-peer", 1.0),
         "adminUrl": f"http://127.0.0.1:{peer_port}"},
        expect=ANY)

    broker = InMemoryBroker()
    telem_mq = MemoryQueue(broker)
    await telem_mq.connect()
    orchestrator = Orchestrator(
        config=ConfigNode({"instance": {
            "download_path": str(tmp_path / "dl")}}),
        mq=MemoryQueue(broker), store=InMemoryObjectStore(),
        telemetry=Telemetry(telem_mq), logger=NullLogger(),
        fleet=plane, worker_id="local-worker",
    )
    await orchestrator.start()
    try:
        record = orchestrator.registry.register("trace-job", "card")
        record.trace_id = "ab" * 16
        started = time.monotonic()
        document = await orchestrator.assemble_trace("ab" * 16)
        elapsed = time.monotonic() - started
        assert elapsed < 7.5, "peer budget did not bound the brownout"
        assert document["degraded"] is True
        assert any("slow-peer" in err for err in document["errors"])
        # the local segment is still served
        assert any(s["jobId"] == "trace-job"
                   for s in document["segments"])
    finally:
        await orchestrator.shutdown(grace_seconds=5)
        await peer_runner.cleanup()


# ---------------------------------------------------------------------------
# orchestrator digest + cli rendering
# ---------------------------------------------------------------------------

def test_slo_digest_carries_breakers_and_tenants():
    """The heartbeat digest fed by a (synthetic) orchestrator shape:
    SloTracker digest + open breakers + tenant depths."""
    tracker = SloTracker({"NORMAL": Objective("NORMAL", 1000.0, 0.99)})
    digest = tracker.digest()
    assert set(digest) >= {"burn", "budget", "hops", "hopSeconds",
                           "stageSeconds", "hopReconcileRatio",
                           "breached"}
    assert digest["hopReconcileRatio"] is None  # nothing settled yet


def test_render_overview_frames():
    body = {
        "workerId": "w1",
        "degraded": False,
        "overviewAgeSeconds": 0.8,
        "errors": [],
        "overview": {
            "updatedBy": "w0",
            "workers": [
                {"workerId": "w0", "heartbeatAt": time.time(),
                 "leases": 1,
                 "signals": {"queue_depth": 4, "active_jobs": 2},
                 "digest": _digest(
                     burn_fast=3.2, burn_slow=1.1,
                     breakers={"store": {"state": "open",
                                         "reason": "slow"}})},
                {"workerId": "w-old", "heartbeatAt": time.time(),
                 "leases": 0, "signals": None, "digest": None},
            ],
            "totals": {
                "tenantShares": {"vip": 0.75, "batch": 0.25},
                "topHops": [{"hop": "upload", "secondsPerGb": 8.1}],
                "hopReconcileRatioMixed": 0.93,
            },
        },
    }
    lines = render_overview(body)
    text = "\n".join(lines)
    assert "aggregated by w0" in text
    assert "store:slow" in text
    assert "NORMAL 3.20/1.10" in text
    assert "(no digest)" in text  # the pre-digest worker is listed
    assert "vip=75%" in text
    assert "upload=8.1" in text
    assert "0.93" in text
    # degraded local-only frame renders from the local view
    degraded = {
        "workerId": "w1", "degraded": True,
        "errors": ["coord overview: boom"], "overview": None,
        "local": {"workerId": "w1",
                  "signals": {"queue_depth": 1, "active_jobs": 0},
                  "digest": _digest()},
    }
    text = "\n".join(render_overview(degraded))
    assert "DEGRADED" in text and "coord overview: boom" in text
    assert "w1" in text


# ---------------------------------------------------------------------------
# acceptance: a real 3-worker fleet, one worker browned out
# ---------------------------------------------------------------------------

async def test_fleet_overview_acceptance_3_worker_brownout(tmp_path):
    """ISSUE 15 acceptance: a REAL 3-worker subprocess fleet (SoakRig)
    with worker 0 under a windowed store brownout and the slow-call
    breaker policy armed.  ``GET /v1/fleet/overview`` on a HEALTHY
    worker must show all 3 members, worker 0's slow-opened breaker and
    elevated burn rate, and fleet-wide tenant queue shares — with
    ``degraded`` false while the coordination store is reachable.
    Killing the coordination store then degrades to the local-only view
    (``degraded: true``, still HTTP 200) — and the run itself finishes
    with zero job failures."""
    import aiohttp

    from test_soak import SoakTestWorld

    from downloader_tpu.soak import SoakProfile

    profile = SoakProfile.smoke(
        jobs=18, workers=3, kills=0, kill_interval=0.0,
        probe_jobs=0, manifest_jobs=0, racing_fraction=0.0,
        hot_fraction=0.4, bulk_fraction=0.3,
        # one job at a time per worker: worker 0 must ACK a few slow
        # jobs (burning error budget against the tightened targets
        # below) BEFORE its slow-call window fills and the breaker
        # sheds the rest to the peers — with higher concurrency the
        # breaker trips before the first settle and every worker-0 job
        # migrates as a nack, which is a redelivery, not a resolution
        max_concurrent_jobs=1,
        # worker 0: latency-only store brownout from (near) boot —
        # workers are ready in <1 s and an 18-job burst drains in a few
        # seconds, so the window must already be open when the traffic
        # lands (zero errors: the slow-call policy must trip, and the
        # tightened SLO targets must visibly burn)
        fault_plan=(
            '[{"seam": "store.*", "kind": "brownout",'
            ' "start_s": 0.3, "window_s": 30.0,'
            ' "latency_ms": 300, "jitter_ms": 100}]'),
        # slow_min_calls sized to ~3-4 jobs' worth of ring-entering
        # store calls (~2 puts per job): the first few browned-out
        # jobs settle (slowly — the burn observation), then the
        # sustained slow fraction opens the breaker (the slow-open
        # observation)
        breakers={"store": {"slow_threshold_ms": 120,
                            "slow_ratio": 0.5, "slow_window": 16,
                            "slow_min_calls": 8, "reset": 4.0}},
        slo={"objectives": {
            "HIGH": {"p99_ms": 800}, "NORMAL": {"p99_ms": 800},
            "BULK": {"p99_ms": 2000}}},
    )
    world = await SoakTestWorld.create(str(tmp_path), profile)
    rig = world.rig
    rig._session = aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=5.0))
    publisher = None
    try:
        for slot in rig.slots:
            await asyncio.to_thread(rig.write_config, slot)
            await rig.spawn(
                slot,
                fault_plan=profile.fault_plan if slot.index == 0
                else "")
        browned = rig.slots[0].worker_id
        healthy = rig.slots[1]
        publisher = asyncio.get_running_loop().create_task(
            rig.publish_all(world.workload.specs))

        observed = {"members3": False, "slow_breaker": False,
                    "burn": False, "tenant_shares": False,
                    "age_gauge": False}
        overview_url = (f"http://127.0.0.1:{healthy.health_port}"
                        "/v1/fleet/overview")
        metrics_url = (f"http://127.0.0.1:{healthy.health_port}"
                       "/metrics")
        deadline = time.monotonic() + 90.0
        while time.monotonic() < deadline:
            pending = [o for o in rig.outcomes.values()
                       if o.resolved_mono is None]
            for start in range(0, len(pending), 16):
                await asyncio.gather(*(
                    rig._check_marker(o)
                    for o in pending[start:start + 16]))
            try:
                async with rig._session.get(overview_url) as resp:
                    assert resp.status == 200, await resp.text()
                    body = await resp.json()
            except (aiohttp.ClientError, OSError, TimeoutError):
                await asyncio.sleep(0.4)
                continue
            # the coordination store is reachable throughout this
            # phase: aggregation must NEVER read degraded
            assert body["degraded"] is False, body["errors"]
            overview = body.get("overview") or {}
            totals = overview.get("totals") or {}
            members = {m.get("workerId"): m
                       for m in overview.get("workers") or []}
            if len(members) == 3:
                observed["members3"] = True
            member = members.get(browned) or {}
            digest = member.get("digest") or {}
            breakers = digest.get("openBreakers") or {}
            store_breaker = breakers.get("store") or {}
            if store_breaker.get("reason") == "slow":
                observed["slow_breaker"] = True
            if any((rates or {}).get("fast", 0.0) > 0.0
                   for rates in (digest.get("burn") or {}).values()):
                observed["burn"] = True
            shares = totals.get("tenantShares") or {}
            if shares and abs(sum(shares.values()) - 1.0) < 0.01:
                observed["tenant_shares"] = True
            if not observed["age_gauge"]:
                try:
                    async with rig._session.get(metrics_url) as resp:
                        text = await resp.text()
                    for line in text.splitlines():
                        if line.startswith(
                                "downloader_fleet_overview_age_seconds"):
                            age = float(line.rsplit(" ", 1)[1])
                            # published + read each heartbeat (the
                            # browned-out aggregator pays +300 ms per
                            # coord op, so this bound is looser than
                            # the steady-state 2x-heartbeat guard
                            # bench v20 holds)
                            if 0.0 <= age <= 8.0:
                                observed["age_gauge"] = True
                except (aiohttp.ClientError, OSError, TimeoutError):
                    pass
            if (all(observed.values())
                    and len(rig.outcomes) >= len(world.workload.specs)
                    and not pending):
                break
            await asyncio.sleep(0.4)
        missing = sorted(k for k, v in observed.items() if not v)
        assert not missing, f"never observed: {missing}"

        # zero job failures: every job resolved, none FAILED/POISONED
        assert len(rig.outcomes) == len(world.workload.specs)
        unresolved = [o.spec.job_id for o in rig.outcomes.values()
                      if o.resolved_mono is None]
        assert not unresolved, unresolved
        bad = [f"{o.spec.job_id}={o.terminal_state}"
               for o in rig.outcomes.values()
               if o.terminal_state in ("FAILED", "DROPPED_POISON")]
        assert not bad, bad

        # -- kill the coordination store ---------------------------------
        await world.s3.stop()
        world.s3 = None  # world.close() must not double-stop it
        async with rig._session.get(overview_url) as resp:
            assert resp.status == 200  # NEVER a 5xx
            body = await resp.json()
        assert body["degraded"] is True
        assert body["errors"], "degraded response must list the error"
        assert body["overview"] is None
        # the local view survives: identity + live signals + digest
        local = body["local"]
        assert local["workerId"] == healthy.worker_id
        assert "signals" in local and "digest" in local
    finally:
        if publisher is not None and not publisher.done():
            publisher.cancel()
            try:
                await publisher
            except asyncio.CancelledError:
                pass
        await rig._session.close()
        rig._session = None
        await world.close()
